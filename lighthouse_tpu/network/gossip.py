"""Gossip pub/sub (the vendored-gossipsub role, lighthouse_network/gossipsub).

Round 4: frames on the wire are REAL gossipsub protobuf RPC envelopes
(network/gossipsub_wire.py — eth2 StrictNoSign messages, snappy-BLOCK
payloads, the spec's SHA256-domain message-id), so the frame a peer
reads off the GOSSIP channel is the byte shape a gossipsub v1.x node
produces. Behavior kept from round 3:
  - fork-digest-scoped topics (types/pubsub.rs:482 style),
  - a per-topic MESH of peers messages are eagerly forwarded to,
  - a seen-cache so each message id propagates once,
  - per-peer delivery accounting feeding peer scoring
    (gossipsub/src/peer_score.rs role).
Mesh membership changes also emit spec GRAFT/PRUNE control frames.
Round 4c adds the v1.2 IDONTWANT flow: large received messages are
announced to the rest of the mesh before the payload forward, and
incoming IDONTWANTs suppress our duplicate forwards for the window.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Optional

from .transport import CHANNEL_GOSSIP, Endpoint

MESH_SIZE = 8        # gossipsub D
MESH_LOW = 6         # D_low: heartbeat grafts below this
MESH_HIGH = 12       # D_high: heartbeat prunes above this
GOSSIP_LAZY = 6      # D_lazy: IHAVE fanout per heartbeat
MCACHE_LEN = 5       # heartbeats of message history kept
MCACHE_GOSSIP = 3    # newest heartbeats advertised in IHAVE
SEEN_CACHE_SIZE = 4096

# peer-score thresholds (gossipsub v1.1 scoring, peer_score.rs role;
# magnitudes follow the reference's beacon defaults' shape)
PRUNE_BACKOFF = 60           # heartbeats before re-grafting a pruner
# gossipsub v1.2 (IDONTWANT): only messages at least this large are
# worth a control round-trip to suppress; cap what one peer may park
IDONTWANT_SIZE_THRESHOLD = 1000
IDONTWANT_MAX_PER_PEER = 1024
GOSSIP_THRESHOLD = -40.0     # below: ignore their gossip + IHAVE
GRAYLIST_THRESHOLD = -80.0   # below: prune everywhere, drop frames

# topic name templates (fork digest scoping like topics in pubsub.rs)
TOPIC_BLOCK = "beacon_block"
TOPIC_AGGREGATE = "beacon_aggregate_and_proof"
TOPIC_ATTESTATION_SUBNET = "beacon_attestation_{subnet}"
TOPIC_VOLUNTARY_EXIT = "voluntary_exit"
TOPIC_PROPOSER_SLASHING = "proposer_slashing"
TOPIC_ATTESTER_SLASHING = "attester_slashing"
TOPIC_SYNC_CONTRIBUTION = "sync_committee_contribution_and_proof"
TOPIC_SYNC_COMMITTEE_SUBNET = "sync_committee_{subnet}"
TOPIC_BLS_TO_EXECUTION_CHANGE = "bls_to_execution_change"
TOPIC_BLOB_SIDECAR = "blob_sidecar_{subnet}"
TOPIC_DATA_COLUMN_SIDECAR = "data_column_sidecar_{subnet}"
TOPIC_LC_FINALITY_UPDATE = "light_client_finality_update"
TOPIC_LC_OPTIMISTIC_UPDATE = "light_client_optimistic_update"


def topic_for(template: str, fork_digest: bytes, subnet: int = None) -> str:
    name = template.format(subnet=subnet) if "{subnet}" in template else template
    return f"/eth2/{fork_digest.hex()}/{name}/ssz_snappy"


from . import gossipsub_wire as W


class GossipRouter:
    """Publish/forward over the mesh with at-most-once handling."""

    def __init__(self, endpoint: Endpoint, on_message: Callable = None):
        self.endpoint = endpoint
        self.on_message = on_message  # (peer_id, topic, data) -> None
        self.subscriptions: set[str] = set()
        self.mesh: dict[str, set] = {}
        self._seen: OrderedDict[bytes, None] = OrderedDict()
        # delivery stats for peer scoring: peer -> (first, duplicate)
        self.delivery_stats: dict[str, list] = {}
        # v1.1 scoring: the full topic-parameterized P1..P7 model
        # (network/peer_score.py; peer_score.rs:937 analog)
        from .peer_score import PeerScore, PeerScoreParams

        self.peer_score = PeerScore(PeerScoreParams())
        # mcache: deque of heartbeat windows, each {mid: (topic, wire)}
        self._mcache: list = [dict() for _ in range(MCACHE_LEN)]
        # IWANT bookkeeping: mid -> heartbeat number requested at (so a
        # peer that never answers does not burn the mid forever)
        self._iwant_sent: dict[bytes, int] = {}
        self._heartbeat_no = 0
        # PRUNE backoff: (topic, peer) -> heartbeat number we may
        # re-graft at (spec: respect the pruner's backoff window)
        self._backoff: dict[tuple, int] = {}
        # gossipsub v1.2 IDONTWANT: peer -> mids the peer told us not
        # to forward it this window; cleared every heartbeat, capped
        # per peer so a peer cannot grow our state without bound
        self._dont_want: dict[str, set] = {}

    # -- membership

    def subscribe(self, topic: str) -> None:
        self.subscriptions.add(topic)
        self.mesh.setdefault(topic, set())
        # register per-topic score params: subnet topics weigh little
        # individually (their union matters), block/aggregate more
        if topic not in self.peer_score.params.topics:
            from .peer_score import beacon_topic_params

            self.peer_score.params.topics[topic] = beacon_topic_params(
                is_subnet="_attestation_" in topic or "subnet" in topic
                or "sync_committee_" in topic
            )

    def unsubscribe(self, topic: str) -> None:
        self.subscriptions.discard(topic)
        self.mesh.pop(topic, None)

    def graft(self, topic: str, peer_id: str) -> None:
        self.mesh.setdefault(topic, set())
        if len(self.mesh[topic]) < MESH_SIZE:
            self.mesh[topic].add(peer_id)
            self.peer_score.graft(peer_id, topic)  # P1 clock starts
            # announce mesh membership with a spec GRAFT control frame
            rpc = W.GossipRpc()
            rpc.control.graft.append(topic)
            self.endpoint.send(peer_id, CHANNEL_GOSSIP, W.encode_rpc(rpc))

    def prune(self, peer_id: str) -> None:
        pruned = [t for t, peers in self.mesh.items() if peer_id in peers]
        for peers in self.mesh.values():
            peers.discard(peer_id)
        for t in pruned:
            self.peer_score.prune(peer_id, t)  # P3b settles here
        self.delivery_stats.pop(peer_id, None)
        if pruned:
            rpc = W.GossipRpc()
            rpc.control.prune = [(t, PRUNE_BACKOFF) for t in pruned]
            self.endpoint.send(peer_id, CHANNEL_GOSSIP, W.encode_rpc(rpc))
            # honor our OWN announced backoff: re-GRAFTing a peer inside
            # the window we told it to wait draws the spec's
            # GRAFT-during-backoff behaviour penalty from real peers
            for t in pruned:
                self._backoff[(t, peer_id)] = (
                    self._heartbeat_no + PRUNE_BACKOFF
                )

    # -- data plane

    def publish(self, topic: str, data: bytes) -> int:
        """Originate a message (data = raw SSZ): snappy-compress into
        the wire form, mark seen, forward to the mesh. The id hashes
        the SSZ we already hold — no decompress round-trip."""
        wire = W.compress_payload(data)
        mid = W.message_id_from_ssz(topic, data)
        self._mark_seen(mid)
        self._mcache[0][mid] = (topic, wire)  # serve IWANTs for our own
        return self._forward(topic, wire, exclude=None, mid=mid)

    def handle_frame(self, sender: str, payload: bytes) -> Optional[tuple]:
        """Inbound gossipsub RPC frame: dedup/forward every published
        message, apply control messages, deliver fresh subscribed
        payloads locally. Returns (sender, topic, ssz_data) for the
        first fresh message on a subscribed topic, else None."""
        if self.score(sender) <= GRAYLIST_THRESHOLD:
            # graylisted: drop unprocessed; continuing to send while
            # graylisted keeps the score pinned down (decay forgives
            # silence, not persistence)
            self.peer_score.add_penalty(sender)
            return None
        try:
            rpc = W.decode_rpc(payload)
        except Exception:
            # ANY malformed remote bytes (bad protobuf, non-UTF8 topic,
            # wrong wire types) score negatively — they must never reach
            # the service poll loop as an exception
            stats = self.delivery_stats.setdefault(sender, [0, 0])
            stats[1] += 1
            self.peer_score.add_penalty(sender, 2)
            return None
        self._handle_gossip_control(sender, rpc)
        for topic in rpc.control.graft:
            # spec posture: GRAFT on a topic we aren't subscribed to
            # (or whose mesh is full) is answered with PRUNE — and
            # never grows state for arbitrary remote strings
            if topic in self.subscriptions and len(
                self.mesh.setdefault(topic, set())
            ) < 2 * MESH_HIGH:  # transient overshoot OK (sanity cap);
                # the heartbeat prunes anything above D_high back to D
                self.mesh[topic].add(sender)
                self.peer_score.graft(sender, topic)
            else:
                # unsolicited GRAFT is a behavioural offence (P7)
                if topic not in self.subscriptions:
                    self.peer_score.add_penalty(sender)
                rej = W.GossipRpc()
                rej.control.prune.append((topic, 0))
                self.endpoint.send(sender, CHANNEL_GOSSIP, W.encode_rpc(rej))
        for topic, backoff in rpc.control.prune:
            # same no-arbitrary-remote-state posture as GRAFT: a PRUNE
            # for a topic we don't subscribe to can't need backoff (we
            # would never graft it) — recording it would let one peer
            # grow _backoff without bound on fabricated topic strings
            if topic not in self.subscriptions:
                continue
            self.mesh.get(topic, set()).discard(sender)
            self.peer_score.prune(sender, topic)
            # honor the pruner's backoff so the heartbeat does not
            # re-graft next second (GRAFT/PRUNE churn with peers not
            # subscribed to the topic would mutually P7 honest nodes)
            until = self._heartbeat_no + min(
                int(backoff) or PRUNE_BACKOFF, 10 * PRUNE_BACKOFF
            )
            self._backoff[(topic, sender)] = until
        delivered = None
        for m in rpc.publish:
            stats = self.delivery_stats.setdefault(sender, [0, 0])
            try:
                ssz = W.decompress_payload(m.data)
                mid = W.message_id_from_ssz(m.topic, ssz)
            except Exception:
                stats[1] += 1  # undecodable payload: dedup junk by id
                if m.topic in self.subscriptions:
                    self.peer_score.reject(sender, m.topic)  # P4
                else:
                    # junk topic strings must not grow per-topic state;
                    # the bounded P7 scalar absorbs the offence
                    self.peer_score.add_penalty(sender, 2)
                try:
                    self._mark_seen(W.message_id(m.topic, m.data))
                except Exception:
                    pass
                continue
            if mid in self._seen:
                stats[1] += 1  # duplicate still feeds the P3 mesh rate
                self.peer_score.deliver_duplicate(sender, m.topic)
                continue
            stats[0] += 1
            self.peer_score.deliver_first(sender, m.topic)  # P2 (+P3)
            self._mark_seen(mid)
            self._mcache[0][mid] = (m.topic, m.data)
            # v1.2: tell the rest of the mesh we hold this message
            # BEFORE forwarding the (large) payload, so they can skip
            # sending us their duplicate copy (threshold on the MESSAGE
            # size, not the snappy wire size)
            if len(ssz) >= IDONTWANT_SIZE_THRESHOLD:
                note = W.GossipRpc()
                note.control.idontwant.append(mid)
                frame = W.encode_rpc(note)
                for peer in self.mesh.get(m.topic, ()):
                    if peer != sender:
                        self.endpoint.send(peer, CHANNEL_GOSSIP, frame)
            self._forward(m.topic, m.data, exclude=sender, mid=mid)
            if m.topic in self.subscriptions:
                if self.on_message is not None:
                    self.on_message(sender, m.topic, ssz)
                if delivered is None:
                    delivered = (sender, m.topic, ssz)
        return delivered

    def _forward(
        self,
        topic: str,
        wire: bytes,
        exclude: Optional[str],
        mid: Optional[bytes] = None,
    ) -> int:
        rpc = W.GossipRpc(
            publish=[W.PublishedMessage(topic=topic, data=wire)]
        )
        frame = W.encode_rpc(rpc)
        n = 0
        for peer in self.mesh.get(topic, ()):
            if peer == exclude:
                continue
            # v1.2: honor the peer's IDONTWANT for this window
            if mid is not None and mid in self._dont_want.get(peer, ()):
                continue
            if self.endpoint.send(peer, CHANNEL_GOSSIP, frame):
                n += 1
        return n

    def _mark_seen(self, mid: bytes) -> None:
        self._seen[mid] = None
        while len(self._seen) > SEEN_CACHE_SIZE:
            self._seen.popitem(last=False)

    # -- v1.1 scoring

    def score(self, peer: str) -> float:
        """The peer's current P1..P7 composite score."""
        return self.peer_score.score(peer)

    # -- lazy gossip (IHAVE/IWANT over the mcache)

    def _handle_gossip_control(self, sender: str, rpc) -> None:
        ctrl = rpc.control
        if ctrl.ihave and self.score(sender) > GOSSIP_THRESHOLD:
            want = []
            for topic, mids in ctrl.ihave:
                if topic not in self.subscriptions:
                    continue
                for mid in mids:
                    if mid not in self._seen and mid not in self._iwant_sent:
                        want.append(mid)
                        if len(want) >= 32:  # match the serving bound
                            break
            if want:
                # mark ONLY what we actually request; entries expire in
                # heartbeat() so an unanswered IWANT can be retried
                # against the next advertiser
                for mid in want:
                    self._iwant_sent[mid] = self._heartbeat_no
                req = W.GossipRpc()
                req.control.iwant.extend(want)
                self.endpoint.send(sender, CHANNEL_GOSSIP, W.encode_rpc(req))
        if ctrl.iwant:
            out = W.GossipRpc()
            for mid in ctrl.iwant[:32]:  # response size bound
                for window in self._mcache:
                    entry = window.get(mid)
                    if entry is not None:
                        out.publish.append(
                            W.PublishedMessage(topic=entry[0], data=entry[1])
                        )
                        break
            if out.publish:
                self.endpoint.send(sender, CHANNEL_GOSSIP, W.encode_rpc(out))
        if ctrl.idontwant:
            dw = self._dont_want.setdefault(sender, set())
            for mid in ctrl.idontwant:
                # eth2 gossip ids are exactly 20 bytes; anything else is
                # junk that would otherwise park frame-sized blobs here
                if len(mid) != 20:
                    self.peer_score.add_penalty(sender)
                    continue
                if len(dw) >= IDONTWANT_MAX_PER_PEER:
                    break
                dw.add(mid)

    # -- heartbeat (mesh maintenance + IHAVE emission, behaviour.rs role)

    def heartbeat(self, candidates: list = None) -> None:
        """One gossipsub heartbeat: shed graylisted and overfull mesh
        peers, graft toward D from `candidates` (connected peers,
        respecting PRUNE backoffs), advertise recent mcache windows via
        IHAVE to a sample of non-mesh peers, then decay scores."""
        import random

        self._heartbeat_no += 1
        hb = self._heartbeat_no
        # expire state: answered-or-not IWANTs retry after 2 beats;
        # elapsed backoffs re-open grafting
        self._iwant_sent = {
            mid: n for mid, n in self._iwant_sent.items() if hb - n <= 2
        }
        self._backoff = {
            k: until for k, until in self._backoff.items() if until > hb
        }
        # IDONTWANT holds for one window: the suppressed duplicate is
        # only in flight around the heartbeat it was announced in
        self._dont_want.clear()
        scores = {
            p: self.score(p)
            for p in set(candidates or [])
            | {p for peers in self.mesh.values() for p in peers}
        }
        candidates = [
            p
            for p in (candidates or [])
            if scores.get(p, 0.0) > GRAYLIST_THRESHOLD
        ]
        for topic in self.subscriptions:
            peers = self.mesh.setdefault(topic, set())
            for peer in [
                p
                for p in peers
                if scores.get(p, 0.0) <= GRAYLIST_THRESHOLD
            ]:
                self.prune(peer)
            if len(peers) < MESH_LOW:
                pool = [
                    p
                    for p in candidates
                    if p not in peers and (topic, p) not in self._backoff
                ]
                random.shuffle(pool)
                for peer in pool[: MESH_SIZE - len(peers)]:
                    self.graft(topic, peer)
            elif len(peers) > MESH_HIGH:
                # shed lowest-scoring members back to D (inbound GRAFTs
                # are accepted up to D_high, so this branch is live)
                by_score = sorted(
                    peers, key=lambda p: scores.get(p, 0.0)
                )
                rpc = W.GossipRpc()
                rpc.control.prune.append((topic, PRUNE_BACKOFF))
                frame = W.encode_rpc(rpc)
                for peer in by_score[: len(peers) - MESH_SIZE]:
                    peers.discard(peer)
                    self.peer_score.prune(peer, topic)
                    self._backoff[(topic, peer)] = (
                        self._heartbeat_no + PRUNE_BACKOFF
                    )
                    self.endpoint.send(peer, CHANNEL_GOSSIP, frame)
            # IHAVE: advertise recent history to non-mesh peers
            mids = [
                mid
                for window in self._mcache[:MCACHE_GOSSIP]
                for mid, (t, _) in window.items()
                if t == topic
            ]
            if mids:
                lazy = [p for p in candidates if p not in peers]
                random.shuffle(lazy)
                rpc = W.GossipRpc()
                rpc.control.ihave.append((topic, mids[:64]))
                frame = W.encode_rpc(rpc)
                for peer in lazy[:GOSSIP_LAZY]:
                    self.endpoint.send(peer, CHANNEL_GOSSIP, frame)
        # decay LAST: shedding above used the scores peers earned;
        # decay forgives between heartbeats
        self.peer_score.refresh()
        # rotate the mcache window
        self._mcache.pop()
        self._mcache.insert(0, {})
