"""yamux 1.0 stream multiplexer — sans-IO session core.

The reference multiplexes every connection with yamux over noise
(lighthouse_network service/utils.rs:52-63 builds
`yamux::Config::default()` into the transport; each gossipsub mesh
link and each req/resp request is a yamux substream). This module
implements the yamux spec (hashicorp/yamux spec.md, the wire protocol
rust-yamux speaks) as a sans-IO state machine so it can run over TCP,
noise transport messages, or an in-memory pipe in tests.

Frame header — 12 bytes, all multi-byte fields BIG-endian:

    u8  version   (0)
    u8  type      0 Data | 1 WindowUpdate | 2 Ping | 3 GoAway
    u16 flags     1 SYN | 2 ACK | 4 FIN | 8 RST
    u32 stream_id (odd = client-opened, even = server-opened)
    u32 length    Data: payload bytes following; WindowUpdate: delta;
                  Ping: opaque value; GoAway: error code

Flow control: each direction of a stream starts with a 256 KiB receive
window; Data consumes it, WindowUpdate replenishes. This session
auto-replenishes (queues a WindowUpdate once half the window is
consumed) because delivered events hand the bytes straight to the
application. Writes past the peer's window are buffered per-stream and
flushed as updates arrive.

Usage:
    s = YamuxSession(is_client=True)
    sid = s.open_stream()
    s.send(sid, b"hello")            # queues frames
    wire_bytes = s.data_to_send()     # -> socket/noise
    events = s.receive(peer_bytes)    # [(kind, sid, payload), ...]
"""

from __future__ import annotations

import struct
from collections import deque
from typing import List, Optional, Tuple

TYPE_DATA = 0x0
TYPE_WINDOW_UPDATE = 0x1
TYPE_PING = 0x2
TYPE_GO_AWAY = 0x3

FLAG_SYN = 0x1
FLAG_ACK = 0x2
FLAG_FIN = 0x4
FLAG_RST = 0x8

INITIAL_WINDOW = 256 * 1024
_MAX_FRAME_DATA = 1 << 20  # sanity cap on one Data frame

GOAWAY_NORMAL = 0x0
GOAWAY_PROTO_ERROR = 0x1
GOAWAY_INTERNAL_ERROR = 0x2

# receive() event kinds
EV_STREAM_OPENED = "stream_opened"   # remote SYN
EV_DATA = "data"                     # payload bytes
EV_STREAM_CLOSED = "stream_closed"   # remote FIN (half-close)
EV_STREAM_RESET = "stream_reset"     # remote RST
EV_PING = "ping"                     # remote SYN ping (ACK auto-queued)
EV_GO_AWAY = "go_away"               # session teardown, payload = code


class YamuxError(Exception):
    pass


def encode_frame(
    typ: int, flags: int, stream_id: int, length: int, payload: bytes = b""
) -> bytes:
    return struct.pack(">BBHII", 0, typ, flags, stream_id, length) + payload


class _Stream:
    __slots__ = (
        "sid", "send_window", "recv_consumed", "pending",
        "local_closed", "remote_closed", "acked", "fin_pending",
    )

    def __init__(self, sid: int):
        self.sid = sid
        self.send_window = INITIAL_WINDOW
        self.recv_consumed = 0          # since last WindowUpdate we sent
        self.pending = deque()          # buffered writes past peer window
        self.local_closed = False       # we sent FIN
        self.remote_closed = False      # peer sent FIN
        self.acked = False              # peer ACKed our SYN
        self.fin_pending = False        # FIN deferred behind buffered data


class YamuxSession:
    """One yamux session (one underlying connection)."""

    def __init__(self, is_client: bool):
        self.is_client = is_client
        self._next_sid = 1 if is_client else 2
        self._streams: dict[int, _Stream] = {}
        self._out = bytearray()
        self._in = bytearray()
        self._goaway_sent = False
        self._goaway_recv: Optional[int] = None

    # ----------------------------------------------------------- opening

    def open_stream(self) -> int:
        """Allocate a stream and queue its SYN (empty window update)."""
        sid = self._next_sid
        self._next_sid += 2
        self._streams[sid] = _Stream(sid)
        self._out += encode_frame(TYPE_WINDOW_UPDATE, FLAG_SYN, sid, 0)
        return sid

    # ----------------------------------------------------------- sending

    def send(self, sid: int, data: bytes) -> None:
        st = self._require(sid)
        if st.local_closed or st.fin_pending:
            raise YamuxError(f"stream {sid} closed for sending")
        if st.pending:
            # earlier bytes are still queued behind the peer's window;
            # emitting now would reorder the stream
            st.pending.append(bytes(data))
            return
        self._emit_data(st, data)

    def _emit_data(self, st: _Stream, data: bytes) -> None:
        view = memoryview(bytes(data))
        while view:
            if st.send_window == 0:
                # remainder goes FIRST in the queue: it precedes any
                # chunk queued after it
                st.pending.appendleft(bytes(view))
                return
            n = min(len(view), st.send_window, _MAX_FRAME_DATA)
            st.send_window -= n
            self._out += encode_frame(
                TYPE_DATA, 0, st.sid, n, bytes(view[:n])
            )
            view = view[n:]

    def _drain_pending(self, st: _Stream) -> None:
        while st.pending and st.send_window:
            self._emit_data(st, st.pending.popleft())
        if st.fin_pending and not st.pending:
            st.fin_pending = False
            self._finish_close(st)

    def close_stream(self, sid: int) -> None:
        """Half-close: FIN. Peer may keep sending until its own FIN.
        If writes are still buffered behind the peer's window, the FIN
        is deferred until they flush (a FIN ahead of buffered data
        would truncate the transfer)."""
        st = self._streams.get(sid)
        if st is None or st.local_closed or st.fin_pending:
            return
        if st.pending:
            st.fin_pending = True
            return
        self._finish_close(st)

    def _finish_close(self, st: _Stream) -> None:
        st.local_closed = True
        self._out += encode_frame(TYPE_DATA, FLAG_FIN, st.sid, 0)
        self._gc(st)

    def reset_stream(self, sid: int) -> None:
        st = self._streams.pop(sid, None)
        if st is not None:
            self._out += encode_frame(TYPE_WINDOW_UPDATE, FLAG_RST, sid, 0)

    def ping(self, value: int = 0) -> None:
        self._out += encode_frame(TYPE_PING, FLAG_SYN, 0, value)

    def go_away(self, code: int = GOAWAY_NORMAL) -> None:
        if not self._goaway_sent:
            self._goaway_sent = True
            self._out += encode_frame(TYPE_GO_AWAY, 0, 0, code)

    def data_to_send(self) -> bytes:
        out = bytes(self._out)
        del self._out[:]
        return out

    # ---------------------------------------------------------- receiving

    def receive(self, data: bytes) -> List[Tuple[str, int, bytes]]:
        """Feed wire bytes; returns ordered events (kind, sid, payload)."""
        self._in += data
        events: List[Tuple[str, int, bytes]] = []
        while True:
            if len(self._in) < 12:
                return events
            ver, typ, flags, sid, length = struct.unpack(
                ">BBHII", bytes(self._in[:12])
            )
            if ver != 0:
                raise YamuxError(f"bad yamux version {ver}")
            body = b""
            if typ == TYPE_DATA:
                if length > _MAX_FRAME_DATA:
                    raise YamuxError(f"oversized data frame {length}")
                if len(self._in) - 12 < length:
                    return events
                body = bytes(self._in[12 : 12 + length])
                del self._in[: 12 + length]
            else:
                del self._in[:12]
            self._handle(typ, flags, sid, length, body, events)

    def _handle(self, typ, flags, sid, length, body, events) -> None:
        if typ == TYPE_PING:
            if flags & FLAG_SYN:
                self._out += encode_frame(TYPE_PING, FLAG_ACK, 0, length)
                events.append((EV_PING, 0, struct.pack(">I", length)))
            return
        if typ == TYPE_GO_AWAY:
            self._goaway_recv = length
            events.append((EV_GO_AWAY, 0, struct.pack(">I", length)))
            return
        if typ not in (TYPE_DATA, TYPE_WINDOW_UPDATE):
            raise YamuxError(f"unknown frame type {typ}")

        st = self._streams.get(sid)
        if flags & FLAG_SYN:
            if st is not None:
                raise YamuxError(f"SYN on existing stream {sid}")
            if self._inbound_sid_invalid(sid):
                self._out += encode_frame(
                    TYPE_WINDOW_UPDATE, FLAG_RST, sid, 0
                )
                return
            st = _Stream(sid)
            st.acked = True
            self._streams[sid] = st
            self._out += encode_frame(TYPE_WINDOW_UPDATE, FLAG_ACK, sid, 0)
            events.append((EV_STREAM_OPENED, sid, b""))
        if st is None:
            # frames on unknown/reset streams are dropped (late data
            # after our RST is legal peer behavior)
            return
        if flags & FLAG_ACK:
            st.acked = True
        if flags & FLAG_RST:
            self._streams.pop(sid, None)
            events.append((EV_STREAM_RESET, sid, b""))
            return

        if typ == TYPE_WINDOW_UPDATE:
            st.send_window += length
            self._drain_pending(st)
        elif body:
            st.recv_consumed += len(body)
            if st.recv_consumed >= INITIAL_WINDOW // 2:
                self._out += encode_frame(
                    TYPE_WINDOW_UPDATE, 0, sid, st.recv_consumed
                )
                st.recv_consumed = 0
            events.append((EV_DATA, sid, body))

        if flags & FLAG_FIN:
            st.remote_closed = True
            events.append((EV_STREAM_CLOSED, sid, b""))
            self._gc(st)

    def _inbound_sid_invalid(self, sid: int) -> bool:
        # peers open odd ids when they are the client, even otherwise;
        # an inbound SYN must come from the peer's id space
        peer_is_client = not self.is_client
        return sid % 2 != (1 if peer_is_client else 0) or sid == 0

    def _gc(self, st: _Stream) -> None:
        if st.local_closed and st.remote_closed:
            self._streams.pop(st.sid, None)

    # ------------------------------------------------------------- misc

    def _require(self, sid: int) -> _Stream:
        st = self._streams.get(sid)
        if st is None:
            raise YamuxError(f"unknown stream {sid}")
        return st

    def stream_ids(self) -> list:
        return sorted(self._streams)
