"""NetworkBeaconProcessor: the bridge from network events to chain work
(network/src/network_beacon_processor/mod.rs:88-131 + gossip_methods.rs,
rpc_methods.rs analog).

Inbound gossip becomes `Work` for the beacon_processor — attestations
carry BOTH process_individual and process_batch closures so the
scheduler can form TPU-scale batches with the per-item fallback
(mod.rs:88-131; batch path gossip_methods.rs:230-241). RPC server
handlers serve blocks/blobs out of the chain's store (rpc_methods.rs).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..common import metrics
from ..consensus import types as T
from ..node.beacon_chain import AttestationError, AvailabilityPending, BlockError
from ..node.beacon_processor import Work, WorkType
from .gossip import (
    TOPIC_AGGREGATE,
    TOPIC_ATTESTATION_SUBNET,
    TOPIC_BLOB_SIDECAR,
    TOPIC_BLOCK,
    topic_for,
)
from .peer_manager import PeerAction
from .rpc import (
    BlocksByRangeRequest,
    Protocol,
    ResponseCode,
    Status,
)

# gossip ingest — the FIRST stage of the slot timeline. Labeled by
# message kind so queue-wait/drop series downstream can be correlated
# with what actually arrived on the wire.
GOSSIP_RX = metrics.counter(
    "network_gossip_messages_total",
    "Gossip messages received, by kind",
    labelnames=("kind",),
)
GOSSIP_DECODE_FAIL = metrics.counter(
    "network_gossip_decode_failures_total",
    "Gossip messages that failed SSZ decoding, by kind",
    labelnames=("kind",),
)


class NetworkBeaconProcessor:
    def __init__(self, chain, processor, service, fork_digest: bytes = b"\x00" * 4):
        self.chain = chain
        self.processor = processor
        self.service = service
        self.fork_digest = fork_digest
        self._register_rpc()
        # gossip verification stats for tests/metrics
        self.imported_blocks = 0
        self.verified_attestations = 0
        self.on_unknown_parent: Optional[Callable] = None  # sync hook
        # blocks parked on data availability: root -> signed block
        # (bounded; honest Deneb ordering is block-before-blobs)
        self._awaiting_blobs: dict[bytes, object] = {}
        self._AWAITING_CAP = 64

    # ------------------------------------------------------------ gossip in

    def handle_gossip(self, peer_id: str, topic: str, data: bytes) -> None:
        """Router dispatch (router.rs:34 handle_gossip)."""
        if f"/{TOPIC_BLOCK}/" in topic:
            self._on_gossip_block(peer_id, data)
        elif "/beacon_attestation_" in topic:
            self._on_gossip_attestation(peer_id, data)
        elif f"/{TOPIC_AGGREGATE}/" in topic:
            self._on_gossip_aggregate(peer_id, data)
        elif "/blob_sidecar_" in topic:
            self._on_gossip_blob(peer_id, data)

    def _on_gossip_block(self, peer_id: str, data: bytes) -> None:
        GOSSIP_RX.labels(kind="block").inc()
        try:
            from .sync import decode_block_response

            signed = decode_block_response(self.chain.spec, data)
        except Exception:
            GOSSIP_DECODE_FAIL.labels(kind="block").inc()
            self.service.report_peer(peer_id, PeerAction.LOW_TOLERANCE)
            return

        def process(_payload) -> None:
            try:
                self.chain.process_block(signed)
                self.imported_blocks += 1
            except AvailabilityPending:
                # honest Deneb ordering (block before trailing blobs):
                # first try completing DA from the EL's pool
                # (fetch_blobs.rs — usually beats gossip), else park,
                # NO penalty; retried when the sidecars land
                from ..node.fetch_blobs import fetch_blobs_and_import

                if fetch_blobs_and_import(self.chain, signed):
                    try:
                        self.chain.process_block(signed)
                        self.imported_blocks += 1
                        return
                    except AvailabilityPending:
                        pass  # EL had only part of the set
                if len(self._awaiting_blobs) < self._AWAITING_CAP:
                    self._awaiting_blobs[
                        signed.message.hash_tree_root()
                    ] = signed
            except BlockError as e:
                if "unknown parent" in str(e) and self.on_unknown_parent:
                    # park the child with the lookup; it re-enters the
                    # queue once the ancestor chain lands (the
                    # reprocessing-queue role for orphans)
                    self.on_unknown_parent(
                        peer_id, bytes(signed.message.parent_root), signed
                    )
                else:
                    self.service.report_peer(peer_id, PeerAction.MID_TOLERANCE)

        self.processor.submit(
            Work(
                kind=WorkType.GOSSIP_BLOCK,
                process_individual=process,
                slot=int(signed.message.slot),
            )
        )

    def _on_gossip_attestation(self, peer_id: str, data: bytes) -> None:
        GOSSIP_RX.labels(kind="attestation").inc()
        try:
            att = T.Attestation.deserialize(data)
        except Exception:
            GOSSIP_DECODE_FAIL.labels(kind="attestation").inc()
            self.service.report_peer(peer_id, PeerAction.LOW_TOLERANCE)
            return

        def individual(payload) -> None:
            try:
                v = self.chain.verify_attestation_for_gossip(payload)
            except AttestationError:
                self.service.report_peer(peer_id, PeerAction.HIGH_TOLERANCE)
                return
            good = self.chain.batch_verify_attestations([v])
            self.verified_attestations += len(good)

        def batch(payloads: list) -> bool:
            verified = []
            for p in payloads:
                try:
                    verified.append(self.chain.verify_attestation_for_gossip(p))
                except AttestationError:
                    continue
            # ONE crypto batch; poisoning fallback happens inside
            good = self.chain.batch_verify_attestations(verified)
            self.verified_attestations += len(good)
            return True

        self.processor.submit(
            Work(
                kind=WorkType.GOSSIP_ATTESTATION,
                process_individual=individual,
                process_batch=batch,
                payload=att,
                slot=int(att.data.slot),
                # slot-relative deadline (ISSUE 8): an unaggregated
                # attestation is only profitable within roughly its own
                # slot window — work served later counts as a deadline
                # miss even when it isn't shed
                deadline=time.perf_counter()
                + self.chain.spec.seconds_per_slot,
            )
        )

    def _on_gossip_aggregate(self, peer_id: str, data: bytes) -> None:
        """Aggregate-and-proof gossip → the AGGREGATE priority lane
        (class 1): one shed aggregate loses ~hundreds of attestations,
        so the scheduler serves these before any unaggregated work."""
        GOSSIP_RX.labels(kind="aggregate").inc()
        try:
            signed = T.SignedAggregateAndProof.deserialize(data)
        except Exception:
            GOSSIP_DECODE_FAIL.labels(kind="aggregate").inc()
            self.service.report_peer(peer_id, PeerAction.LOW_TOLERANCE)
            return

        def individual(payload) -> None:
            try:
                self.chain.verify_aggregate_for_gossip(payload)
            except AttestationError:
                # duplicate aggregators / overlapping bits are the
                # common benign case on a fanout mesh — no penalty
                return
            self.verified_attestations += 1

        def batch(payloads: list) -> bool:
            for p in payloads:
                individual(p)
            return True

        self.processor.submit(
            Work(
                kind=WorkType.GOSSIP_AGGREGATE,
                process_individual=individual,
                process_batch=batch,
                payload=signed,
                slot=int(signed.message.aggregate.data.slot),
                # aggregates stay profitable through the next proposal
                # opportunity (~2 slots), unlike single attestations
                deadline=time.perf_counter()
                + 2 * self.chain.spec.seconds_per_slot,
            )
        )

    def _on_gossip_blob(self, peer_id: str, data: bytes) -> None:
        GOSSIP_RX.labels(kind="blob_sidecar").inc()
        try:
            sidecar = T.BlobSidecar.deserialize(data)
        except Exception:
            GOSSIP_DECODE_FAIL.labels(kind="blob_sidecar").inc()
            self.service.report_peer(peer_id, PeerAction.LOW_TOLERANCE)
            return

        def process(_payload) -> None:
            try:
                ready = self.chain.receive_blob_sidecars([sidecar])
            except Exception:
                self.service.report_peer(peer_id, PeerAction.MID_TOLERANCE)
                return
            # sidecar completed a parked block's blob set: import it now
            for root in ready:
                parked = self._awaiting_blobs.pop(root, None)
                if parked is not None:
                    try:
                        self.chain.process_block(parked)
                        self.imported_blocks += 1
                    except BlockError:
                        self.service.report_peer(
                            peer_id, PeerAction.MID_TOLERANCE
                        )

        self.processor.submit(
            Work(
                kind=WorkType.GOSSIP_BLOCK,
                process_individual=process,
                slot=int(sidecar.signed_block_header.message.slot),
            )
        )

    # ------------------------------------------------------------ gossip out

    def publish_block(self, signed_block) -> None:
        topic = topic_for(TOPIC_BLOCK, self.fork_digest)
        self.service.publish(topic, T.SignedBeaconBlock.serialize(signed_block))

    def publish_attestation(self, attestation, subnet: int = 0) -> None:
        topic = topic_for(TOPIC_ATTESTATION_SUBNET, self.fork_digest, subnet)
        self.service.publish(topic, T.Attestation.serialize(attestation))

    def publish_aggregate(self, signed_aggregate) -> None:
        topic = topic_for(TOPIC_AGGREGATE, self.fork_digest)
        self.service.publish(
            topic, T.SignedAggregateAndProof.serialize(signed_aggregate)
        )

    def publish_blob_sidecar(self, sidecar) -> None:
        topic = topic_for(
            TOPIC_BLOB_SIDECAR, self.fork_digest, int(sidecar.index)
        )
        self.service.publish(topic, T.BlobSidecar.serialize(sidecar))

    # ------------------------------------------------------------ rpc server

    def _register_rpc(self) -> None:
        self.service.rpc.register(Protocol.STATUS, self._serve_status)
        self.service.rpc.register(
            Protocol.BLOCKS_BY_RANGE, self._serve_blocks_by_range
        )
        self.service.rpc.register(
            Protocol.BLOCKS_BY_ROOT, self._serve_blocks_by_root
        )
        self.service.rpc.register(
            Protocol.BLOBS_BY_ROOT, self._serve_blobs_by_root
        )
        self.service.rpc.register(
            Protocol.LIGHT_CLIENT_BOOTSTRAP, self._serve_lc_bootstrap
        )
        self.service.rpc.register(
            Protocol.LIGHT_CLIENT_OPTIMISTIC_UPDATE,
            self._serve_lc_optimistic,
        )
        self.service.rpc.register(
            Protocol.LIGHT_CLIENT_FINALITY_UPDATE, self._serve_lc_finality
        )
        self.service.rpc.register(
            Protocol.LIGHT_CLIENT_UPDATES_BY_RANGE,
            self._serve_lc_updates_by_range,
        )
        self.service.rpc.register(
            Protocol.DATA_COLUMNS_BY_ROOT, self._serve_columns_by_root
        )
        self.service.rpc.register(
            Protocol.DATA_COLUMNS_BY_RANGE, self._serve_columns_by_range
        )

    def local_status(self):
        fin_epoch, fin_root = self.chain.fork_choice.finalized_checkpoint
        return Status.make(
            fork_digest=self.fork_digest,
            finalized_root=fin_root,
            finalized_epoch=fin_epoch,
            head_root=self.chain.head.root,
            head_slot=self.chain.head.slot,
        )

    def _serve_status(self, peer_id: str, body: bytes):
        return ResponseCode.SUCCESS, [Status.serialize(self.local_status())]

    def _serve_blocks_by_range(self, peer_id: str, body: bytes):
        req = BlocksByRangeRequest.deserialize(body)
        count = min(int(req.count), 1024)
        chunks = []
        for slot in range(req.start_slot, req.start_slot + count):
            root = self.chain.block_root_at_slot(slot)
            if root is None:
                continue  # skipped slot
            block = self.chain.store.get_block(root)
            if block is not None:
                chunks.append(T.SignedBeaconBlock.serialize(block))
        return ResponseCode.SUCCESS, chunks

    def _serve_blocks_by_root(self, peer_id: str, body: bytes):
        roots = [body[i : i + 32] for i in range(0, len(body), 32)]
        chunks = []
        for root in roots[:128]:
            block = self.chain.store.get_block(root)
            if block is not None:
                chunks.append(T.SignedBeaconBlock.serialize(block))
        return ResponseCode.SUCCESS, chunks

    def _serve_blobs_by_root(self, peer_id: str, body: bytes):
        roots = [body[i : i + 32] for i in range(0, len(body), 32)]
        chunks = []
        for root in roots[:128]:
            for sc in self.chain.store.get_blobs(root):
                chunks.append(T.BlobSidecar.serialize(sc))
        return ResponseCode.SUCCESS, chunks

    # ------------------------------------------------- peerdas rpc

    def _serve_columns_by_root(self, peer_id: str, body: bytes):
        """Body: concatenated DataColumnIdentifier (40 bytes each);
        serves only custodied columns (rpc_methods.rs columns path)."""
        from ..consensus import data_column as dc

        # group identifiers by root: ONE store read + deserialize per
        # distinct block even when all 128 columns of it are asked for
        by_root: dict = {}
        for i in range(0, min(len(body), 40 * 128), 40):
            ident = dc.DataColumnIdentifier.deserialize(body[i : i + 40])
            by_root.setdefault(bytes(ident.block_root), set()).add(
                int(ident.index)
            )
        chunks = []
        for root, want in by_root.items():
            for sc in self.chain.store.get_columns(root):
                if int(sc.index) in want:
                    chunks.append(dc.DataColumnSidecar.serialize(sc))
        return ResponseCode.SUCCESS, chunks

    def _serve_columns_by_range(self, peer_id: str, body: bytes):
        from ..consensus import data_column as dc

        req = dc.DataColumnsByRangeRequest.deserialize(body)
        want = {int(c) for c in req.columns}
        chunks = []
        for slot in range(req.start_slot, req.start_slot + min(int(req.count), 1024)):
            root = self.chain.block_root_at_slot(slot)
            if root is None:
                continue
            for sc in self.chain.store.get_columns(root):
                if int(sc.index) in want:
                    chunks.append(dc.DataColumnSidecar.serialize(sc))
        return ResponseCode.SUCCESS, chunks

    # ------------------------------------------------- light-client rpc

    def _serve_lc_bootstrap(self, peer_id: str, body: bytes):
        from ..consensus import light_client as lc

        cache = self.chain.light_client_cache
        if cache is None:
            return ResponseCode.RESOURCE_UNAVAILABLE, []
        bootstrap = cache.get_bootstrap(body[:32])
        if bootstrap is None:
            return ResponseCode.RESOURCE_UNAVAILABLE, []
        return ResponseCode.SUCCESS, [
            lc.LightClientBootstrap.serialize(bootstrap)
        ]

    def _serve_lc_optimistic(self, peer_id: str, body: bytes):
        from ..consensus import light_client as lc

        cache = self.chain.light_client_cache
        if cache is None or cache.latest_optimistic_update is None:
            return ResponseCode.RESOURCE_UNAVAILABLE, []
        return ResponseCode.SUCCESS, [
            lc.LightClientOptimisticUpdate.serialize(
                cache.latest_optimistic_update
            )
        ]

    def _serve_lc_finality(self, peer_id: str, body: bytes):
        from ..consensus import light_client as lc

        cache = self.chain.light_client_cache
        if cache is None or cache.latest_finality_update is None:
            return ResponseCode.RESOURCE_UNAVAILABLE, []
        return ResponseCode.SUCCESS, [
            lc.LightClientFinalityUpdate.serialize(
                cache.latest_finality_update
            )
        ]

    def _serve_lc_updates_by_range(self, peer_id: str, body: bytes):
        from ..consensus import light_client as lc

        cache = self.chain.light_client_cache
        if cache is None:
            return ResponseCode.RESOURCE_UNAVAILABLE, []
        req = lc.LightClientUpdatesByRangeRequest.deserialize(body)
        updates = cache.get_updates(
            int(req.start_period), min(int(req.count), 128)
        )
        return ResponseCode.SUCCESS, [
            lc.LightClientUpdate.serialize(u) for u in updates
        ]
