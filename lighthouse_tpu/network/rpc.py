"""Req/resp RPC (lighthouse_network rpc/protocol.rs:294-334 analog).

Protocols carried: Status, Goodbye, Ping, MetaData, BlocksByRange,
BlocksByRoot, BlobsByRange, BlobsByRoot — the sync-critical subset of
the reference's 14 (light-client and PeerDAS column protocols slot into
the same enum when those subsystems land).

Framing over the transport's RPC channel:
  request : <req_id u32><proto u8><is_resp=0><ssz payload>
  response: <req_id u32><proto u8><is_resp=1><code u8><n u16><len-prefixed chunks>

Responses are chunk lists (a BlocksByRange response is a chunk per
block, like the reference's streamed chunks, rpc/codec.rs). An inbound
token-bucket rate limiter guards each protocol (rpc/rate_limiter.rs:531
role).
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass
from enum import IntEnum
from typing import Callable, Optional

from ..consensus.ssz import Container, uint64, Bytes4, Bytes32
from .transport import CHANNEL_RPC, Endpoint


class Protocol(IntEnum):
    STATUS = 0
    GOODBYE = 1
    PING = 2
    METADATA = 3
    BLOCKS_BY_RANGE = 4
    BLOCKS_BY_ROOT = 5
    BLOBS_BY_RANGE = 6
    BLOBS_BY_ROOT = 7
    # light-client server protocols (rpc/protocol.rs LightClient*)
    LIGHT_CLIENT_BOOTSTRAP = 8
    LIGHT_CLIENT_OPTIMISTIC_UPDATE = 9
    LIGHT_CLIENT_FINALITY_UPDATE = 10
    LIGHT_CLIENT_UPDATES_BY_RANGE = 11
    # PeerDAS column protocols (rpc/protocol.rs DataColumnsBy{Root,Range})
    DATA_COLUMNS_BY_ROOT = 12
    DATA_COLUMNS_BY_RANGE = 13
    # ENR-record discovery (discv5 FINDNODE role; boot_node serves it)
    DISCOVERY = 14


class ResponseCode(IntEnum):
    SUCCESS = 0
    INVALID_REQUEST = 1
    SERVER_ERROR = 2
    RESOURCE_UNAVAILABLE = 3
    RATE_LIMITED = 4


Status = Container(
    "Status",
    [
        ("fork_digest", Bytes4),
        ("finalized_root", Bytes32),
        ("finalized_epoch", uint64),
        ("head_root", Bytes32),
        ("head_slot", uint64),
    ],
)

BlocksByRangeRequest = Container(
    "BlocksByRangeRequest",
    [("start_slot", uint64), ("count", uint64), ("step", uint64)],
)

Ping = Container("Ping", [("seq_number", uint64)])

MetaData = Container(
    "MetaData", [("seq_number", uint64), ("attnets", uint64)]
)


@dataclass
class _Bucket:
    tokens: float
    last: float


class RateLimiter:
    """Per-(peer, protocol) token bucket (rpc/rate_limiter.rs role)."""

    # protocol -> (capacity, refill per second)
    LIMITS = {
        Protocol.STATUS: (8, 4.0),
        Protocol.GOODBYE: (2, 1.0),
        Protocol.PING: (8, 4.0),
        Protocol.METADATA: (4, 2.0),
        Protocol.BLOCKS_BY_RANGE: (512, 128.0),
        Protocol.BLOCKS_BY_ROOT: (256, 128.0),
        Protocol.BLOBS_BY_RANGE: (512, 128.0),
        Protocol.BLOBS_BY_ROOT: (256, 128.0),
        Protocol.LIGHT_CLIENT_BOOTSTRAP: (4, 1.0),
        Protocol.LIGHT_CLIENT_OPTIMISTIC_UPDATE: (8, 2.0),
        Protocol.LIGHT_CLIENT_FINALITY_UPDATE: (8, 2.0),
        Protocol.LIGHT_CLIENT_UPDATES_BY_RANGE: (16, 4.0),
        Protocol.DATA_COLUMNS_BY_ROOT: (256, 128.0),
        Protocol.DATA_COLUMNS_BY_RANGE: (512, 128.0),
        Protocol.DISCOVERY: (16, 4.0),
    }

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._buckets: dict[tuple, _Bucket] = {}

    def allow(self, peer_id: str, proto: Protocol, cost: int = 1) -> bool:
        cap, rate = self.LIMITS[proto]
        key = (peer_id, proto)
        now = self._clock()
        b = self._buckets.get(key)
        if b is None:
            b = self._buckets[key] = _Bucket(tokens=float(cap), last=now)
        b.tokens = min(cap, b.tokens + (now - b.last) * rate)
        b.last = now
        if b.tokens >= cost:
            b.tokens -= cost
            return True
        return False


class MalformedFrame(Exception):
    """Raised on unparseable RPC frames so the service can penalize the
    sender instead of letting a remote byte string kill the event loop."""


class RpcHandler:
    """Owns request issue/dispatch over an endpoint. Server behavior is
    supplied as per-protocol callables returning (code, [chunks])."""

    def __init__(self, endpoint: Endpoint, clock=time.monotonic):
        self.endpoint = endpoint
        self.handlers: dict[Protocol, Callable] = {}
        self.limiter = RateLimiter(clock)
        self._next_req = 0
        # req_id -> (protocol, callback(peer, code, chunks))
        self._pending: dict[int, tuple] = {}
        self.goodbyes: list = []

    def register(self, proto: Protocol, handler: Callable) -> None:
        """handler(peer_id, request_bytes) -> (ResponseCode, list[bytes])"""
        self.handlers[proto] = handler

    # -- client side

    def request(
        self, peer_id: str, proto: Protocol, payload: bytes, callback: Callable
    ) -> int:
        req_id = self._next_req
        self._next_req += 1
        # the target peer is recorded so another peer cannot forge or
        # cancel this request's response with a guessed req_id
        self._pending[req_id] = (proto, peer_id, callback)
        frame = struct.pack("<IBB", req_id, proto, 0) + payload
        if not self.endpoint.send(peer_id, CHANNEL_RPC, frame):
            self._pending.pop(req_id, None)
            callback(peer_id, ResponseCode.RESOURCE_UNAVAILABLE, [])
            return -1
        return req_id

    # -- inbound

    def handle_frame(self, sender: str, payload: bytes) -> None:
        """Raises MalformedFrame on garbage — remote input must never be
        able to crash the poll loop."""
        try:
            req_id, proto_raw, is_resp = struct.unpack("<IBB", payload[:6])
            proto = Protocol(proto_raw)
        except (struct.error, ValueError) as e:
            raise MalformedFrame(str(e)) from None
        body = payload[6:]
        if is_resp:
            entry = self._pending.get(req_id)
            if entry is None:
                return
            _, expected_peer, callback = entry
            if sender != expected_peer:
                raise MalformedFrame("response from wrong peer")
            self._pending.pop(req_id, None)
            try:
                code, chunks = _decode_response(body)
            except (struct.error, ValueError) as e:
                raise MalformedFrame(str(e)) from None
            callback(sender, code, chunks)
            return
        # request path
        if not self.limiter.allow(sender, proto):
            self._respond(sender, req_id, proto, ResponseCode.RATE_LIMITED, [])
            return
        if proto == Protocol.GOODBYE:
            self.goodbyes.append(sender)
            return
        handler = self.handlers.get(proto)
        if handler is None:
            self._respond(
                sender, req_id, proto, ResponseCode.INVALID_REQUEST, []
            )
            return
        try:
            code, chunks = handler(sender, body)
        except Exception:
            code, chunks = ResponseCode.SERVER_ERROR, []
        self._respond(sender, req_id, proto, code, chunks)

    def _respond(self, peer, req_id, proto, code, chunks) -> None:
        frame = (
            struct.pack("<IBB", req_id, proto, 1)
            + struct.pack("<BH", code, len(chunks))
            + b"".join(struct.pack("<I", len(c)) + c for c in chunks)
        )
        self.endpoint.send(peer, CHANNEL_RPC, frame)


def _decode_response(body: bytes) -> tuple:
    code, n = struct.unpack("<BH", body[:3])
    chunks, pos = [], 3
    for _ in range(n):
        (ln,) = struct.unpack("<I", body[pos : pos + 4])
        pos += 4
        chunks.append(body[pos : pos + ln])
        pos += ln
    return ResponseCode(code), chunks
