"""Req/resp RPC (lighthouse_network rpc/protocol.rs:294-334 analog).

Protocols carried: Status, Goodbye, Ping, MetaData, BlocksByRange,
BlocksByRoot, BlobsByRange, BlobsByRoot, light-client and PeerDAS
column protocols.

Framing over the transport's RPC channel (round 4):
  <req_id u32><proto u8><is_resp u8>  -- mux header: the stream-id role
                                         yamux plays in the reference
  then SPEC-EXACT ssz_snappy chunk bytes (network/rpc_codec.py,
  rpc/codec.rs parity):
  request : <uvarint ssz_len><snappy-FRAME(ssz)>
  response: chunks of <result u8>[<context 4B>]<uvarint len><frames>

Responses are chunk lists (a BlocksByRange response is a chunk per
block, like the reference's streamed chunks). An inbound token-bucket
rate limiter guards each protocol (rpc/rate_limiter.rs:531 role).
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass
from enum import IntEnum
from typing import Callable, Optional

from ..consensus.ssz import Container, uint64, Bytes4, Bytes32
from . import rpc_codec
from .transport import CHANNEL_RPC, Endpoint


class Protocol(IntEnum):
    STATUS = 0
    GOODBYE = 1
    PING = 2
    METADATA = 3
    BLOCKS_BY_RANGE = 4
    BLOCKS_BY_ROOT = 5
    BLOBS_BY_RANGE = 6
    BLOBS_BY_ROOT = 7
    # light-client server protocols (rpc/protocol.rs LightClient*)
    LIGHT_CLIENT_BOOTSTRAP = 8
    LIGHT_CLIENT_OPTIMISTIC_UPDATE = 9
    LIGHT_CLIENT_FINALITY_UPDATE = 10
    LIGHT_CLIENT_UPDATES_BY_RANGE = 11
    # PeerDAS column protocols (rpc/protocol.rs DataColumnsBy{Root,Range})
    DATA_COLUMNS_BY_ROOT = 12
    DATA_COLUMNS_BY_RANGE = 13
    # ENR-record discovery (discv5 FINDNODE role; boot_node serves it)
    DISCOVERY = 14


class ResponseCode(IntEnum):
    """Wire values per methods.rs:614-635 (round 4: RATE_LIMITED moved
    from the private value 4 to the spec's 139)."""

    SUCCESS = 0
    INVALID_REQUEST = 1
    SERVER_ERROR = 2
    RESOURCE_UNAVAILABLE = 3
    RATE_LIMITED = 139
    BLOBS_NOT_FOUND = 140


# Protocol -> (rpc_codec name, has_context_bytes); DISCOVERY is the
# boot-node's private protocol (no spec id).
_PROTO_NAMES = {
    Protocol.STATUS: "status",
    Protocol.GOODBYE: "goodbye",
    Protocol.PING: "ping",
    Protocol.METADATA: "metadata",
    Protocol.BLOCKS_BY_RANGE: "beacon_blocks_by_range",
    Protocol.BLOCKS_BY_ROOT: "beacon_blocks_by_root",
    Protocol.BLOBS_BY_RANGE: "blob_sidecars_by_range",
    Protocol.BLOBS_BY_ROOT: "blob_sidecars_by_root",
    Protocol.LIGHT_CLIENT_BOOTSTRAP: "light_client_bootstrap",
    Protocol.LIGHT_CLIENT_OPTIMISTIC_UPDATE: "light_client_optimistic_update",
    Protocol.LIGHT_CLIENT_FINALITY_UPDATE: "light_client_finality_update",
    Protocol.LIGHT_CLIENT_UPDATES_BY_RANGE: "light_client_updates_by_range",
    Protocol.DATA_COLUMNS_BY_ROOT: "data_column_sidecars_by_root",
    Protocol.DATA_COLUMNS_BY_RANGE: "data_column_sidecars_by_range",
}


def protocol_has_context(proto: Protocol) -> bool:
    name = _PROTO_NAMES.get(proto)
    if name is None:
        return False
    return rpc_codec.PROTOCOL_IDS[name][1]


def protocol_id(proto: Protocol) -> str:
    """The spec's /eth2/beacon_chain/req/... identifier."""
    name = _PROTO_NAMES.get(proto)
    return rpc_codec.PROTOCOL_IDS[name][0] if name else f"/lh-tpu/{proto.name}"


Status = Container(
    "Status",
    [
        ("fork_digest", Bytes4),
        ("finalized_root", Bytes32),
        ("finalized_epoch", uint64),
        ("head_root", Bytes32),
        ("head_slot", uint64),
    ],
)

BlocksByRangeRequest = Container(
    "BlocksByRangeRequest",
    [("start_slot", uint64), ("count", uint64), ("step", uint64)],
)

Ping = Container("Ping", [("seq_number", uint64)])

MetaData = Container(
    "MetaData", [("seq_number", uint64), ("attnets", uint64)]
)


@dataclass
class _Bucket:
    tokens: float
    last: float


class RateLimiter:
    """Per-(peer, protocol) token bucket (rpc/rate_limiter.rs role)."""

    # protocol -> (capacity, refill per second)
    LIMITS = {
        Protocol.STATUS: (8, 4.0),
        Protocol.GOODBYE: (2, 1.0),
        Protocol.PING: (8, 4.0),
        Protocol.METADATA: (4, 2.0),
        Protocol.BLOCKS_BY_RANGE: (512, 128.0),
        Protocol.BLOCKS_BY_ROOT: (256, 128.0),
        Protocol.BLOBS_BY_RANGE: (512, 128.0),
        Protocol.BLOBS_BY_ROOT: (256, 128.0),
        Protocol.LIGHT_CLIENT_BOOTSTRAP: (4, 1.0),
        Protocol.LIGHT_CLIENT_OPTIMISTIC_UPDATE: (8, 2.0),
        Protocol.LIGHT_CLIENT_FINALITY_UPDATE: (8, 2.0),
        Protocol.LIGHT_CLIENT_UPDATES_BY_RANGE: (16, 4.0),
        Protocol.DATA_COLUMNS_BY_ROOT: (256, 128.0),
        Protocol.DATA_COLUMNS_BY_RANGE: (512, 128.0),
        Protocol.DISCOVERY: (16, 4.0),
    }

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._buckets: dict[tuple, _Bucket] = {}

    def allow(self, peer_id: str, proto: Protocol, cost: int = 1) -> bool:
        cap, rate = self.LIMITS[proto]
        key = (peer_id, proto)
        now = self._clock()
        b = self._buckets.get(key)
        if b is None:
            b = self._buckets[key] = _Bucket(tokens=float(cap), last=now)
        b.tokens = min(cap, b.tokens + (now - b.last) * rate)
        b.last = now
        if b.tokens >= cost:
            b.tokens -= cost
            return True
        return False


class MalformedFrame(Exception):
    """Raised on unparseable RPC frames so the service can penalize the
    sender instead of letting a remote byte string kill the event loop."""


class RpcHandler:
    """Owns request issue/dispatch over an endpoint. Server behavior is
    supplied as per-protocol callables returning (code, [chunks])."""

    def __init__(
        self,
        endpoint: Endpoint,
        clock=time.monotonic,
        fork_digest: bytes = b"\x00\x00\x00\x00",
    ):
        self.endpoint = endpoint
        self.handlers: dict[Protocol, Callable] = {}
        self.limiter = RateLimiter(clock)
        # context bytes stamped on success chunks of context-carrying
        # protocols (the fork digest of the payload's fork)
        self.fork_digest = fork_digest
        self._next_req = 0
        # req_id -> (protocol, peer, callback(peer, code, chunks),
        #            issued-at) — issued-at drives expiry: a peer that
        # accepts a request and never answers must not pin the caller's
        # state machine forever (the reference's RPC response timeout)
        self._pending: dict[int, tuple] = {}
        self._clock = clock
        self.request_timeout = 15.0
        self.goodbyes: list = []

    def register(self, proto: Protocol, handler: Callable) -> None:
        """handler(peer_id, request_bytes) -> (ResponseCode, list[bytes])"""
        self.handlers[proto] = handler

    # -- client side

    def request(
        self, peer_id: str, proto: Protocol, payload: bytes, callback: Callable
    ) -> int:
        req_id = self._next_req
        self._next_req += 1
        # the target peer is recorded so another peer cannot forge or
        # cancel this request's response with a guessed req_id
        self._pending[req_id] = (proto, peer_id, callback, self._clock())
        frame = struct.pack("<IBB", req_id, proto, 0) + rpc_codec.encode_request(
            payload
        )
        if not self.endpoint.send(peer_id, CHANNEL_RPC, frame):
            self._pending.pop(req_id, None)
            callback(peer_id, ResponseCode.RESOURCE_UNAVAILABLE, [])
            return -1
        return req_id

    def expire_requests(self) -> list:
        """Time out pending requests past `request_timeout`: each fires
        its callback with RESOURCE_UNAVAILABLE and the timed-out peer
        ids are returned so the caller can penalize. Drive from the
        service heartbeat."""
        now = self._clock()
        expired = [
            (rid, e)
            for rid, e in self._pending.items()
            if now - e[3] >= self.request_timeout
        ]
        peers = []
        for rid, (_proto, peer, callback, _t) in expired:
            self._pending.pop(rid, None)
            peers.append(peer)
            callback(peer, ResponseCode.RESOURCE_UNAVAILABLE, [])
        return peers

    # -- inbound

    def handle_frame(self, sender: str, payload: bytes) -> None:
        """Raises MalformedFrame on garbage — remote input must never be
        able to crash the poll loop."""
        try:
            req_id, proto_raw, is_resp = struct.unpack("<IBB", payload[:6])
            proto = Protocol(proto_raw)
        except (struct.error, ValueError) as e:
            raise MalformedFrame(str(e)) from None
        body = payload[6:]
        if is_resp:
            entry = self._pending.get(req_id)
            if entry is None:
                return
            _, expected_peer, callback, _issued = entry
            if sender != expected_peer:
                raise MalformedFrame("response from wrong peer")
            self._pending.pop(req_id, None)
            try:
                code, chunks = _decode_response(proto, body)
            except (rpc_codec.RpcCodecError, ValueError) as e:
                raise MalformedFrame(str(e)) from None
            callback(sender, code, chunks)
            return
        # request path
        try:
            body = rpc_codec.decode_request(body)
        except rpc_codec.RpcCodecError as e:
            raise MalformedFrame(str(e)) from None
        if not self.limiter.allow(sender, proto):
            self._respond(sender, req_id, proto, ResponseCode.RATE_LIMITED, [])
            return
        if proto == Protocol.GOODBYE:
            self.goodbyes.append(sender)
            return
        handler = self.handlers.get(proto)
        if handler is None:
            self._respond(
                sender, req_id, proto, ResponseCode.INVALID_REQUEST, []
            )
            return
        try:
            code, chunks = handler(sender, body)
        except Exception:
            code, chunks = ResponseCode.SERVER_ERROR, []
        self._respond(sender, req_id, proto, code, chunks)

    def _respond(self, peer, req_id, proto, code, chunks) -> None:
        """Success: one spec chunk per payload (context bytes stamped on
        context-carrying protocols). Error: one chunk whose ssz body is
        the ErrorType message (rpc/codec.rs RpcResponse::Error arm)."""
        ctx = self.fork_digest if protocol_has_context(proto) else None
        if code == ResponseCode.SUCCESS:
            body = b"".join(
                rpc_codec.encode_response_chunk(int(code), c, ctx)
                for c in chunks
            )
        else:
            body = rpc_codec.encode_response_chunk(int(code), b"")
        frame = struct.pack("<IBB", req_id, proto, 1) + body
        self.endpoint.send(peer, CHANNEL_RPC, frame)


def _decode_response(proto: Protocol, body: bytes) -> tuple:
    parsed = rpc_codec.decode_response_chunks(
        body, has_context=protocol_has_context(proto)
    )
    if not parsed:
        return ResponseCode.SUCCESS, []
    first = parsed[0][0]
    if first != rpc_codec.SUCCESS:
        return ResponseCode(first), []
    return ResponseCode.SUCCESS, [ssz for _, _, ssz in parsed]
