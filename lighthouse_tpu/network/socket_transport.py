"""TCP socket transport — the real process boundary.

Implements the transport.Endpoint seam over localhost/LAN TCP so two
`lighthouse_tpu.cli bn` OS processes can handshake, gossip and
range-sync (the role of lighthouse_network's TCP stack,
service/utils.rs:52-63 — minus QUIC/noise/yamux, which ride behind the
same seam later; frames carry snappy-compressed payloads like the
reference's gossip transform and SSZ-snappy RPC codec).

Wire format, one frame:
    u32le  frame_length (of everything after this field)
    u8     channel      (CHANNEL_GOSSIP / CHANNEL_RPC / 255 = HELLO)
    bytes  snappy(payload)

Connection lifecycle: dial -> send HELLO{our peer_id} -> receive
HELLO{their peer_id} -> frames flow. The acceptor side mirrors it.
Reader threads push decoded frames into the same inbox `poll()`/
`drain()` the in-process hub uses, so NetworkService and everything
above it is transport-agnostic.
"""

from __future__ import annotations

import socket
import struct
import threading
from collections import deque
from typing import Callable, Optional

from . import snappy_codec as snappy
from .transport import Frame

CHANNEL_HELLO = 255
_MAX_FRAME = 1 << 24  # 16 MiB cap (DoS guard; RPC chunks are far smaller)
# Per-peer inbox high-water mark: a peer with this many frames QUEUED
# (not yet drained) gets disconnected instead of exhausting memory —
# per-peer accounting so a flooder can't get honest peers shed
# (advisor r3 + round-4 review).
_MAX_INBOX_PER_PEER = 4096


class SocketEndpoint:
    """transport.Endpoint over TCP. join via SocketHub below."""

    def __init__(self, peer_id: str, host: str = "127.0.0.1", port: int = 0):
        self.peer_id = peer_id
        self._inbox: deque[Frame] = deque()
        self._inbox_counts: dict[str, int] = {}
        self._lock = threading.Lock()
        self._conns: dict[str, socket.socket] = {}
        self._closed = False
        self.on_peer_connected: Optional[Callable] = None
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.addr = self._listener.getsockname()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    # ------------------------------------------------------------ wiring

    def connect(self, host: str, port: int, timeout: float = 5.0) -> str:
        """Dial a peer; returns its peer_id after the HELLO exchange."""
        s = socket.create_connection((host, port), timeout=timeout)
        s.settimeout(timeout)
        _send_frame(s, CHANNEL_HELLO, self.peer_id.encode())
        ch, payload = _recv_frame(s)
        if ch != CHANNEL_HELLO:
            s.close()
            raise ConnectionError("peer did not HELLO")
        peer = payload.decode()
        s.settimeout(None)
        self._register(peer, s)
        return peer

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                s, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._accept_one, args=(s,), daemon=True
            ).start()

    def _accept_one(self, s: socket.socket) -> None:
        try:
            s.settimeout(5.0)
            ch, payload = _recv_frame(s)
            if ch != CHANNEL_HELLO:
                s.close()
                return
            peer = payload.decode()
            _send_frame(s, CHANNEL_HELLO, self.peer_id.encode())
            s.settimeout(None)
            self._register(peer, s)
        except (OSError, ConnectionError, snappy.SnappyError):
            s.close()

    def _register(self, peer: str, s: socket.socket) -> None:
        with self._lock:
            old = self._conns.pop(peer, None)
            self._conns[peer] = s
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        threading.Thread(
            target=self._read_loop, args=(peer, s), daemon=True
        ).start()
        cb = self.on_peer_connected
        if cb is not None:
            cb(peer)

    def _read_loop(self, peer: str, s: socket.socket) -> None:
        try:
            while not self._closed:
                ch, payload = _recv_frame(s)
                with self._lock:
                    if self._inbox_counts.get(peer, 0) >= _MAX_INBOX_PER_PEER:
                        raise ConnectionError(
                            f"inbox overflow from {peer}: disconnecting"
                        )
                    self._inbox.append(
                        Frame(sender=peer, channel=ch, payload=payload)
                    )
                    self._inbox_counts[peer] = (
                        self._inbox_counts.get(peer, 0) + 1
                    )
        except (OSError, ConnectionError, snappy.SnappyError):
            pass
        finally:
            with self._lock:
                if self._conns.get(peer) is s:
                    del self._conns[peer]
            try:
                s.close()
            except OSError:
                pass

    # ------------------------------------------------------- Endpoint API

    def send(self, to_peer: str, channel: int, payload: bytes) -> bool:
        with self._lock:
            s = self._conns.get(to_peer)
        if s is None:
            return False
        try:
            _send_frame(s, channel, payload)
            return True
        except OSError:
            return False

    def poll(self) -> Optional[Frame]:
        with self._lock:
            if not self._inbox:
                return None
            f = self._inbox.popleft()
            self._dec_count(f.sender)
            return f

    def drain(self) -> list:
        with self._lock:
            out = list(self._inbox)
            self._inbox.clear()
            self._inbox_counts.clear()
            return out

    def _dec_count(self, peer: str) -> None:
        c = self._inbox_counts.get(peer, 0) - 1
        if c <= 0:
            self._inbox_counts.pop(peer, None)
        else:
            self._inbox_counts[peer] = c

    def push(self, frame: Frame) -> None:
        with self._lock:
            self._inbox.append(frame)
            self._inbox_counts[frame.sender] = (
                self._inbox_counts.get(frame.sender, 0) + 1
            )

    def connected_peers(self) -> list:
        with self._lock:
            return list(self._conns)

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for s in conns:
            try:
                s.close()
            except OSError:
                pass


class SocketHub:
    """hub.join() shim so NetworkService builds unchanged on sockets."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self.endpoint: Optional[SocketEndpoint] = None

    def join(self, peer_id: str) -> SocketEndpoint:
        self.endpoint = SocketEndpoint(peer_id, self.host, self.port)
        return self.endpoint


# ---------------------------------------------------------------- framing


def _send_frame(s: socket.socket, channel: int, payload: bytes) -> None:
    body = bytes([channel]) + snappy.compress(payload)
    s.sendall(struct.pack("<I", len(body)) + body)


def _recv_exact(s: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _recv_frame(s: socket.socket) -> tuple:
    (ln,) = struct.unpack("<I", _recv_exact(s, 4))
    if ln < 1 or ln > _MAX_FRAME:
        raise ConnectionError(f"bad frame length {ln}")
    body = _recv_exact(s, ln)
    return body[0], snappy.decompress(body[1:])
