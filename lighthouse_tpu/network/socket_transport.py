"""TCP socket transport — the real process boundary.

Implements the transport.Endpoint seam over localhost/LAN TCP so two
`lighthouse_tpu.cli bn` OS processes can handshake, gossip and
range-sync (the role of lighthouse_network's TCP stack,
service/utils.rs:52-63 — minus QUIC/yamux, which ride behind the same
seam later; frames carry snappy-compressed payloads like the
reference's gossip transform).

Wire format, one frame:
    u32le  frame_length (of everything after this field)
    u8     channel      (CHANNEL_GOSSIP / CHANNEL_RPC / 255 = HELLO)
    bytes  snappy(payload)

With `noise=True` (round 4) the connection first runs a REAL
Noise_XX_25519_ChaChaPoly_SHA256 handshake (network/noise.py — the
protocol the reference's snow stack speaks, service/utils.rs:38-63);
the peer-id HELLO rides the handshake payloads, and every subsequent
frame body (channel byte + snappy payload) is AEAD-encrypted under the
session's transport ciphers. Plaintext mode stays the default for the
in-repo twin-node tests.

Connection lifecycle: dial -> HELLO (or noise handshake) -> frames
flow. Reader threads push decoded frames into the same inbox `poll()`/
`drain()` the in-process hub uses, so NetworkService and everything
above it is transport-agnostic.
"""

from __future__ import annotations

import socket
import struct
import threading
from collections import deque
from typing import Callable, Optional

from . import snappy_codec as snappy
from .transport import Frame

CHANNEL_HELLO = 255
_MAX_FRAME = 1 << 24  # 16 MiB cap (DoS guard; RPC chunks are far smaller)
# Per-peer inbox high-water mark: a peer with this many frames QUEUED
# (not yet drained) gets disconnected instead of exhausting memory —
# per-peer accounting so a flooder can't get honest peers shed
# (advisor r3 + round-4 review).
_MAX_INBOX_PER_PEER = 4096


class SocketEndpoint:
    """transport.Endpoint over TCP. join via SocketHub below."""

    def __init__(
        self,
        peer_id: str,
        host: str = "127.0.0.1",
        port: int = 0,
        noise: bool = False,
        static_key: bytes = None,
    ):
        self.peer_id = peer_id
        self.noise = noise
        self._static_key = static_key
        self._inbox: deque[Frame] = deque()
        self._inbox_counts: dict[str, int] = {}
        self._lock = threading.Lock()
        self._conns: dict[str, socket.socket] = {}
        # peer -> (send_cipher, recv_cipher, send_lock); None = plaintext
        self._ciphers: dict[str, tuple] = {}
        self._closed = False
        self.on_peer_connected: Optional[Callable] = None
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.addr = self._listener.getsockname()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    # ------------------------------------------------------------ wiring

    def connect(self, host: str, port: int, timeout: float = 5.0) -> str:
        """Dial a peer; returns its peer_id after the HELLO exchange
        (or the noise handshake when encryption is on)."""
        s = socket.create_connection((host, port), timeout=timeout)
        try:
            s.settimeout(timeout)
            if self.noise:
                peer, ciphers = self._noise_dial(s)
                s.settimeout(None)
                self._register(peer, s, ciphers)
                return peer
            _send_frame(s, CHANNEL_HELLO, self.peer_id.encode())
            ch, payload = _recv_frame(s)
            if ch != CHANNEL_HELLO:
                raise ConnectionError("peer did not HELLO")
            peer = payload.decode()
            s.settimeout(None)
            self._register(peer, s)
            return peer
        except BaseException:
            try:
                s.close()  # never leak the fd on a failed handshake
            except OSError:
                pass
            raise

    # ---------------------------------------------------------- noise

    def _noise_dial(self, s: socket.socket) -> tuple:
        from .noise import NoiseXX

        hs = NoiseXX(initiator=True, static_private=self._static_key)
        _send_raw(s, hs.write_msg1())
        hs.read_msg2(_recv_raw(s))
        _send_raw(s, hs.write_msg3(self.peer_id.encode()))
        peer = hs.remote_payload.decode()
        send, recv = hs.split()
        return peer, (send, recv, threading.Lock())

    def _noise_accept(self, s: socket.socket) -> tuple:
        from .noise import NoiseXX

        hs = NoiseXX(initiator=False, static_private=self._static_key)
        hs.read_msg1(_recv_raw(s))
        _send_raw(s, hs.write_msg2(self.peer_id.encode()))
        hs.read_msg3(_recv_raw(s))
        peer = hs.remote_payload.decode()
        send, recv = hs.split()
        return peer, (send, recv, threading.Lock())

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                s, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._accept_one, args=(s,), daemon=True
            ).start()

    def _accept_one(self, s: socket.socket) -> None:
        try:
            s.settimeout(5.0)
            if self.noise:
                peer, ciphers = self._noise_accept(s)
                s.settimeout(None)
                self._register(peer, s, ciphers)
                return
            ch, payload = _recv_frame(s)
            if ch != CHANNEL_HELLO:
                s.close()
                return
            peer = payload.decode()
            _send_frame(s, CHANNEL_HELLO, self.peer_id.encode())
            s.settimeout(None)
            self._register(peer, s)
        except Exception:
            # remote bytes must never kill the acceptor thread or leak
            # the fd (non-UTF8 handshake payloads, codec errors, ...)
            try:
                s.close()
            except OSError:
                pass

    def _register(self, peer: str, s: socket.socket, ciphers=None) -> None:
        with self._lock:
            old = self._conns.pop(peer, None)
            self._conns[peer] = s
            if ciphers is not None:
                self._ciphers[peer] = ciphers
            else:
                self._ciphers.pop(peer, None)
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        threading.Thread(
            target=self._read_loop, args=(peer, s), daemon=True
        ).start()
        cb = self.on_peer_connected
        if cb is not None:
            cb(peer)

    def _read_loop(self, peer: str, s: socket.socket) -> None:
        from .noise import NoiseError

        ciphers = self._ciphers.get(peer)
        recv_cipher = ciphers[1] if ciphers else None
        try:
            while not self._closed:
                ch, payload = _recv_frame(s, recv_cipher)
                with self._lock:
                    if self._inbox_counts.get(peer, 0) >= _MAX_INBOX_PER_PEER:
                        raise ConnectionError(
                            f"inbox overflow from {peer}: disconnecting"
                        )
                    self._inbox.append(
                        Frame(sender=peer, channel=ch, payload=payload)
                    )
                    self._inbox_counts[peer] = (
                        self._inbox_counts.get(peer, 0) + 1
                    )
        except (OSError, ConnectionError, snappy.SnappyError, NoiseError):
            pass
        finally:
            with self._lock:
                if self._conns.get(peer) is s:
                    del self._conns[peer]
                    self._ciphers.pop(peer, None)
            try:
                s.close()
            except OSError:
                pass

    # ------------------------------------------------------- Endpoint API

    def send(self, to_peer: str, channel: int, payload: bytes) -> bool:
        with self._lock:
            s = self._conns.get(to_peer)
            ciphers = self._ciphers.get(to_peer)
        if s is None:
            return False
        try:
            if ciphers is not None:
                send_cipher, _, send_lock = ciphers
                # nonce ordering: one in-flight encrypt+send per conn
                with send_lock:
                    _send_frame(s, channel, payload, send_cipher)
            else:
                _send_frame(s, channel, payload)
            return True
        except OSError:
            return False

    def poll(self) -> Optional[Frame]:
        with self._lock:
            if not self._inbox:
                return None
            f = self._inbox.popleft()
            self._dec_count(f.sender)
            return f

    def drain(self) -> list:
        with self._lock:
            out = list(self._inbox)
            self._inbox.clear()
            self._inbox_counts.clear()
            return out

    def _dec_count(self, peer: str) -> None:
        c = self._inbox_counts.get(peer, 0) - 1
        if c <= 0:
            self._inbox_counts.pop(peer, None)
        else:
            self._inbox_counts[peer] = c

    def push(self, frame: Frame) -> None:
        with self._lock:
            self._inbox.append(frame)
            self._inbox_counts[frame.sender] = (
                self._inbox_counts.get(frame.sender, 0) + 1
            )

    def connected_peers(self) -> list:
        with self._lock:
            return list(self._conns)

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for s in conns:
            try:
                s.close()
            except OSError:
                pass


class SocketHub:
    """hub.join() shim so NetworkService builds unchanged on sockets."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self.endpoint: Optional[SocketEndpoint] = None

    def join(self, peer_id: str) -> SocketEndpoint:
        self.endpoint = SocketEndpoint(peer_id, self.host, self.port)
        return self.endpoint


# ---------------------------------------------------------------- framing


def _send_frame(
    s: socket.socket, channel: int, payload: bytes, cipher=None
) -> None:
    body = bytes([channel]) + snappy.compress(payload)
    if cipher is not None:
        body = cipher.encrypt_with_ad(b"", body)
    s.sendall(struct.pack("<I", len(body)) + body)


def _recv_exact(s: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = s.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return bytes(buf)


def _recv_frame(s: socket.socket, cipher=None) -> tuple:
    (ln,) = struct.unpack("<I", _recv_exact(s, 4))
    if ln < 1 or ln > _MAX_FRAME:
        raise ConnectionError(f"bad frame length {ln}")
    body = _recv_exact(s, ln)
    if cipher is not None:
        body = cipher.decrypt_with_ad(b"", body)
    return body[0], snappy.decompress(body[1:])


def _send_raw(s: socket.socket, data: bytes) -> None:
    s.sendall(struct.pack("<I", len(data)) + data)


def _recv_raw(s: socket.socket) -> bytes:
    (ln,) = struct.unpack("<I", _recv_exact(s, 4))
    if ln > _MAX_FRAME:
        raise ConnectionError(f"bad handshake length {ln}")
    return _recv_exact(s, ln)
