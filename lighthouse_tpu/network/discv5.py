"""discv5 v5.1 node: UDP service, sessions, handshakes, FINDNODE.

The runtime half of the discovery wire layer (packet codec:
network/discv5_wire.py; records: network/enr.py). Plays the role
sigp/discv5's `Discv5` service plays for the reference
(`beacon_node/lighthouse_network/src/discovery/mod.rs` drives it for
peer discovery; `boot_node/` runs one standalone).

Protocol flow implemented (discv5-theory spec):

  A has no session with B:
    A -> B  ordinary packet, random message data (can't encrypt yet)
    B -> A  WHOAREYOU (id-nonce challenge, references A's nonce)
    A -> B  HANDSHAKE packet: id-signature over the challenge data,
            ephemeral pubkey, [A's ENR if B's view is stale], plus the
            original message encrypted under the fresh session keys
    B       verifies the id-signature against A's ENR key, derives the
            same keys, decrypts; session established both ways.

  With a session: ordinary packets, AES-128-GCM.

Server side answers PING with PONG (ip/port echo) and FINDNODE with
NODES chunked at NODES_PER_MSG records; TALKREQ gets an empty
TALKRESP (no sub-protocols registered).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..crypto import secp256k1
from . import discv5_wire as W
from .enr import Enr

NODES_PER_MSG = 4
REQUEST_TIMEOUT = 2.0
MAX_TABLE = 1024
# unauthenticated-state bounds: spoofed src ids must not grow memory
# without limit (oldest entries evicted, insertion order)
MAX_TRANSIENT = 4096


def _bounded_put(d: dict, key, value, cap: int = MAX_TRANSIENT) -> None:
    if key not in d and len(d) >= cap:
        d.pop(next(iter(d)))
    d[key] = value


class Discv5Error(Exception):
    pass


class Discv5Node:
    """One UDP discovery endpoint."""

    def __init__(
        self,
        private_key: bytes = None,
        host: str = "127.0.0.1",
        port: int = 0,
        enr_kwargs: dict = None,
    ):
        self.private_key = private_key or os.urandom(32)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((host, port))
        self.addr = self.sock.getsockname()
        kwargs = dict(enr_kwargs or {})
        kwargs.setdefault("ip", socket.inet_aton(host))
        kwargs.setdefault("udp", self.addr[1])
        self.enr = Enr.build(self.private_key, **kwargs)
        self.node_id = self.enr.node_id()
        # peer state
        self._table: Dict[bytes, Enr] = {}          # node_id -> ENR
        self._sessions: Dict[bytes, W.Session] = {}  # node_id -> keys
        self._addrs: Dict[bytes, tuple] = {}         # node_id -> udp addr
        # outbound nonces that may be challenged: nonce -> node_id
        # (session sends register too — a restarted peer WHOAREYOUs an
        # encrypted packet and we must re-handshake, not go deaf)
        self._sent_nonces: Dict[bytes, bytes] = {}
        # messages waiting for a handshake to finish: node_id -> [msg]
        # (ONE handshake per peer at a time; concurrent requests queue
        # here instead of racing the challenge)
        self._pending_msgs: Dict[bytes, list] = {}
        self._pending_ts: Dict[bytes, float] = {}
        # challenges we issued: node_id -> challenge-data
        self._challenges: Dict[bytes, bytes] = {}
        # request/response correlation: req_id -> [reply Messages]
        self._responses: Dict[bytes, list] = {}
        self._resp_cv = threading.Condition()
        self._lock = threading.RLock()
        self._closed = False
        self.on_enr_discovered: Optional[Callable] = None
        threading.Thread(target=self._recv_loop, daemon=True).start()

    # ------------------------------------------------------------ table

    def add_enr(self, enr: Enr) -> bool:
        if not enr.verify():
            return False
        nid = enr.node_id()
        with self._lock:
            known = self._table.get(nid)
            if known is not None and known.seq >= enr.seq:
                return False
            if len(self._table) >= MAX_TABLE and nid not in self._table:
                return False
            self._table[nid] = enr
            if enr.ip and enr.udp:
                self._addrs[nid] = (enr.ip, enr.udp)
        cb = self.on_enr_discovered
        if cb is not None:
            cb(enr)
        return True

    def known_enrs(self) -> List[Enr]:
        with self._lock:
            return list(self._table.values())

    # ------------------------------------------------------- client ops

    def ping(self, enr: Enr, timeout: float = REQUEST_TIMEOUT) -> Optional[W.Message]:
        """PING; returns the PONG message (enr_seq tells us whether to
        re-fetch their record) or None."""
        req_id = os.urandom(4)
        msg = W.encode_ping(req_id, self.enr.seq)
        replies = self._request(enr, req_id, msg, timeout, want=1)
        return replies[0] if replies else None

    def find_node(
        self, enr: Enr, distances: List[int], timeout: float = REQUEST_TIMEOUT
    ) -> List[Enr]:
        """FINDNODE at the given log2 distances; NODES replies are
        signature-verified and ingested into the table."""
        req_id = os.urandom(4)
        msg = W.encode_findnode(req_id, distances)
        replies = self._request(enr, req_id, msg, timeout, want=None)
        out = []
        for reply in replies:
            if reply.kind != W.MSG_NODES:
                continue
            for rec in reply.records:
                # Enr.decode already verified the signature inside
                # decode_message; add_enr re-verifies at its own gate
                self.add_enr(rec)
                out.append(rec)
        return out

    def _request(
        self, enr: Enr, req_id: bytes, msg: bytes, timeout: float, want
    ) -> list:
        """Send a request (handshaking if needed) and gather replies.
        want=N waits for N messages; want=None waits for a NODES total."""
        nid = enr.node_id()
        self.add_enr(enr)
        with self._resp_cv:
            self._responses[req_id] = []
        got: list = []
        try:
            self._send_message(nid, msg)
            deadline = time.time() + timeout
            with self._resp_cv:
                while time.time() < deadline:
                    got = self._responses.get(req_id, [])
                    if want is not None and len(got) >= want:
                        break
                    if want is None and got and sum(
                        1 for m in got if m.kind == W.MSG_NODES
                    ) >= (got[0].total or 1):
                        break
                    self._resp_cv.wait(timeout=0.05)
        except Discv5Error:
            pass  # e.g. the ENR carries no ip/udp: behave as a timeout
        finally:
            with self._resp_cv:
                self._responses.pop(req_id, None)
        return got

    # ---------------------------------------------------------- sending

    def _send_message(self, nid: bytes, message_pt: bytes) -> None:
        with self._lock:
            session = self._sessions.get(nid)
            addr = self._addrs.get(nid)
        if addr is None:
            raise Discv5Error("no address for node")
        if session is None:
            with self._lock:
                queue = self._pending_msgs.setdefault(nid, [])
                queue.append(message_pt)
                now = time.time()
                fresh = now - self._pending_ts.get(nid, 0) < REQUEST_TIMEOUT
                if len(queue) > 1 and fresh:
                    return  # a handshake is already in flight
                # elicit a WHOAREYOU (first message, or the previous
                # random packet looks lost); message rides
                # _pending_msgs, hence the None
                self._pending_ts[nid] = now
                nonce = os.urandom(12)
                _bounded_put(self._sent_nonces, nonce, (nid, None))
            pkt = W.encode_packet(
                nid, W.FLAG_ORDINARY, nonce, self.node_id, os.urandom(16)
            )
            self.sock.sendto(pkt, addr)
            return
        nonce = session.next_nonce()
        masking_iv = os.urandom(16)
        header = W.build_header(W.FLAG_ORDINARY, nonce, self.node_id)
        ct = W.aes_gcm_encrypt(
            session.send_key, nonce, message_pt, masking_iv + header
        )
        pkt = W.encode_packet(
            nid, W.FLAG_ORDINARY, nonce, self.node_id, ct, masking_iv
        )
        with self._lock:
            # a restarted peer may challenge this nonce: remember it so
            # the WHOAREYOU triggers a re-handshake with this message
            _bounded_put(self._sent_nonces, nonce, (nid, message_pt))
        self.sock.sendto(pkt, addr)

    # -------------------------------------------------------- receiving

    def _recv_loop(self) -> None:
        while not self._closed:
            try:
                data, addr = self.sock.recvfrom(2048)
            except OSError:
                return
            try:
                pkt = W.decode_packet(self.node_id, data)
                self._handle_packet(pkt, addr)
            except Exception:
                # ANY malformed remote datagram (bad rlp, EnrError, a
                # short struct field, ...) must never kill the receive
                # thread — one escape deafens the node permanently
                continue

    def _handle_packet(self, pkt: W.Packet, addr) -> None:
        if pkt.flag == W.FLAG_WHOAREYOU:
            self._on_whoareyou(pkt, addr)
        elif pkt.flag == W.FLAG_HANDSHAKE:
            self._on_handshake(pkt, addr)
        elif pkt.flag == W.FLAG_ORDINARY:
            self._on_ordinary(pkt, addr)

    def _on_ordinary(self, pkt: W.Packet, addr) -> None:
        nid = pkt.src_id
        with self._lock:
            session = self._sessions.get(nid)
            if nid not in self._addrs:
                _bounded_put(self._addrs, nid, addr)
        if session is None:
            self._send_whoareyou(pkt, nid, addr)
            return
        try:
            pt = W.aes_gcm_decrypt(
                session.recv_key,
                pkt.nonce,
                pkt.message_ct,
                pkt.masking_iv + pkt.header,
            )
        except W.Discv5WireError:
            # undecryptable under the current session: stale keys on
            # their side -> re-challenge
            self._send_whoareyou(pkt, nid, addr)
            return
        with self._lock:
            # authenticated packet: track NAT rebinds, else replies go
            # to the stale endpoint forever
            if self._addrs.get(nid) != addr:
                _bounded_put(self._addrs, nid, addr)
        self._on_message(nid, addr, W.decode_message(pt))

    def _send_whoareyou(self, pkt: W.Packet, nid: bytes, addr) -> None:
        id_nonce = os.urandom(16)
        with self._lock:
            known = self._table.get(nid)
        authdata = W.whoareyou_authdata(
            id_nonce, known.seq if known is not None else 0
        )
        masking_iv = os.urandom(16)
        challenge_data = (
            masking_iv
            + W.build_header(W.FLAG_WHOAREYOU, pkt.nonce, authdata)
        )
        with self._lock:
            _bounded_put(self._challenges, nid, challenge_data)
        out = W.encode_packet(
            nid, W.FLAG_WHOAREYOU, pkt.nonce, authdata, b"", masking_iv
        )
        self.sock.sendto(out, addr)

    def _on_whoareyou(self, pkt: W.Packet, addr) -> None:
        """One of our packets was challenged: handshake and (re)send
        the pending message(s) under the fresh keys. Covers both the
        deliberate no-session random packet and a session packet a
        restarted peer could no longer decrypt."""
        if len(pkt.authdata) != 24:
            return  # id-nonce(16) || enr-seq(8), nothing else is valid
        with self._lock:
            entry = self._sent_nonces.pop(pkt.nonce, None)
        if entry is None:
            return
        nid, challenged_msg = entry
        with self._lock:
            self._sessions.pop(nid, None)  # stale either way
            remote = self._table.get(nid)
            queue = self._pending_msgs.pop(nid, [])
            self._pending_ts.pop(nid, None)
        if challenged_msg is not None:
            queue.insert(0, challenged_msg)
        if remote is None or not queue:
            return
        remote_pub = remote.pairs.get(b"secp256k1")
        if remote_pub is None:
            return
        challenge_data = pkt.masking_iv + pkt.header
        eph_priv = os.urandom(32)
        eph_pub = secp256k1.pubkey_compressed(eph_priv)
        secret = W.ecdh(remote_pub, eph_priv)
        ini_key, rec_key = W.derive_session_keys(
            secret, self.node_id, nid, challenge_data
        )
        sig = W.id_sign(self.private_key, challenge_data, eph_pub, nid)
        # include our record when their view of us is stale
        their_seq = struct.unpack(">Q", pkt.authdata[16:24])[0]
        record = self.enr.encode() if their_seq < self.enr.seq else b""
        authdata = W.handshake_authdata(self.node_id, sig, eph_pub, record)
        session = W.Session(send_key=ini_key, recv_key=rec_key)
        nonce = session.next_nonce()
        masking_iv = os.urandom(16)
        header = W.build_header(W.FLAG_HANDSHAKE, nonce, authdata)
        ct = W.aes_gcm_encrypt(
            ini_key, nonce, queue[0], masking_iv + header
        )
        out = W.encode_packet(
            nid, W.FLAG_HANDSHAKE, nonce, authdata, ct, masking_iv
        )
        with self._lock:
            self._sessions[nid] = session
            self._addrs[nid] = addr
        self.sock.sendto(out, addr)
        # any requests queued behind the handshake ride the session
        for msg in queue[1:]:
            self._send_message(nid, msg)

    def _on_handshake(self, pkt: W.Packet, addr) -> None:
        src_id, sig, eph_pub, record_rlp = W.parse_handshake_authdata(
            pkt.authdata
        )
        with self._lock:
            # peek, don't pop: a forged handshake must not destroy the
            # legitimate peer's pending challenge (popped on success)
            challenge_data = self._challenges.get(src_id)
            known = self._table.get(src_id)
        if challenge_data is None:
            return
        if record_rlp:
            try:
                enr = Enr.decode(record_rlp)
            except Exception:
                return
            if enr.node_id() != src_id:
                return  # record does not prove the claimed source
            self.add_enr(enr)  # False just means we already knew it
            known = enr
        if known is None:
            return
        remote_pub = known.pairs.get(b"secp256k1")
        if remote_pub is None or not W.id_verify(
            remote_pub, sig, challenge_data, eph_pub, self.node_id
        ):
            return
        secret = W.ecdh(eph_pub, self.private_key)
        ini_key, rec_key = W.derive_session_keys(
            secret, src_id, self.node_id, challenge_data
        )
        # they are the initiator: their send key is ours to receive
        session = W.Session(send_key=rec_key, recv_key=ini_key)
        try:
            pt = W.aes_gcm_decrypt(
                ini_key, pkt.nonce, pkt.message_ct, pkt.masking_iv + pkt.header
            )
        except W.Discv5WireError:
            return
        with self._lock:
            self._challenges.pop(src_id, None)  # consumed by success
            self._sessions[src_id] = session
            self._addrs[src_id] = addr
        self._on_message(src_id, addr, W.decode_message(pt))

    # ----------------------------------------------------- message plane

    def _on_message(self, nid: bytes, addr, msg: W.Message) -> None:
        if msg.kind == W.MSG_PING:
            self._send_message(
                nid,
                W.encode_pong(
                    msg.req_id,
                    self.enr.seq,
                    socket.inet_aton(addr[0]),
                    addr[1],
                ),
            )
        elif msg.kind == W.MSG_FINDNODE:
            self._serve_findnode(nid, msg)
        elif msg.kind == W.MSG_TALKREQ:
            self._send_message(nid, W.encode_talkresp(msg.req_id, b""))
        elif msg.kind in (W.MSG_PONG, W.MSG_NODES, W.MSG_TALKRESP):
            with self._resp_cv:
                if msg.req_id in self._responses:
                    self._responses[msg.req_id].append(msg)
                    self._resp_cv.notify_all()

    def _serve_findnode(self, nid: bytes, msg: W.Message) -> None:
        wanted = set(msg.distances)
        matches: List[bytes] = []
        with self._lock:
            candidates = list(self._table.values())
        if 0 in wanted:
            matches.append(self.enr.encode())
        for enr in candidates:
            if W.node_distance(self.node_id, enr.node_id()) in wanted:
                matches.append(enr.encode())
        matches = matches[:16]  # spec cap on total records
        chunks = [
            matches[i : i + NODES_PER_MSG]
            for i in range(0, len(matches), NODES_PER_MSG)
        ] or [[]]
        total = len(chunks)
        for chunk in chunks:
            self._send_message(
                nid, W.encode_nodes(msg.req_id, total, chunk)
            )

    def close(self) -> None:
        self._closed = True
        try:
            self.sock.close()
        except OSError:
            pass
