"""Noise_XX_25519_ChaChaPoly_SHA256 — the libp2p-noise handshake the
reference runs under every connection (lighthouse_network
service/utils.rs builds noise-over-TCP via snow; protocol name
`Noise_XX_25519_ChaChaPoly_SHA256`).

Implements the Noise spec (rev 34) state machine for the XX pattern:

    XX:
      -> e
      <- e, ee, s, es
      -> s, se

plus the transport phase (CipherState pair from Split()). Primitives:
crypto/x25519.py + crypto/chacha20poly1305.py (RFC-vector pinned),
SHA256/HMAC from hashlib. The handshake payloads carry whatever the
caller supplies (libp2p puts a signed identity blob there; the socket
transport uses the peer-id HELLO).

Symmetry is proven by tests/test_noise.py: both roles derive identical
transport keys, messages tamper-fail, and nonces advance per message.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
from typing import Optional

from ..crypto import chacha20poly1305 as aead
from ..crypto import x25519

PROTOCOL_NAME = b"Noise_XX_25519_ChaChaPoly_SHA256"


class NoiseError(Exception):
    pass


def _hkdf(chaining_key: bytes, ikm: bytes, n: int) -> tuple:
    """Noise HKDF: returns n (2 or 3) 32-byte outputs."""
    temp = hmac.new(chaining_key, ikm, hashlib.sha256).digest()
    out1 = hmac.new(temp, b"\x01", hashlib.sha256).digest()
    out2 = hmac.new(temp, out1 + b"\x02", hashlib.sha256).digest()
    if n == 2:
        return out1, out2
    out3 = hmac.new(temp, out2 + b"\x03", hashlib.sha256).digest()
    return out1, out2, out3


class CipherState:
    def __init__(self):
        self.k: Optional[bytes] = None
        self.n = 0

    def initialize_key(self, key: Optional[bytes]) -> None:
        self.k = key
        self.n = 0

    def _nonce(self) -> bytes:
        return b"\x00" * 4 + struct.pack("<Q", self.n)

    def encrypt_with_ad(self, ad: bytes, plaintext: bytes) -> bytes:
        if self.k is None:
            return plaintext
        out = aead.seal(self.k, self._nonce(), plaintext, ad)
        self.n += 1
        return out

    def decrypt_with_ad(self, ad: bytes, ciphertext: bytes) -> bytes:
        if self.k is None:
            return ciphertext
        try:
            out = aead.open_(self.k, self._nonce(), ciphertext, ad)
        except ValueError as e:
            raise NoiseError(str(e)) from None
        self.n += 1
        return out


class SymmetricState:
    def __init__(self):
        self.ck = hashlib.sha256(PROTOCOL_NAME).digest() if len(
            PROTOCOL_NAME
        ) > 32 else PROTOCOL_NAME.ljust(32, b"\x00")
        self.h = self.ck
        self.cipher = CipherState()

    def mix_key(self, ikm: bytes) -> None:
        self.ck, temp_k = _hkdf(self.ck, ikm, 2)
        self.cipher.initialize_key(temp_k)

    def mix_hash(self, data: bytes) -> None:
        self.h = hashlib.sha256(self.h + data).digest()

    def encrypt_and_hash(self, plaintext: bytes) -> bytes:
        ct = self.cipher.encrypt_with_ad(self.h, plaintext)
        self.mix_hash(ct)
        return ct

    def decrypt_and_hash(self, ciphertext: bytes) -> bytes:
        pt = self.cipher.decrypt_with_ad(self.h, ciphertext)
        self.mix_hash(ciphertext)
        return pt

    def split(self) -> tuple:
        k1, k2 = _hkdf(self.ck, b"", 2)
        c1, c2 = CipherState(), CipherState()
        c1.initialize_key(k1)
        c2.initialize_key(k2)
        return c1, c2


class NoiseXX:
    """One side of a Noise XX handshake.

    Usage (initiator):     Usage (responder):
      m1 = a.write_msg1()    b.read_msg1(m1)
      a.read_msg2(m2)        m2 = b.write_msg2(payload)
      m3 = a.write_msg3(pl)  b.read_msg3(m3)
      a.split() / b.split() -> (send_cipher, recv_cipher), role-aware.
    """

    def __init__(self, initiator: bool, static_private: bytes = None):
        self.initiator = initiator
        self.s_priv = static_private or os.urandom(32)
        self.s_pub = x25519.public_key(self.s_priv)
        self.e_priv: Optional[bytes] = None
        self.e_pub: Optional[bytes] = None
        self.re: Optional[bytes] = None
        self.rs: Optional[bytes] = None
        self.ss = SymmetricState()
        self.ss.mix_hash(b"")  # empty prologue
        self.remote_payload: bytes = b""

    # -- message 1: -> e

    def write_msg1(self) -> bytes:
        assert self.initiator
        self.e_priv = self.e_priv or os.urandom(32)
        self.e_pub = x25519.public_key(self.e_priv)
        self.ss.mix_hash(self.e_pub)
        return self.e_pub + self.ss.encrypt_and_hash(b"")

    def read_msg1(self, msg: bytes) -> None:
        assert not self.initiator
        if len(msg) < 32:
            raise NoiseError("short msg1")
        self.re = msg[:32]
        self.ss.mix_hash(self.re)
        self.ss.decrypt_and_hash(msg[32:])

    # -- message 2: <- e, ee, s, es

    def write_msg2(self, payload: bytes = b"") -> bytes:
        assert not self.initiator
        self.e_priv = self.e_priv or os.urandom(32)
        self.e_pub = x25519.public_key(self.e_priv)
        out = bytearray()
        self.ss.mix_hash(self.e_pub)
        out += self.e_pub
        self.ss.mix_key(x25519.x25519(self.e_priv, self.re))      # ee
        out += self.ss.encrypt_and_hash(self.s_pub)               # s
        self.ss.mix_key(x25519.x25519(self.s_priv, self.re))      # es
        out += self.ss.encrypt_and_hash(payload)
        return bytes(out)

    def read_msg2(self, msg: bytes) -> None:
        assert self.initiator
        if len(msg) < 32 + 48:
            raise NoiseError("short msg2")
        self.re = msg[:32]
        self.ss.mix_hash(self.re)
        self.ss.mix_key(x25519.x25519(self.e_priv, self.re))      # ee
        self.rs = self.ss.decrypt_and_hash(msg[32:80])            # s
        self.ss.mix_key(x25519.x25519(self.e_priv, self.rs))      # es
        self.remote_payload = self.ss.decrypt_and_hash(msg[80:])

    # -- message 3: -> s, se

    def write_msg3(self, payload: bytes = b"") -> bytes:
        assert self.initiator
        out = bytearray()
        out += self.ss.encrypt_and_hash(self.s_pub)               # s
        self.ss.mix_key(x25519.x25519(self.s_priv, self.re))      # se
        out += self.ss.encrypt_and_hash(payload)
        return bytes(out)

    def read_msg3(self, msg: bytes) -> None:
        assert not self.initiator
        if len(msg) < 48:
            raise NoiseError("short msg3")
        self.rs = self.ss.decrypt_and_hash(msg[:48])              # s
        self.ss.mix_key(x25519.x25519(self.e_priv, self.rs))      # se
        self.remote_payload = self.ss.decrypt_and_hash(msg[48:])

    def split(self) -> tuple:
        """(send, recv) CipherStates for THIS role (noise spec: the
        first split cipher is the initiator->responder direction)."""
        c1, c2 = self.ss.split()
        return (c1, c2) if self.initiator else (c2, c1)

    @property
    def handshake_hash(self) -> bytes:
        return self.ss.h
