"""SyncManager: per-chain range sync + single-block lookups
(network/src/sync/manager.rs:224, range_sync/{range.rs,chain.rs,
batch.rs}, block_lookups/).

Rebuilt (ISSUE 7) from a single global pending batch into the
reference's load-bearing structure:

  - Status handshakes classify peers into HEAD CHAINS keyed by
    (target root, target slot) (range.rs add_peer role). Two nodes on
    opposite sides of a healed partition advertise different targets —
    each gets its own `SyncingChain` with its own peer pool.
  - Every chain runs a batch state machine: batches move through
    QUEUED -> DOWNLOADING -> AWAITING_PROCESSING -> PROCESSING ->
    PROCESSED | FAILED (batch.rs BatchState), with per-batch attempt
    tracking, per-peer `tried` sets, and peer penalization on failure.
  - Chains start at the COMMON point — the local finalized slot (or
    the checkpoint anchor for checkpoint-synced nodes) — never at the
    local head: after a fork, blocks above the fork point would not
    attach and the serving peer would be penalized for OUR gap (the
    root cause of the 4-node post-partition convergence failure).
  - Segment import failures are typed (`SegmentError.reason`):
    `unknown_parent` is our start point being wrong (restart the
    chain, NO penalty); `not_linked`/`invalid_block` are the peer's
    misbehavior (penalize, retry from the next peer in the chain).
  - In-flight batches carry an issue timestamp; `tick()` expires
    batches past `batch_timeout` so a silent peer (e.g. one behind an
    asymmetric partition that swallows responses) cannot wedge sync —
    the stalled peer is penalized and the batch re-queued.
  - An empty batch is only accepted as a run of skipped slots after a
    SECOND peer confirms it (or no other peer exists): a withholding
    peer that advertises a head but serves nothing is caught by the
    cross-check and penalized once the confirming peer serves blocks.
  - Chain arbitration: the syncing target is NOT "highest advertised
    head slot wins". Chains whose target fork choice already contains
    are complete (nothing to sync); among live chains the one with the
    most supporting peers syncs first (range.rs chain selection), and
    the HEAD decision stays with fork choice at import time — sync
    only feeds it blocks.
  - Unknown-parent gossip blocks trigger a BlocksByRoot lookup walking
    back to a known ancestor (block_lookups/ role); failed lookups
    release their request slot (no permanent `_parent_requests` leak)
    and retry against the next peer; released children whose parent
    import raced re-enter the lookup path instead of being dropped.

The manager is synchronous and event-driven (`tick()` + callbacks) and
takes an injectable clock, so sync policy is unit-testable without a
runtime (tests/test_sync.py); the node's loop drives it alongside
NetworkService.poll().

Observability (rides the PR 3 metrics/tracing layer): `sync_state`
gauge (one series per state, 0/1), `sync_chains_active`,
`sync_batches_total{result=...}`, `sync_peer_penalties_total{reason=
...}`, `sync_parent_lookups_total{result=...}`, and `sync:*` spans
anchored to the batch's start slot.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from ..common import metrics, tracing
from ..consensus import types as T
from ..consensus.forked_types import UnsupportedBlockContent
from ..node.beacon_chain import BlockError, SegmentError
from ..node.beacon_processor import Work, WorkType
from .peer_manager import PeerAction, PeerStatus
from .rpc import BlocksByRangeRequest, Protocol, ResponseCode, Status


def decode_block_response(spec, raw: bytes):
    """Decode a SignedBeaconBlock RPC chunk: the framework's native
    union encoding first, then the fork-dispatched SPEC-EXACT decode
    (consensus/forked_types.decode_signed_block) so blocks served by an
    externally-implemented peer ingest too (beacon_block.rs superstruct
    decode role). Raises ValueError when neither parses."""
    try:
        return T.SignedBeaconBlock.deserialize(raw)
    except Exception:
        from ..consensus import forked_types as FT

        return FT.decode_signed_block(spec, raw)


BATCH_SLOTS = 64  # EPOCHS_PER_BATCH * 32 in the reference
MAX_PARENT_DEPTH = 32  # block_lookups parent-chain length cap
# batch retry economics (range_sync/batch.rs MAX_BATCH_DOWNLOAD_ATTEMPTS
# role): a failed batch retries against peers that haven't failed it
# yet; after this many attempts the CHAIN is abandoned (the advertised
# target may simply be gone)
MAX_BATCH_ATTEMPTS = 5
# one unknown-parent chain restart is allowed (a prune may have raced
# the start-slot computation); a second means the chain can't attach
MAX_CHAIN_RESTARTS = 1
# batches in flight per chain: downloads pipeline ahead of processing,
# processing stays strictly in slot order
MAX_INFLIGHT_PER_CHAIN = 2

_SYNC_STATE = metrics.gauge(
    "sync_state",
    "Sync state machine position (1 on exactly one state series)",
    labelnames=("state",),
)
_SYNC_CHAINS = metrics.gauge(
    "sync_chains_active", "Head chains currently being range-synced"
)
_SYNC_BATCHES = metrics.counter(
    "sync_batches_total",
    "Range-sync batch outcomes",
    labelnames=("result",),
)
_SYNC_PENALTIES = metrics.counter(
    "sync_peer_penalties_total",
    "Peers penalized by sync, by reason",
    labelnames=("reason",),
)
_SYNC_LOOKUPS = metrics.counter(
    "sync_parent_lookups_total",
    "Single-block (unknown parent) lookup outcomes",
    labelnames=("result",),
)


class SyncState(Enum):
    IDLE = "idle"  # in sync (or no better peer known)
    RANGE = "range"  # catching up one or more head chains
    STALLED = "stalled"  # targets exist but no usable peer serves them


class BatchState(Enum):
    QUEUED = "queued"
    DOWNLOADING = "downloading"
    AWAITING_PROCESSING = "awaiting_processing"
    PROCESSING = "processing"
    PROCESSED = "processed"
    FAILED = "failed"


@dataclass
class Batch:
    """One slot-range download unit (batch.rs BatchInfo)."""

    start_slot: int
    count: int
    state: BatchState = BatchState.QUEUED
    attempts: int = 0
    tried: set = field(default_factory=set)
    peer: Optional[str] = None
    issued_at: float = 0.0
    # monotonically bumped on every (re)issue: a late response carrying
    # a stale token (the request was already expired/retried) is ignored
    token: int = 0
    blocks: Optional[list] = None
    # peer that served an empty response pending cross-check by a
    # second peer (withholding defense)
    empty_from: Optional[str] = None

    @property
    def end_slot(self) -> int:
        return self.start_slot + self.count - 1


class SyncingChain:
    """One head chain: a (target_root, target_slot) plus the peers that
    advertise it and a batch pipeline from start_slot to the target
    (range_sync/chain.rs:1306 role, reduced to its state machine)."""

    def __init__(
        self, target_root: bytes, target_slot: int, start_slot: int
    ):
        self.target_root = target_root
        self.target_slot = target_slot
        self.start_slot = start_slot
        self.peers: set[str] = set()
        self.batches: list[Batch] = []
        self.processed_through = start_slot - 1
        self.restarts = 0
        self._build_batches()

    def _build_batches(self) -> None:
        self.batches = []
        slot = self.start_slot
        while slot <= self.target_slot:
            count = min(BATCH_SLOTS, self.target_slot - slot + 1)
            self.batches.append(Batch(start_slot=slot, count=count))
            slot += count

    def restart(self, start_slot: Optional[int] = None) -> None:
        """Unknown-parent segment: our attach point was wrong; rebuild
        the whole pipeline (chain.rs restart role). The caller passes a
        FRESHLY computed common start slot — the stored one is exactly
        what a racing prune/finalization made stale, so retrying from
        it would fail identically."""
        self.restarts += 1
        if start_slot is not None:
            self.start_slot = start_slot
        self.processed_through = self.start_slot - 1
        self._build_batches()

    def downloading(self) -> list:
        return [b for b in self.batches if b.state == BatchState.DOWNLOADING]

    def next_to_download(self) -> Optional[Batch]:
        for b in self.batches:
            if b.state == BatchState.QUEUED:
                return b
        return None

    def next_to_process(self) -> Optional[Batch]:
        """Processing is strictly ordered: only the batch that starts
        where processing left off may run (chain.rs ordered import)."""
        for b in self.batches:
            if b.state in (BatchState.PROCESSED,):
                continue
            if b.state == BatchState.AWAITING_PROCESSING and (
                b.start_slot == self.processed_through + 1
            ):
                return b
            return None
        return None

    def is_complete(self) -> bool:
        return all(b.state == BatchState.PROCESSED for b in self.batches)


class SyncManager:
    def __init__(
        self,
        chain,
        processor,
        service,
        nbp,
        sampler=None,
        clock=time.monotonic,
    ):
        self.chain = chain
        self.processor = processor
        self.service = service
        self.nbp = nbp
        # optional PeerDAS sampler (network/sampling.PeerSampler):
        # sync DRIVES sampling — every imported block carrying blob
        # commitments gets its columns sampled from custody peers
        # (peer_sampling.rs:706 role, VERDICT r4 missing #5)
        self.sampler = sampler
        self._clock = clock
        self.state = SyncState.IDLE
        self._set_state_gauge(SyncState.IDLE)
        self.peer_status: dict[str, object] = {}
        self._status_at: dict[str, float] = {}
        # seconds before an unanswered batch request is declared stalled
        self.batch_timeout = 15.0
        # seconds after which a usable peer's status is re-requested
        # from tick() (status refresh keeps targets fresh after faults
        # without the driver hand-holding add_peer)
        self.status_refresh = 30.0
        # target_root -> SyncingChain
        self.chains: dict[bytes, SyncingChain] = {}
        # targets we cannot represent (UnsupportedBlockContent): never
        # recreate a chain for them — it can only fail the same way
        self._unsupported_targets: set[bytes] = set()
        self._parent_requests: dict[bytes, int] = {}  # root -> depth
        # orphans parked until their ancestor chain lands
        self._awaiting_parent: dict[bytes, list] = {}
        # backfill bookkeeping (checkpoint-synced nodes)
        self._backfill_inflight = False
        self._backfill_empty_streak = 0
        nbp.on_unknown_parent = self.on_unknown_parent

    # ------------------------------------------------------------ status

    def add_peer(self, peer_id: str) -> None:
        """Handshake: ask for the peer's chain status."""
        self.service.request(
            peer_id,
            Protocol.STATUS,
            Status.serialize(self.nbp.local_status()),
            self._on_status,
        )

    def _on_status(self, peer_id: str, code, chunks) -> None:
        if code != ResponseCode.SUCCESS or not chunks:
            return
        status = Status.deserialize(chunks[0])
        self.peer_status[peer_id] = status
        self._status_at[peer_id] = self._clock()
        info = self.service.peers.peers.get(peer_id)
        if info is not None:
            info.chain_status = status
        self._classify_peer(peer_id, status)

    def _classify_peer(self, peer_id: str, status) -> None:
        """Range-sync peer classification (range.rs add_peer): a peer
        whose head we already hold needs no chain; otherwise it joins
        (or creates) the chain for its advertised (root, slot) target."""
        target_root = bytes(status.head_root)
        target_slot = int(status.head_slot)
        # a peer advertises exactly ONE head at a time: drop it from any
        # chain it previously supported, so an honest peer that reorged
        # or advanced isn't later blamed (target_not_served) for a
        # target it no longer claims
        for root, sc in self.chains.items():
            if root != target_root:
                sc.peers.discard(peer_id)
        if target_root in self._unsupported_targets:
            return
        if self.chain.fork_choice.contains_block(target_root):
            return  # their head is already ours (or a known fork)
        if target_slot <= self._finalized_slot():
            # a head at/below our finalized slot that we don't hold is
            # on a finality-incompatible chain — unsyncable, not a gap
            return
        start_slot = self._common_start_slot()
        if target_slot < start_slot:
            # nothing to request: their head is below our common start
            # (a lagging peer while we're checkpoint-anchored). An empty
            # pipeline would be vacuously 'complete' and blame the peer
            # for a target nobody ever requested
            return
        sc = self.chains.get(target_root)
        if sc is None:
            sc = SyncingChain(target_root, target_slot, start_slot)
            self.chains[target_root] = sc
            _SYNC_CHAINS.set(len(self.chains))
        sc.peers.add(peer_id)

    def target_slot(self) -> int:
        """Highest slot sync is working toward: the furthest live chain
        target, or the local head when in sync (the /eth/v1/node/syncing
        `sync_distance` source, http_api.node_syncing)."""
        local = int(self.chain.head.slot)
        targets = [sc.target_slot for sc in self.chains.values()]
        return max([local] + targets)

    def _finalized_slot(self) -> int:
        fin_epoch, _ = self.chain.fork_choice.finalized_checkpoint
        return int(fin_epoch) * self.chain.spec.preset.slots_per_epoch

    def _common_start_slot(self) -> int:
        """First slot to request: just past the last point guaranteed
        shared with any honest peer — the finalized boundary — clamped
        to the checkpoint anchor for checkpoint-synced nodes (history
        below the anchor is backfill's job, not range sync's). Starting
        at the local HEAD is the bug this replaces: after a fork the
        served blocks don't attach and the peer takes the blame."""
        anchor = int(getattr(self.chain, "oldest_block_slot", 0) or 0)
        return max(self._finalized_slot(), anchor) + 1

    # ------------------------------------------------------------ range sync

    def tick(self) -> None:
        """Drive sync: expire stalled downloads, retire finished
        chains, pick the next chain (most-peers arbitration), keep its
        download pipeline full, and fall back to genesis-ward backfill
        when idle (backfill_sync/mod.rs: lower priority than the head)."""
        now = self._clock()
        self._expire_stalled(now)
        self._refresh_stale_statuses(now)
        self._retire_chains()
        chain = self._select_chain()
        if chain is None:
            if self.chains:
                self._set_state_gauge(SyncState.STALLED)
            else:
                self._set_state_gauge(SyncState.IDLE)
            # backfill must not starve behind unserveable head chains:
            # any usable peer covering old slots can serve it even
            # while every head target is stalled
            self._tick_backfill()
            return
        self._set_state_gauge(SyncState.RANGE)
        self._drive_chain(chain)

    def _set_state_gauge(self, state: SyncState) -> None:
        self.state = state
        for s in SyncState:
            _SYNC_STATE.labels(state=s.value).set(
                1.0 if s is state else 0.0
            )

    def _expire_stalled(self, now: float) -> None:
        """A peer that accepted a batch request and never answered must
        not wedge the chain: past batch_timeout the download is failed,
        the silent peer penalized, and the batch re-queued (the
        reference's RPC timeout feeding batch retry)."""
        for sc in list(self.chains.values()):
            for b in sc.downloading():
                if now - b.issued_at < self.batch_timeout:
                    continue
                _SYNC_BATCHES.labels(result="timeout").inc()
                self._penalize(b.peer, PeerAction.MID_TOLERANCE, "stall")
                b.token += 1  # a late response is no longer welcome
                self._fail_download(sc, b, b.peer)

    def _refresh_stale_statuses(self, now: float) -> None:
        """Statuses age out: re-handshake the stalest usable peer so
        new targets surface without the driver calling add_peer (the
        reference re-statuses peers on a timer)."""
        stalest, stalest_at = None, now - self.status_refresh
        for peer in self.service.peers.connected():
            at = self._status_at.get(peer, 0.0)
            if at <= stalest_at:
                stalest, stalest_at = peer, at
        if stalest is not None:
            self._status_at[stalest] = now  # debounce until reply
            self.add_peer(stalest)

    def _retire_chains(self) -> None:
        """Drop chains that finished or lost their purpose."""
        book = self.service.peers.peers
        for root, sc in list(self.chains.items()):
            # supporters the book banned or forgot are never coming
            # back — drop them (score-DISCONNECTED peers may decay back
            # in, so their chains stay, observably STALLED). A chain
            # with no supporters left has nobody to sync from or to
            # blame: GC it, or it pins sync_state=stalled forever
            sc.peers = {
                p
                for p in sc.peers
                if p in book and book[p].status != PeerStatus.BANNED
            }
            if not sc.peers:
                del self.chains[root]
                continue
            done = self.chain.fork_choice.contains_block(root)
            exhausted = sc.is_complete()
            if exhausted and not done:
                # every batch processed yet the advertised target never
                # appeared: the chain's peers advertised a head they
                # could not serve
                for peer in sc.peers:
                    self._penalize(
                        peer, PeerAction.MID_TOLERANCE, "target_not_served"
                    )
                _SYNC_BATCHES.labels(result="target_not_served").inc()
            if done or exhausted:
                del self.chains[root]
        _SYNC_CHAINS.set(len(self.chains))

    def _select_chain(self) -> Optional[SyncingChain]:
        """Chain arbitration. NOT "highest head slot wins": the chain
        with the most supporting peers syncs first (range.rs selection
        — peer count is the stake-weight proxy sync can see), target
        slot only breaks ties. The actual HEAD decision happens in fork
        choice as segments import; a synced chain that loses the weight
        race simply never becomes head."""
        best, best_key = None, None
        for sc in self.chains.values():
            usable = [
                p for p in sc.peers if self.service.peers.is_usable(p)
            ]
            if not usable:
                continue
            key = (len(usable), sc.target_slot)
            if best_key is None or key > best_key:
                best, best_key = sc, key
        return best

    def _drive_chain(self, sc: SyncingChain) -> None:
        """Keep the pipeline full: issue downloads up to the in-flight
        cap, process the next in-order downloaded batch."""
        if self.chains.get(sc.target_root) is not sc:
            return  # chain was retired/failed while a callback ran
        self._process_ready(sc)
        while len(sc.downloading()) < MAX_INFLIGHT_PER_CHAIN:
            if self.chains.get(sc.target_root) is not sc:
                return
            batch = sc.next_to_download()
            if batch is None:
                break
            if not self._issue_batch(sc, batch):
                break

    def _batch_peer(self, sc: SyncingChain, batch: Batch) -> Optional[str]:
        """Best usable peer of this chain that hasn't failed this batch
        (batch.rs retry: never the same peer twice for one batch)."""
        for peer in self.service.peers.best_peers():
            if peer in sc.peers and peer not in batch.tried:
                return peer
        return None

    def _issue_batch(self, sc: SyncingChain, batch: Batch) -> bool:
        if batch.attempts >= MAX_BATCH_ATTEMPTS:
            batch.state = BatchState.FAILED
            self._fail_chain(sc, "retries_exhausted")
            return False
        peer = self._batch_peer(sc, batch)
        if peer is None:
            return False
        batch.state = BatchState.DOWNLOADING
        batch.peer = peer
        batch.attempts += 1
        batch.issued_at = self._clock()
        batch.token += 1
        token = batch.token
        req = BlocksByRangeRequest.make(
            start_slot=batch.start_slot, count=batch.count, step=1
        )
        self.service.request(
            peer,
            Protocol.BLOCKS_BY_RANGE,
            BlocksByRangeRequest.serialize(req),
            lambda p, c, ch: self._on_batch_response(
                sc, batch, token, p, c, ch
            ),
        )
        return True

    def _fail_download(self, sc: SyncingChain, batch: Batch, peer) -> None:
        """One download attempt failed: back to QUEUED for the next
        peer, or fail the chain once attempts are exhausted."""
        if peer is not None:
            batch.tried.add(peer)
        batch.state = BatchState.QUEUED
        batch.peer = None
        batch.blocks = None
        if batch.attempts >= MAX_BATCH_ATTEMPTS:
            batch.state = BatchState.FAILED
            self._fail_chain(sc, "retries_exhausted")
            return
        # re-issue immediately (don't wait a tick): the reference's
        # retry fires from the failure handler
        self._drive_chain(sc)

    def _fail_chain(self, sc: SyncingChain, reason: str) -> None:
        if self.chains.get(sc.target_root) is not sc:
            return  # already retired — don't double-count
        _SYNC_BATCHES.labels(result=f"chain_{reason}").inc()
        del self.chains[sc.target_root]
        _SYNC_CHAINS.set(len(self.chains))

    def _on_batch_response(
        self, sc: SyncingChain, batch: Batch, token: int, peer_id, code, chunks
    ) -> None:
        if (
            batch.token != token
            or batch.state != BatchState.DOWNLOADING
            or self.chains.get(sc.target_root) is not sc
            or not any(b is batch for b in sc.batches)
        ):
            return  # stale: batch expired/retried, chain gone/restarted
        if code != ResponseCode.SUCCESS:
            _SYNC_BATCHES.labels(result="rpc_error").inc()
            self._penalize(peer_id, PeerAction.MID_TOLERANCE, "rpc_error")
            self._fail_download(sc, batch, peer_id)
            return
        blocks = []
        for raw in chunks:
            try:
                blocks.append(decode_block_response(self.chain.spec, raw))
            except UnsupportedBlockContent:
                # OUR representational limit, not the peer's fault: the
                # whole target is undecodable for us — park it forever
                self._unsupported_targets.add(sc.target_root)
                self._fail_chain(sc, "unsupported")
                return
            except Exception:
                _SYNC_BATCHES.labels(result="decode_error").inc()
                self._penalize(
                    peer_id, PeerAction.LOW_TOLERANCE, "decode_error"
                )
                self._fail_download(sc, batch, peer_id)
                return
        if blocks:
            slots = [int(b.message.slot) for b in blocks]
            if slots != sorted(slots) or (
                slots[0] < batch.start_slot or slots[-1] > batch.end_slot
            ):
                # blocks outside the requested window (or out of order):
                # an already-imported stale block would otherwise sail
                # through the imported-prefix skip and mark the whole
                # batch PROCESSED with zero actual progress
                _SYNC_BATCHES.labels(result="bad_range").inc()
                self._penalize(peer_id, PeerAction.LOW_TOLERANCE, "bad_range")
                self._fail_download(sc, batch, peer_id)
                return
        if not blocks:
            # withholding defense: accept an empty batch as a skipped-
            # slot run only once a SECOND peer confirms it (or nobody
            # else can be asked)
            batch.tried.add(peer_id)
            if batch.empty_from is None and self._batch_peer(sc, batch):
                batch.empty_from = peer_id
                batch.state = BatchState.QUEUED
                batch.peer = None
                self._drive_chain(sc)
                return
            _SYNC_BATCHES.labels(result="empty").inc()
            batch.state = BatchState.AWAITING_PROCESSING
            batch.blocks = []
            self._drive_chain(sc)
            return
        # batch.empty_from stays set: the first peer claimed this range
        # was empty and this peer served blocks — judgment waits until
        # the blocks PROVE importable, so an attacker can't frame an
        # honest empty-server by fabricating decodable garbage
        batch.state = BatchState.AWAITING_PROCESSING
        batch.blocks = blocks
        self._drive_chain(sc)

    def _process_ready(self, sc: SyncingChain) -> None:
        batch = sc.next_to_process()
        if batch is None:
            return
        batch.state = BatchState.PROCESSING
        blocks = batch.blocks or []
        peer_id = batch.peer

        def process(_payload) -> None:
            if self.chains.get(sc.target_root) is not sc or not any(
                b is batch for b in sc.batches
            ):
                return  # chain retired/restarted while queued
            if not blocks:
                self._after_empty(sc, batch)
                return
            with tracing.span("sync:segment", slot=batch.start_slot):
                try:
                    imported = self.chain.process_chain_segment(blocks)
                except SegmentError as e:
                    self._on_segment_error(sc, batch, peer_id, e)
                    return
                except BlockError:
                    self._on_segment_error(
                        sc, batch, peer_id, SegmentError("invalid_block", "")
                    )
                    return
            tip_root = blocks[-1].message.hash_tree_root()
            if not imported and not self.chain.fork_choice.contains_block(
                tip_root
            ):
                # NOTHING above the already-imported prefix landed: the
                # served batch was not importable. (A partial import —
                # e.g. truncated at a data-availability gate — is
                # progress, not the peer's fault: accept it; the
                # chain-completion target check catches a tail that
                # never arrives.)
                _SYNC_BATCHES.labels(result="unimportable").inc()
                self._penalize(
                    peer_id, PeerAction.MID_TOLERANCE, "unimportable"
                )
                self._fail_download(sc, batch, peer_id)
                return
            _SYNC_BATCHES.labels(result="processed").inc()
            batch.state = BatchState.PROCESSED
            batch.blocks = None
            sc.processed_through = batch.end_slot
            if batch.empty_from is not None:
                # the range provably held importable blocks the first
                # peer withheld while claiming it empty
                self._penalize(
                    batch.empty_from, PeerAction.MID_TOLERANCE, "withheld"
                )
                batch.empty_from = None
            if imported:
                self.service.report_peer(peer_id, PeerAction.VALUABLE)
                self.maybe_sample(blocks)
            self._drive_chain(sc)

        def shed(_w, reason) -> None:
            if batch.state is not BatchState.PROCESSING:
                # the handler already advanced the batch's state
                # machine before failing terminally — don't rewind it
                return
            if reason == "failed":
                # the handler RAN and raised on every allowed attempt
                # (blocks possibly part-consumed): blame the download
                # like any unprocessable batch — bounded re-download
                # from another peer — instead of re-submitting the
                # same closure forever
                self._fail_download(sc, batch, peer_id)
                return
            # never ran (backpressure past the attempt caps): put the
            # batch back to AWAITING_PROCESSING (blocks still in hand)
            # so the next tick retries — no timeout covers PROCESSING,
            # so leaving it there would wedge the chain forever
            batch.state = BatchState.AWAITING_PROCESSING

        # chain segments take the HIGHEST priority lane (lib.rs:1037);
        # transient backpressure bounces inside the scheduler
        # (bounded retry-with-requeue), so no hand-rolled re-queue here
        self.processor.submit(
            Work(
                kind=WorkType.CHAIN_SEGMENT,
                process_individual=process,
                slot=batch.start_slot,
                on_shed=shed,
            )
        )

    def _after_empty(self, sc: SyncingChain, batch: Batch) -> None:
        """A confirmed-empty batch: a genuine run of skipped slots."""
        batch.state = BatchState.PROCESSED
        batch.empty_from = None  # both peers agreed — nobody withheld
        sc.processed_through = batch.end_slot
        self._drive_chain(sc)

    def _on_segment_error(
        self, sc: SyncingChain, batch: Batch, peer_id, e: SegmentError
    ) -> None:
        reason = getattr(e, "reason", "invalid_block")
        if reason == "unknown_parent":
            # OUR attach point was wrong — the serving peer did nothing
            # wrong: restart the chain once, drop it if that repeats
            _SYNC_BATCHES.labels(result="unknown_parent").inc()
            if sc.restarts >= MAX_CHAIN_RESTARTS:
                self._fail_chain(sc, "unattachable")
                return
            sc.restart(self._common_start_slot())
            self._drive_chain(sc)
            return
        if reason == "unsupported":
            self._unsupported_targets.add(sc.target_root)
            self._fail_chain(sc, "unsupported")
            return
        # not_linked / invalid_block: the peer assembled or served a
        # consensus-invalid batch
        _SYNC_BATCHES.labels(result=reason).inc()
        self._penalize(peer_id, PeerAction.LOW_TOLERANCE, reason)
        self._fail_download(sc, batch, peer_id)

    def _penalize(self, peer_id, action: PeerAction, reason: str) -> None:
        if peer_id is None:
            return
        _SYNC_PENALTIES.labels(reason=reason).inc()
        self.service.report_peer(peer_id, action)

    # ------------------------------------------------------------ backfill

    def _tick_backfill(self) -> None:
        oldest = getattr(self.chain, "oldest_block_slot", 0)
        if oldest <= 0 or self._backfill_inflight:
            return
        peer = self._any_peer_serving(oldest)
        if peer is None:
            return
        # consecutive empty responses WIDEN the window (a run of skipped
        # slots longer than one batch must not livelock re-requesting
        # the same empty range) until it reaches genesis
        width = BATCH_SLOTS * (1 + self._backfill_empty_streak)
        start = max(0, oldest - width)
        count = oldest - start
        # in flight until the response is fully PROCESSED — clearing at
        # receipt would let a tick issue a duplicate request whose batch
        # no longer links after the first one lands
        self._backfill_inflight = True
        req = BlocksByRangeRequest.make(start_slot=start, count=count, step=1)
        self.service.request(
            peer,
            Protocol.BLOCKS_BY_RANGE,
            BlocksByRangeRequest.serialize(req),
            lambda p, c, ch: self._on_backfill_batch(p, c, ch, start),
        )

    def _any_peer_serving(self, slot: int) -> Optional[str]:
        """Best usable peer whose advertised head covers `slot`."""
        for peer in self.service.peers.best_peers():
            status = self.peer_status.get(peer)
            if status is not None and int(status.head_slot) >= slot:
                return peer
        return None

    def _on_backfill_batch(self, peer_id: str, code, chunks, start: int) -> None:
        if code != ResponseCode.SUCCESS:
            self._backfill_inflight = False
            self._penalize(peer_id, PeerAction.MID_TOLERANCE, "rpc_error")
            return
        blocks = []
        for raw in chunks:
            try:
                blocks.append(decode_block_response(self.chain.spec, raw))
            except UnsupportedBlockContent:
                # OUR representational limit, not the peer's fault
                self._backfill_inflight = False
                return
            except Exception:
                self._backfill_inflight = False
                self._penalize(
                    peer_id, PeerAction.LOW_TOLERANCE, "decode_error"
                )
                return

        def process(_payload) -> None:
            try:
                try:
                    stored = self.chain.backfill_blocks(blocks)
                except BlockError:
                    self._penalize(
                        peer_id, PeerAction.LOW_TOLERANCE, "invalid_block"
                    )
                    return
                if stored:
                    self._backfill_empty_streak = 0
                    self.service.report_peer(peer_id, PeerAction.VALUABLE)
                    return
                # empty response: only the window that REACHES genesis
                # may conclude backfill — anything else is either a
                # skipped-slot run (widen) or a withholding peer
                # (mild penalty + implicit peer rotation via scoring)
                if start == 0:
                    self.chain.oldest_block_slot = 0
                else:
                    self._backfill_empty_streak += 1
                    self._penalize(
                        peer_id, PeerAction.HIGH_TOLERANCE, "backfill_empty"
                    )
            finally:
                self._backfill_inflight = False

        def shed(_w, _reason) -> None:
            # terminal shed: the callback never clears the in-flight
            # flag, so clear it here or backfill halts permanently
            self._backfill_inflight = False

        # backfill takes the LOWEST priority lane (lib.rs:1037 ordering)
        self.processor.submit(
            Work(
                kind=WorkType.CHAIN_SEGMENT_BACKFILL,
                process_individual=process,
                on_shed=shed,
            )
        )

    # ------------------------------------------------------------ sampling

    def maybe_sample(self, blocks) -> int:
        """Start column sampling for imported blocks that carry blob
        commitments; returns sampling requests started."""
        if self.sampler is None:
            return 0
        n = 0
        peers = self.service.peers.connected()
        for block in blocks:
            if not len(block.message.body.blob_kzg_commitments):
                continue
            root = block.message.hash_tree_root()
            if root in self.sampler.active:
                continue
            self.sampler.start(root, peers)
            n += 1
        return n

    # ------------------------------------------------------------ lookups

    def on_unknown_parent(
        self, peer_id: str, parent_root: bytes, child=None, depth: int = 0
    ) -> None:
        """Gossip block with unknown parent: park the child and fetch
        the ancestor chain from the serving peer (single-block lookup
        role; the child re-imports once its parent lands). `depth`
        carries the length of the ancestor WALK — each hop increments it
        so a fabricated deep chain stops at MAX_PARENT_DEPTH instead of
        driving unbounded lookups + parked-block memory growth."""
        if depth >= MAX_PARENT_DEPTH or len(self._awaiting_parent) >= 4 * MAX_PARENT_DEPTH:
            self._penalize(peer_id, PeerAction.MID_TOLERANCE, "deep_lookup")
            return
        if child is not None:
            self._awaiting_parent.setdefault(parent_root, []).append(child)
        if parent_root in self._parent_requests:
            return  # lookup already in flight for this ancestor
        self._parent_requests[parent_root] = depth
        _SYNC_LOOKUPS.labels(result="started").inc()
        self._request_lookup(peer_id, parent_root, depth, tried=set())

    def _request_lookup(
        self, peer_id: str, parent_root: bytes, depth: int, tried: set
    ) -> None:
        self.service.request(
            peer_id,
            Protocol.BLOCKS_BY_ROOT,
            parent_root,
            lambda p, c, ch: self._on_lookup(
                p, c, ch, parent_root, depth, tried
            ),
        )

    def _on_lookup(
        self, peer_id: str, code, chunks, parent_root: bytes, depth: int,
        tried: set,
    ) -> None:
        if code != ResponseCode.SUCCESS or not chunks:
            # the lookup FAILED: release the request slot (leaving it
            # would permanently block any future lookup for this
            # ancestor and strand its parked children) and retry once
            # per remaining peer before giving up
            tried.add(peer_id)
            if code == ResponseCode.SUCCESS:
                self._penalize(
                    peer_id, PeerAction.HIGH_TOLERANCE, "lookup_empty"
                )
            retry = self._lookup_retry_peer(tried)
            if retry is not None:
                self._request_lookup(retry, parent_root, depth, tried)
                return
            self._abandon_lookup(parent_root)
            return
        try:
            block = decode_block_response(self.chain.spec, chunks[0])
        except UnsupportedBlockContent:
            # OUR representational limit, not the peer's fault
            self._abandon_lookup(parent_root)
            return
        except Exception:
            self._penalize(peer_id, PeerAction.LOW_TOLERANCE, "decode_error")
            tried.add(peer_id)
            retry = self._lookup_retry_peer(tried)
            if retry is not None:
                self._request_lookup(retry, parent_root, depth, tried)
                return
            self._abandon_lookup(parent_root)
            return

        def process(_payload) -> None:
            # release by REQUESTED root too: a peer serving a different
            # block than asked must not pin the request slot forever
            self._parent_requests.pop(parent_root, None)
            self._parent_requests.pop(block.message.hash_tree_root(), None)
            with tracing.span(
                "sync:lookup", slot=int(block.message.slot)
            ):
                try:
                    root = self.chain.process_block(block)
                except BlockError as e:
                    if "unknown parent" in str(e):
                        self.on_unknown_parent(
                            peer_id,
                            bytes(block.message.parent_root),
                            block,
                            depth + 1,
                        )
                    else:
                        _SYNC_LOOKUPS.labels(result="invalid").inc()
                        # an invalid ancestor damns its descendants:
                        # drop the parked children rather than strand
                        # them against the _awaiting_parent cap
                        self._abandon_lookup(parent_root)
                    return
            _SYNC_LOOKUPS.labels(result="imported").inc()
            self.maybe_sample([block])
            self._release_children(peer_id, root)

        self.processor.submit(
            Work(
                kind=WorkType.RPC_BLOCK,
                process_individual=process,
                # terminal shed: the callback will never run — release
                # the slot + children or the lookup path wedges forever
                on_shed=lambda _w, _r: self._abandon_lookup(parent_root),
            )
        )

    def _abandon_lookup(self, parent_root: bytes) -> None:
        """Terminal lookup failure: release the request slot AND the
        parked SUBTREE — a dropped child may itself be a parked parent
        (multi-hop walks park intermediate ancestors), and stranding
        any of it permanently eats into the lookup caps (the leak
        class satellite 1 exists to kill)."""
        self._parent_requests.pop(parent_root, None)
        count = 1
        stack = self._awaiting_parent.pop(parent_root, [])
        while stack:
            child = stack.pop()
            count += 1
            stack.extend(
                self._awaiting_parent.pop(
                    child.message.hash_tree_root(), []
                )
            )
        _SYNC_LOOKUPS.labels(result="failed").inc(count)

    def _lookup_retry_peer(self, tried: set) -> Optional[str]:
        for peer in self.service.peers.best_peers():
            if peer not in tried:
                return peer
        return None

    def _release_children(self, peer_id: str, parent_root: bytes) -> None:
        """An ancestor landed: re-import every orphan that was waiting
        on it (recursively — a whole parked chain unwinds). A child
        whose parent import RACED (unknown parent again — e.g. the
        parent was pruned between lookup and release) re-enters the
        lookup path instead of being dropped."""
        for child in self._awaiting_parent.pop(parent_root, []):
            try:
                child_root = self.chain.process_block(child)
            except BlockError as e:
                if "unknown parent" in str(e):
                    _SYNC_LOOKUPS.labels(result="requeued").inc()
                    self.on_unknown_parent(
                        peer_id, bytes(child.message.parent_root), child
                    )
                continue
            _SYNC_LOOKUPS.labels(result="released").inc()
            self._release_children(peer_id, child_root)
