"""SyncManager: range sync + single-block lookups
(network/src/sync/manager.rs:224, range_sync/chain.rs, block_lookups/).

Reduced to the reference's load-bearing structure:
  - Status handshake discovers how far ahead a peer's finalized/head
    chain is (range.rs peer classification).
  - Range sync requests fixed-size slot batches (batch.rs:563 role)
    from the best peer and imports each response as ONE chain segment —
    the whole-segment signature batch is the TPU-relevant property
    (signature_verify_chain_segment, block_verification.rs:599).
  - Failed batches penalize the serving peer and retry from the next
    best (batch retry/penalization, range_sync/batch.rs).
  - Unknown-parent gossip blocks trigger a BlocksByRoot lookup walking
    back to a known ancestor (block_lookups/ role).

The manager is synchronous and event-driven (`tick()` + callbacks), so
sync policy is unit-testable without a runtime; the node's loop drives
it alongside NetworkService.poll().
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from ..consensus import types as T
from ..consensus.forked_types import UnsupportedBlockContent
from ..node.beacon_chain import BlockError
from ..node.beacon_processor import Work, WorkType
from .peer_manager import PeerAction
from .rpc import BlocksByRangeRequest, Protocol, ResponseCode, Status


def decode_block_response(spec, raw: bytes):
    """Decode a SignedBeaconBlock RPC chunk: the framework's native
    union encoding first, then the fork-dispatched SPEC-EXACT decode
    (consensus/forked_types.decode_signed_block) so blocks served by an
    externally-implemented peer ingest too (beacon_block.rs superstruct
    decode role). Raises ValueError when neither parses."""
    try:
        return T.SignedBeaconBlock.deserialize(raw)
    except Exception:
        from ..consensus import forked_types as FT

        return FT.decode_signed_block(spec, raw)

BATCH_SLOTS = 64  # EPOCHS_PER_BATCH * 32 in the reference
MAX_PARENT_DEPTH = 32  # block_lookups parent-chain length cap
# batch retry economics (range_sync/batch.rs MAX_BATCH_DOWNLOAD_ATTEMPTS
# role): a failed batch retries against peers that haven't failed it
# yet; after this many attempts the batch is abandoned and the target
# re-evaluated (the failing chain may simply be gone)
MAX_BATCH_ATTEMPTS = 5


class SyncState(Enum):
    IDLE = "idle"  # in sync (or no better peer known)
    RANGE = "range"  # catching up a long gap
    STALLED = "stalled"  # no usable peer serves the target


@dataclass
class _PendingBatch:
    start_slot: int
    count: int
    peer: str
    attempts: int = 1
    tried: set = field(default_factory=set)


class SyncManager:
    def __init__(self, chain, processor, service, nbp, sampler=None):
        self.chain = chain
        self.processor = processor
        self.service = service
        self.nbp = nbp
        # optional PeerDAS sampler (network/sampling.PeerSampler):
        # sync DRIVES sampling — every imported block carrying blob
        # commitments gets its columns sampled from custody peers
        # (peer_sampling.rs:706 role, VERDICT r4 missing #5)
        self.sampler = sampler
        self.state = SyncState.IDLE
        self.peer_status: dict[str, object] = {}
        self._pending: Optional[_PendingBatch] = None
        self._parent_requests: dict[bytes, int] = {}  # root -> depth
        # orphans parked until their ancestor chain lands
        self._awaiting_parent: dict[bytes, list] = {}
        # backfill bookkeeping (checkpoint-synced nodes)
        self._backfill_inflight = False
        self._backfill_empty_streak = 0
        nbp.on_unknown_parent = self.on_unknown_parent

    # ------------------------------------------------------------ status

    def add_peer(self, peer_id: str) -> None:
        """Handshake: ask for the peer's chain status."""
        self.service.request(
            peer_id,
            Protocol.STATUS,
            Status.serialize(self.nbp.local_status()),
            self._on_status,
        )

    def _on_status(self, peer_id: str, code, chunks) -> None:
        if code != ResponseCode.SUCCESS or not chunks:
            return
        status = Status.deserialize(chunks[0])
        self.peer_status[peer_id] = status
        info = self.service.peers.peers.get(peer_id)
        if info is not None:
            info.chain_status = status

    # ------------------------------------------------------------ range sync

    def target_slot(self) -> int:
        """Highest head slot any usable peer advertises."""
        best = self.chain.head.slot
        for peer, status in self.peer_status.items():
            if self.service.peers.is_usable(peer):
                best = max(best, int(status.head_slot))
        return best

    def tick(self) -> None:
        """Drive sync: issue the next batch request if behind and no
        request is in flight. When caught up forward, backfill history
        genesis-ward (backfill_sync/mod.rs: runs after checkpoint sync,
        at lower priority than staying at the head)."""
        if self._pending is not None:
            return
        target = self.target_slot()
        local = self.chain.head.slot
        if target <= local:
            self.state = SyncState.IDLE
            self._tick_backfill()
            return
        peer = self._best_peer_for(local + 1)
        if peer is None:
            self.state = SyncState.STALLED
            return
        self.state = SyncState.RANGE
        count = min(BATCH_SLOTS, target - local)
        self._pending = _PendingBatch(
            start_slot=local + 1, count=count, peer=peer
        )
        req = BlocksByRangeRequest.make(
            start_slot=local + 1, count=count, step=1
        )
        self.service.request(
            peer,
            Protocol.BLOCKS_BY_RANGE,
            BlocksByRangeRequest.serialize(req),
            self._on_batch,
        )

    def _tick_backfill(self) -> None:
        oldest = getattr(self.chain, "oldest_block_slot", 0)
        if oldest <= 0 or self._backfill_inflight:
            return
        peer = self._best_peer_for(oldest)
        if peer is None:
            return
        # consecutive empty responses WIDEN the window (a run of skipped
        # slots longer than one batch must not livelock re-requesting
        # the same empty range) until it reaches genesis
        width = BATCH_SLOTS * (1 + self._backfill_empty_streak)
        start = max(0, oldest - width)
        count = oldest - start
        # in flight until the response is fully PROCESSED — clearing at
        # receipt would let a tick issue a duplicate request whose batch
        # no longer links after the first one lands
        self._backfill_inflight = True
        req = BlocksByRangeRequest.make(start_slot=start, count=count, step=1)
        self.service.request(
            peer,
            Protocol.BLOCKS_BY_RANGE,
            BlocksByRangeRequest.serialize(req),
            lambda p, c, ch: self._on_backfill_batch(p, c, ch, start),
        )

    def _on_backfill_batch(self, peer_id: str, code, chunks, start: int) -> None:
        if code != ResponseCode.SUCCESS:
            self._backfill_inflight = False
            self.service.report_peer(peer_id, PeerAction.MID_TOLERANCE)
            return
        blocks = []
        for raw in chunks:
            try:
                blocks.append(decode_block_response(self.chain.spec, raw))
            except UnsupportedBlockContent:
                # OUR representational limit, not the peer's fault
                self._backfill_inflight = False
                return
            except Exception:
                self._backfill_inflight = False
                self.service.report_peer(peer_id, PeerAction.LOW_TOLERANCE)
                return

        def process(_payload) -> None:
            try:
                try:
                    stored = self.chain.backfill_blocks(blocks)
                except BlockError:
                    self.service.report_peer(peer_id, PeerAction.LOW_TOLERANCE)
                    return
                if stored:
                    self._backfill_empty_streak = 0
                    self.service.report_peer(peer_id, PeerAction.VALUABLE)
                    return
                # empty response: only the window that REACHES genesis
                # may conclude backfill — anything else is either a
                # skipped-slot run (widen) or a withholding peer
                # (mild penalty + implicit peer rotation via scoring)
                if start == 0:
                    self.chain.oldest_block_slot = 0
                else:
                    self._backfill_empty_streak += 1
                    self.service.report_peer(
                        peer_id, PeerAction.HIGH_TOLERANCE
                    )
            finally:
                self._backfill_inflight = False

        # backfill takes the LOWEST priority lane (lib.rs:1037 ordering)
        self.processor.submit(
            Work(
                kind=WorkType.CHAIN_SEGMENT_BACKFILL,
                process_individual=process,
            )
        )

    def _best_peer_for(self, slot: int, exclude: set = ()) -> Optional[str]:
        for peer in self.service.peers.best_peers():
            if peer in exclude:
                continue
            status = self.peer_status.get(peer)
            if status is not None and int(status.head_slot) >= slot:
                return peer
        return None

    def maybe_sample(self, blocks) -> int:
        """Start column sampling for imported blocks that carry blob
        commitments; returns sampling requests started."""
        if self.sampler is None:
            return 0
        n = 0
        peers = self.service.peers.connected()
        for block in blocks:
            if not len(block.message.body.blob_kzg_commitments):
                continue
            root = block.message.hash_tree_root()
            if root in self.sampler.active:
                continue
            self.sampler.start(root, peers)
            n += 1
        return n

    def _retry_batch(self, pending: _PendingBatch, failed_peer: str) -> None:
        """Re-issue a failed batch against the next-best peer that has
        NOT failed it (batch.rs retry machinery). Exhausted attempts
        abandon the batch — the next tick re-evaluates the target."""
        pending.tried.add(failed_peer)
        if pending.attempts >= MAX_BATCH_ATTEMPTS:
            return
        if self._pending is not None:
            return  # a tick already issued a fresh batch; don't race it
        peer = self._best_peer_for(pending.start_slot, exclude=pending.tried)
        if peer is None:
            return
        pending.attempts += 1
        pending.peer = peer
        self._pending = pending
        req = BlocksByRangeRequest.make(
            start_slot=pending.start_slot, count=pending.count, step=1
        )
        self.service.request(
            peer,
            Protocol.BLOCKS_BY_RANGE,
            BlocksByRangeRequest.serialize(req),
            self._on_batch,
        )

    def _on_batch(self, peer_id: str, code, chunks) -> None:
        pending, self._pending = self._pending, None
        if code != ResponseCode.SUCCESS:
            self.service.report_peer(peer_id, PeerAction.MID_TOLERANCE)
            if pending is not None:
                self._retry_batch(pending, peer_id)
            return
        blocks = []
        for raw in chunks:
            try:
                blocks.append(decode_block_response(self.chain.spec, raw))
            except UnsupportedBlockContent:
                return  # OUR representational limit, not the peer's fault
            except Exception:
                self.service.report_peer(peer_id, PeerAction.LOW_TOLERANCE)
                if pending is not None:
                    self._retry_batch(pending, peer_id)
                return

        def process(_payload) -> None:
            try:
                imported = self.chain.process_chain_segment(blocks)
            except BlockError:
                self.service.report_peer(peer_id, PeerAction.LOW_TOLERANCE)
                if pending is not None:
                    self._retry_batch(pending, peer_id)
                return
            if blocks and not imported:
                # served a batch that contained nothing importable
                self.service.report_peer(peer_id, PeerAction.MID_TOLERANCE)
                if pending is not None:
                    self._retry_batch(pending, peer_id)
            elif imported:
                self.service.report_peer(peer_id, PeerAction.VALUABLE)
                self.maybe_sample(blocks)

        # chain segments take the HIGHEST priority lane (lib.rs:1037)
        self.processor.submit(
            Work(kind=WorkType.CHAIN_SEGMENT, process_individual=process)
        )

    # ------------------------------------------------------------ lookups

    def on_unknown_parent(
        self, peer_id: str, parent_root: bytes, child=None, depth: int = 0
    ) -> None:
        """Gossip block with unknown parent: park the child and fetch
        the ancestor chain from the serving peer (single-block lookup
        role; the child re-imports once its parent lands). `depth`
        carries the length of the ancestor WALK — each hop increments it
        so a fabricated deep chain stops at MAX_PARENT_DEPTH instead of
        driving unbounded lookups + parked-block memory growth."""
        if depth >= MAX_PARENT_DEPTH or len(self._awaiting_parent) >= 4 * MAX_PARENT_DEPTH:
            self.service.report_peer(peer_id, PeerAction.MID_TOLERANCE)
            return
        if child is not None:
            self._awaiting_parent.setdefault(parent_root, []).append(child)
        if parent_root in self._parent_requests:
            return  # lookup already in flight for this ancestor
        self._parent_requests[parent_root] = depth
        self.service.request(
            peer_id,
            Protocol.BLOCKS_BY_ROOT,
            parent_root,
            lambda p, c, ch: self._on_lookup(p, c, ch, depth),
        )

    def _on_lookup(self, peer_id: str, code, chunks, depth: int = 0) -> None:
        if code != ResponseCode.SUCCESS or not chunks:
            return
        try:
            block = decode_block_response(self.chain.spec, chunks[0])
        except UnsupportedBlockContent:
            return  # OUR representational limit, not the peer's fault
        except Exception:
            self.service.report_peer(peer_id, PeerAction.LOW_TOLERANCE)
            return

        def process(_payload) -> None:
            self._parent_requests.pop(block.message.hash_tree_root(), None)
            try:
                root = self.chain.process_block(block)
            except BlockError as e:
                if "unknown parent" in str(e):
                    self.on_unknown_parent(
                        peer_id,
                        bytes(block.message.parent_root),
                        block,
                        depth + 1,
                    )
                return
            self.maybe_sample([block])
            self._release_children(peer_id, root)

        self.processor.submit(
            Work(kind=WorkType.RPC_BLOCK, process_individual=process)
        )

    def _release_children(self, peer_id: str, parent_root: bytes) -> None:
        """An ancestor landed: re-import every orphan that was waiting
        on it (recursively — a whole parked chain unwinds)."""
        for child in self._awaiting_parent.pop(parent_root, []):
            try:
                child_root = self.chain.process_block(child)
            except BlockError:
                continue
            self._release_children(peer_id, child_root)
