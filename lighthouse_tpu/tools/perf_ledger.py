"""Persistent perf ledger (ISSUE 10 tentpole, layer 3).

One append-only trajectory (PERF.jsonl at the repo root) where every
bench round lands as a row: driver-verified rate, freshest
self-measured rate, measurement mode (device / cpu-replay / dead),
per-bucket op counts and roofline estimates, and the CPU-side numbers
(epoch stage seconds, load p99/shed, scenario convergence) that ship
tunnel up or down. `tools/perf_ledger.py` renders the table and flags
regressions between consecutive rounds; `tools/bench_gate.py` turns
the same comparison into a tier-1 exit code.

Row schema ("lighthouse-tpu/perf-ledger/v1") — all fields optional
except schema/source/recorded_at; compare only what both rows carry:

  source            where the row came from (BENCH_r03.json, bench.py)
  recorded_at       ISO-8601 UTC
  mode              "device" | "cpu_replay" | "dead" | "self_measured"
  value_sets_per_s  the round's headline number (0.0 on dead rounds)
  device            device string if a chip answered
  marginal_sets_per_s, batch_sets_per_s
  replay            {bucket, sets_per_s, checked}   (cpu replay rounds)
  kernel            {bucket: {fp_muls_per_set, elem_ops_per_set,
                    roofline_est_sets_per_s}}
  hash              {scenario: sha256 compressions} (ISSUE 11 census:
                    steady_slot / epoch_boundary / block_import /
                    cold_root @250k validators — exact counts)
  hash_wall_s       {scenario: measured hash seconds} (ISSUE 15: host
                    + batched-kernel wall per scenario; boundary and
                    import gate round-over-round)
  hash_device_wall_s {scenario: batched-kernel-only seconds}
  epoch_warm_s      {"250k": s, "500k": s}
  bounds            {certified_sites, min_headroom_bits,
                    trimmed_passes_per_mul, certificate_ok} (ISSUE 14
                    limb-bounds certificates: int32 headroom must
                    never decay below the 2-bit slack floor)
  load              {duty_p99_s, shed_rate, deadline_miss_rate}
  suite             {fast_tier_pred_s, fast_tier_wall_s, truncated}
                    (ISSUE 16 suite cost observatory: the census-
                    predicted tier-1 fast-tier wall, the last measured
                    one, and whether that census was SIGTERM-truncated
                    — the correctness gate's own cost rides the same
                    ratchet as epoch seconds)
  scenarios_pass    bool
  artifacts         export-artifact inventory summary
  note              free text
"""

from __future__ import annotations

import json
import os
import time

SCHEMA = "lighthouse-tpu/perf-ledger/v1"


def default_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "PERF.jsonl")


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def rows(path: str | None = None) -> list:
    path = path or default_path()
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    continue
                if isinstance(doc, dict) and doc.get("schema") == SCHEMA:
                    out.append(doc)
    except OSError:
        pass
    return out


def append(row: dict, path: str | None = None) -> bool:
    """Append one row (stamps schema + recorded_at if missing).
    Dedupes ONLY a row whose entire content (minus the timestamp)
    matches the last row — re-projecting the same BENCH artifact twice
    is a duplicate; two live rounds that merely measured the same
    headline rate are distinct events (their epoch/load/census
    sections differ) and both belong in the trajectory."""
    path = path or default_path()
    row = dict(row)
    row.setdefault("schema", SCHEMA)
    row.setdefault("recorded_at", now_iso())
    prior = rows(path)
    if prior:
        def _key(r):
            return json.dumps(
                {k: v for k, v in r.items() if k != "recorded_at"},
                sort_keys=True,
            )

        if _key(prior[-1]) == _key(row):
            return False
    with open(path, "a") as f:
        f.write(json.dumps(row, sort_keys=True) + "\n")
    return True


def row_from_bench(doc: dict, source: str = "bench.py") -> dict:
    """Project a bench.py JSON line into a ledger row."""
    detail = doc.get("detail", {}) or {}
    row = {
        "schema": SCHEMA,
        "source": source,
        "recorded_at": now_iso(),
        "value_sets_per_s": float(doc.get("value") or 0.0),
    }
    if detail.get("device"):
        row["mode"] = "device"
        row["device"] = detail["device"]
    elif detail.get("replay", {}).get("sets_per_s"):
        row["mode"] = "cpu_replay"
    else:
        row["mode"] = "dead"
    c1 = detail.get("config1_raw_batch") or {}
    if isinstance(c1, dict):
        if c1.get("sets_per_s"):
            row["batch_sets_per_s"] = c1["sets_per_s"]
        if c1.get("marginal_sets_per_s"):
            row["marginal_sets_per_s"] = c1["marginal_sets_per_s"]
    rep = detail.get("replay")
    if isinstance(rep, dict) and rep.get("sets_per_s"):
        row["replay"] = {
            k: rep.get(k) for k in ("bucket", "sets_per_s", "checked")
        }
    kc = detail.get("kernel_costs", {})
    buckets = kc.get("buckets") if isinstance(kc, dict) else None
    if isinstance(buckets, dict):
        row["kernel"] = {
            b: {
                "fp_muls_per_set": e.get("fp_muls_per_set"),
                "elem_ops_per_set": e.get("elem_ops_per_set"),
                "roofline_est_sets_per_s": (
                    (e.get("roofline") or {}).get("est_sets_per_s")
                ),
            }
            for b, e in buckets.items()
            if isinstance(e, dict) and "fp_muls_per_set" in e
        }
    hc = detail.get("hash", {})
    scen = hc.get("scenarios") if isinstance(hc, dict) else None
    if isinstance(scen, dict):
        sub = {
            name: int(e["compressions"])
            for name, e in scen.items()
            if isinstance(e, dict)
            and isinstance(e.get("compressions"), (int, float))
        }
        if sub:
            row["hash"] = sub
        # ISSUE 15: measured hash wall clock per scenario (host +
        # batched kernel) and the kernel-only wall — the bench gate
        # fails round-over-round decay on boundary/import like the
        # epoch stage seconds
        wall = {
            name: float(e["wall_s"])
            for name, e in scen.items()
            if isinstance(e, dict)
            and isinstance(e.get("wall_s"), (int, float))
            and e["wall_s"] > 0
        }
        if wall:
            row["hash_wall_s"] = wall
        dev = {
            name: float(e["device"]["wall_s"])
            for name, e in scen.items()
            if isinstance(e, dict)
            and isinstance((e.get("device") or {}).get("wall_s"),
                           (int, float))
            and e["device"]["wall_s"] > 0
        }
        if dev:
            row["hash_device_wall_s"] = dev
    bd = detail.get("bounds", {})
    if isinstance(bd, dict) and (
        "min_headroom_bits" in bd or "certificate_ok" in bd
    ):
        # keep certificate_ok even when the prover failed outright and
        # carries no numbers — compare() fails a fresh->broken
        # transition explicitly (a collapse must not skip the gate
        # just because min_headroom_bits went missing)
        row["bounds"] = {
            k: bd.get(k)
            for k in (
                "certified_sites", "min_headroom_bits",
                "trimmed_passes_per_mul", "certificate_ok",
            )
            if bd.get(k) is not None
        }
    ep = detail.get("epoch", {})
    if isinstance(ep, dict):
        warm = {
            k[1:]: v["warm_s"]
            for k, v in ep.items()
            if isinstance(v, dict) and "warm_s" in v
        }
        if warm:
            row["epoch_warm_s"] = warm
    load = detail.get("load", {})
    if isinstance(load, dict):
        # LoadReport shape (lighthouse_tpu/tools/loadgen.py):
        # duty_response_ms.{p50,p95,p99}, shed.rate, deadline.rate,
        # overload.{duty_response_ms,attestation_shed_rate,...}
        sub = {}
        # the report schema names the shedding policy generation —
        # compare() only diffs load rates between same-schema rounds
        # (a policy change is a new baseline, not a regression)
        if load.get("schema"):
            sub["scenario"] = load["schema"]
        duty = load.get("duty_response_ms")
        if isinstance(duty, dict) and duty.get("p99") is not None:
            sub["duty_p99_s"] = round(float(duty["p99"]) / 1000.0, 6)
        shed = load.get("shed")
        if isinstance(shed, dict) and shed.get("rate") is not None:
            sub["shed_rate"] = shed["rate"]
        dl = load.get("deadline")
        if isinstance(dl, dict) and dl.get("rate") is not None:
            sub["deadline_miss_rate"] = dl["rate"]
        over = load.get("overload")
        if isinstance(over, dict):
            oduty = over.get("duty_response_ms")
            if isinstance(oduty, dict) and oduty.get("p99") is not None:
                sub["overload_duty_p99_s"] = round(
                    float(oduty["p99"]) / 1000.0, 6
                )
            if over.get("attestation_shed_rate") is not None:
                sub["overload_att_shed_rate"] = over[
                    "attestation_shed_rate"
                ]
            if over.get("fresh_block_sheds") is not None:
                sub["fresh_block_sheds"] = over["fresh_block_sheds"]
            if over.get("critical_deadline_misses") is not None:
                sub["critical_deadline_misses"] = over[
                    "critical_deadline_misses"
                ]
        if sub:
            row["load"] = sub
    suite = detail.get("suite", {})
    if isinstance(suite, dict) and (
        suite.get("fast_tier_pred_s") is not None
        or suite.get("fast_tier_wall_s") is not None
    ):
        sub = {}
        for k in ("fast_tier_pred_s", "fast_tier_wall_s"):
            if isinstance(suite.get(k), (int, float)):
                sub[k] = float(suite[k])
        # truncation is count-gated (one is one too many): always
        # present when the section is, defaulting to 0 so a later
        # truncated round has a baseline to fail against
        sub["truncated"] = int(suite.get("truncated") or 0)
        row["suite"] = sub
    sc = detail.get("scenarios", {})
    if isinstance(sc, dict) and "pass_all" in sc:
        row["scenarios_pass"] = bool(sc["pass_all"])
    bi = detail.get("backend_init", {})
    arts = bi.get("artifacts") if isinstance(bi, dict) else None
    if isinstance(arts, list):
        row["artifacts"] = [
            {k: a.get(k) for k in ("bucket", "backend",
                                   "source_hash_match", "age_s")}
            for a in arts
        ]
    if detail.get("last_self_measured", {}).get("value"):
        lsm = detail["last_self_measured"]
        row["last_self_measured"] = {
            "value": lsm.get("value"), "measured_at": lsm.get("measured_at")
        }
    return row


# ------------------------------------------------------------------ compare

# (dotted path, label, kind): kind "time" = lower is better, "rate" =
# higher is better, "count" = lower is better and exact (op census),
# "ratio" = lower is better, unitless (shed / deadline-miss rates),
# "headroom" = higher is better with an absolute slack floor: any
# round-over-round decrease that lands BELOW the floor fails (the
# ISSUE 14 rule — trims may spend headroom, but never below the slack
# the trim search itself preserves), "flag" = a truthy->falsy
# transition fails (certificate freshness)
COMPARE_FIELDS = (
    # absolute floors sized ~2x the warm steady-state values so shared-
    # CI scheduling noise cannot flap the gate; decays at this scale
    # are also caught by test_scale/test_loadgen's absolute budgets
    ("epoch_warm_s.250k", "epoch warm @250k", "time", 0.08),
    ("epoch_warm_s.500k", "epoch warm @500k", "time", 0.12),
    ("load.duty_p99_s", "load duty p99", "time", 0.05),
    # ISSUE 13: round-over-round scheduler regressions at the fixed
    # loadgen seed — shedding more, or aging more work past deadline,
    # at the same offered load is a scheduler decay. Compared only
    # between rounds sharing load.scenario (see compare()).
    ("load.shed_rate", "load shed rate", "ratio", 0.02),
    ("load.deadline_miss_rate", "load deadline-miss rate", "ratio", 0.02),
    ("load.overload_duty_p99_s", "overload duty p99", "time", 0.05),
    ("load.overload_att_shed_rate", "overload attestation shed rate",
     "ratio", 0.02),
    # block/sync-critical queues must NEVER shed or age out under the
    # seeded overload: exact, any increase fails
    ("load.fresh_block_sheds", "overload fresh-block sheds", "count", 0.0),
    ("load.critical_deadline_misses",
     "overload critical deadline misses", "count", 0.0),
    ("kernel.4096.fp_muls_per_set", "fp-muls/set @4096", "count", 0.0),
    ("kernel.1024.fp_muls_per_set", "fp-muls/set @1024", "count", 0.0),
    ("kernel.128.fp_muls_per_set", "fp-muls/set @128", "count", 0.0),
    # ISSUE 11: SHA-256 compression counts are exact like op counts —
    # any round-over-round increase is a hashing regression
    ("hash.steady_slot", "sha256 compressions @steady-slot", "count", 0.0),
    ("hash.epoch_boundary", "sha256 compressions @epoch-boundary",
     "count", 0.0),
    ("hash.block_import", "sha256 compressions @block-import",
     "count", 0.0),
    # ISSUE 15: measured hash wall clock of the batched boundary /
    # import scenarios — the kernel's win must not silently decay.
    # Floors ~2x the warm CPU-JAX measurements (boundary ~0.1 s,
    # import ~0.05 s) so shared-CI scheduling noise cannot flap the
    # gate; the census count gates above catch work-shape regressions
    # at exact precision either way
    ("hash_wall_s.epoch_boundary", "hash wall @epoch-boundary", "time",
     0.2),
    ("hash_wall_s.block_import", "hash wall @block-import", "time", 0.1),
    # ISSUE 14: certified int32 headroom of the limb-bounds prover —
    # a decrease below the 2-bit slack floor means a norm-schedule or
    # kernel edit spent the safety margin the trim search preserves
    ("bounds.min_headroom_bits", "limb-bounds min headroom (bits)",
     "headroom", 2.0),
    # ...and a fresh->broken certificate transition must fail in its
    # own right: when the prover errors out min_headroom_bits goes
    # missing entirely and the numeric gate above would silently skip
    ("bounds.certificate_ok", "limb-bounds certificate", "flag", 0.0),
    # ISSUE 16: the fast tier's own wall — the correctness gate must
    # keep fitting its 870 s driver timeout, so a round-over-round
    # growth of the census-predicted (or last measured) tier-1 wall
    # fails like an epoch-seconds decay. Floors absorb box jitter
    # (~30 s prediction re-pin noise, ~2 min measured-wall noise on a
    # loaded 1-core box); a truncated census is exact — one rc-124 is
    # one too many
    ("suite.fast_tier_pred_s", "fast-tier predicted wall", "time", 30.0),
    ("suite.fast_tier_wall_s", "fast-tier measured wall", "time", 120.0),
    ("suite.truncated", "fast-tier truncation (timeout killed the "
     "suite)", "count", 0.0),
    ("value_sets_per_s", "driver-verified sets/s", "rate", 0.0),
    ("replay.sets_per_s", "cpu-replay sets/s", "rate", 0.0),
)


def _dig(row: dict, dotted: str):
    cur = row
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur if isinstance(cur, (int, float)) else None


def compare(prev: dict, cur: dict, rel_tol: float = 0.20) -> list:
    """Regressions between two rows: >rel_tol relative decay on any
    field BOTH rows carry (absolute floors keep shared-CI timing noise
    from flapping the gate; op counts are exact — any increase flags).
    Returns human-readable problem strings."""
    problems = []
    load_scenarios_differ = (prev.get("load") or {}).get("scenario") != (
        (cur.get("load") or {}).get("scenario")
    )
    for dotted, label, kind, floor in COMPARE_FIELDS:
        a, b = _dig(prev, dotted), _dig(cur, dotted)
        if a is None or b is None:
            continue
        # load rates are only comparable within one shedding-policy
        # generation (load.scenario): a policy change re-baselines the
        # curves instead of flagging as a regression
        if dotted.startswith("load.") and load_scenarios_differ:
            continue
        if kind == "count":
            if b > a:
                problems.append(
                    f"{label}: {a} -> {b} (+{b - a}; op counts are "
                    f"exact — any increase is a regression)"
                )
        elif kind == "time":
            if b > a * (1 + rel_tol) and (b - a) > floor:
                problems.append(
                    f"{label}: {a:.4g}s -> {b:.4g}s "
                    f"(+{(b / a - 1) * 100:.0f}%)"
                )
        elif kind == "ratio":
            # lower is better; the absolute floor absorbs seeded-but-
            # timing-adjacent jitter (in-queue expiry counts)
            if b > a * (1 + rel_tol) and (b - a) > floor:
                problems.append(
                    f"{label}: {a:.4g} -> {b:.4g} "
                    f"(+{(b / a - 1) * 100:.0f}%)"
                )
        elif kind == "flag":
            # truthy -> falsy is the only failing transition (ISSUE
            # 14 certificate_ok: a round whose certificate went
            # stale/unproven must fail even with no numbers to diff)
            if a and not b:
                problems.append(
                    f"{label}: went stale/unproven (ok -> broken) — "
                    "re-prove: python tools/limb_bounds.py --update"
                )
        elif kind == "headroom":
            # higher is better; decreases are tolerated while the
            # value stays at/above the absolute slack floor — dropping
            # below it round-over-round fails (ISSUE 14)
            if b < a and b < floor:
                problems.append(
                    f"{label}: {a:.4g} -> {b:.4g} (below the "
                    f"{floor:.4g}-bit slack floor — a kernel or "
                    "schedule edit spent the certified safety margin)"
                )
        elif kind == "rate":
            # a dead round (0.0) is not a measurement; only compare
            # when both rounds actually measured something, and only
            # within one measurement mode — a device round followed by
            # a CPU-replay round is a tunnel outage, not a 250x decay
            if dotted == "value_sets_per_s" and (
                prev.get("mode") != cur.get("mode")
            ):
                continue
            if a > 0 and b > 0 and b < a * (1 - rel_tol):
                problems.append(
                    f"{label}: {a:.4g} -> {b:.4g} "
                    f"({(b / a - 1) * 100:.0f}%)"
                )
    return problems


def latest_comparable(all_rows: list) -> tuple:
    """The two most recent rows that share at least one comparable
    field, newest last; (None, None) when fewer than two exist."""
    for i in range(len(all_rows) - 1, 0, -1):
        cur = all_rows[i]
        for j in range(i - 1, -1, -1):
            prev = all_rows[j]
            if any(
                _dig(prev, d) is not None and _dig(cur, d) is not None
                for d, *_ in COMPARE_FIELDS
            ):
                return prev, cur
    return None, None


def render(all_rows: list) -> str:
    """Fixed-width trajectory table for terminals/logs."""
    cols = (
        ("recorded_at", 20), ("source", 16), ("mode", 10),
        ("value_sets_per_s", 12), ("marginal_sets_per_s", 12),
        ("replay_rate", 11), ("fpmul/set@4096", 14),
        ("roofline@4096", 13), ("epoch250k", 9), ("duty_p99", 8),
    )
    lines = ["  ".join(name.ljust(w) for name, w in cols)]
    for r in all_rows:
        vals = {
            "recorded_at": r.get("recorded_at", ""),
            "source": r.get("source", ""),
            "mode": r.get("mode", ""),
            "value_sets_per_s": r.get("value_sets_per_s"),
            "marginal_sets_per_s": r.get("marginal_sets_per_s"),
            "replay_rate": _dig(r, "replay.sets_per_s"),
            "fpmul/set@4096": _dig(r, "kernel.4096.fp_muls_per_set"),
            "roofline@4096": _dig(
                r, "kernel.4096.roofline_est_sets_per_s"),
            "epoch250k": _dig(r, "epoch_warm_s.250k"),
            "duty_p99": _dig(r, "load.duty_p99_s"),
        }
        lines.append("  ".join(
            ("" if vals[name] is None else str(vals[name]))[:w].ljust(w)
            for name, w in cols
        ))
    return "\n".join(lines)
