"""Deterministic traffic-replay harness (ISSUE 8): the load half of
ROADMAP item 4 ("serve a million users").

Spins ONE full node assembly (the PR 7 simulator seams: BeaconChain +
BeaconProcessor + NetworkService + VC on the in-process hub), serves it
over a real `ApiServer` socket, then replays a seeded traffic shape
against it:

  - N simulated validator clients pulling duties (attester / proposer /
    sync), polling heads, states and sync status over HTTP — the
    request mix a real VC population generates;
  - SSE subscribers following head/block events while slots advance;
  - a per-slot synthetic gossip burst sized off a SIMULATED network
    validator count (default 1M), submitted to the node's
    beacon_processor with slot-relative deadlines — a deterministic
    fraction arrives already stale, so the deadline-miss and shed
    series have known-nonzero denominators.

Everything randomized is drawn from `random.Random(seed)`, so the
report SHAPE (request schedule, gossip burst sizes, population split
into stale/expiring/fresh) is EXACTLY reproducible run-to-run.
Shed/deadline-miss TOTALS are seeded but tolerance-exact only (~1%):
the scheduler's expired-sweep eviction clears every expired entry
whenever the deadline watermark fires, so whether an `expiring` item
sheds at enqueue, at the sweep, or at dequeue depends on wall-clock
scheduling — same totals class, slightly different split. Measured
latencies vary freely.

ISSUE 13 — the scheduler fault fleet. After the steady phase the
replay runs a seeded OVERLOAD phase driven by `FaultSpell`s:

  burst          multiplies the per-slot gossip burst (default 4x —
                 the "1M validators all gossiping at once" shape)
  worker_stall   every attestation batch verification sleeps N ms
                 (a wedged TPU dispatch / GC pause stand-in)
  slow_consumer  the scheduler drain is capped at N step() calls per
                 slot, so backlog carries across slots

During overload the harness also injects block/segment/aggregate work
AFTER each burst, so the report can prove the priority chain under
contention: the `overload` section records per-queue sheds by reason,
per-queue deadline misses, overload-phase duty percentiles, and
`order_ok` (every block/sync-critical item processed before any
unaggregated attestation in its slot's drain). The ratcheted tier-1
gates read off it: zero sheds + zero deadline misses on the
block/sync-critical queues, nonzero attestation shed rate, duty p99
<= 250 ms.

The emitted `LoadReport` is the schema-checked contract shared with
`bench.py` (`detail.load`) and gated in tier-1 by
`tests/test_loadgen.py`: per-endpoint p50/p95/p99, duty-response SLO
percentiles, shed rate (split by reason), deadline-miss rate, SSE
delivery counters, and the overload section.

CLI: `python tools/loadgen.py --vcs 200 --seed 7`.
"""

from __future__ import annotations

import http.client
import json
import math
import random
import socket
import statistics
import threading
import time
from concurrent.futures import ThreadPoolExecutor, wait
from dataclasses import asdict, dataclass, field

from ..common import metrics, tracing

# v2: deadline-aware shedding semantics (expired work is shed at
# enqueue AND dequeue, sheds split by reason) + the mandatory overload
# section. tools/bench_gate.py only compares load rates between rounds
# that share this schema string — v1 rows measured a different policy.
SCHEMA = "lighthouse-tpu/load-report/v2"
MAINNET_SLOTS_PER_EPOCH = 32  # the simulated network's slot cadence


class LoadgenError(RuntimeError):
    """Fleet failed to start or the replay could not run."""


# ------------------------------------------------------------ the report


@dataclass
class LoadReport:
    """The schema-checked run report (shared with bench.py detail.load).

    `validate` is the contract: bench records any problems next to the
    report instead of shipping a silently-misshapen section, and the
    tier-1 gate asserts it comes back empty."""

    seed: int
    vcs: int
    slots: int
    simulated_validators: int
    gossip_submitted: int
    wall_s: float
    requests_total: int
    errors_total: int
    endpoints: dict  # name -> {requests, errors, p50_ms, p95_ms, p99_ms}
    duty_response_ms: dict  # {count, p50, p95, p99}
    shed: dict  # {received, dropped, rate}
    deadline: dict  # {processed, misses, rate}
    sse: dict  # {subscribers, events_received, events_sent, slow_client_drops}
    # ISSUE 11: what the replay's hashing cost — total SHA-256
    # compressions measured during the run and the read-path share per
    # endpoint (states/{id}/root hashes the whole head state per hit)
    hash: dict  # {compressions, read_path: {endpoint: compressions}}
    # ISSUE 13: the seeded scheduler-fault-fleet section — graceful
    # degradation under 4x overload (see module docstring)
    overload: dict
    schema: str = SCHEMA

    def to_dict(self) -> dict:
        return asdict(self)

    _ENDPOINT_KEYS = ("requests", "errors", "p50_ms", "p95_ms", "p99_ms")
    _SECTION_KEYS = {
        "duty_response_ms": ("count", "p50", "p95", "p99"),
        "shed": ("received", "dropped", "rate", "by_reason"),
        "deadline": ("processed", "misses", "rate"),
        "sse": (
            "subscribers",
            "events_received",
            "events_sent",
            "slow_client_drops",
        ),
        "hash": ("compressions", "read_path"),
        "overload": (
            "slots",
            "burst_multiplier",
            "spells",
            "gossip_submitted",
            "duty_response_ms",
            "sheds",
            "deadline_misses",
            "attestation_shed_rate",
            "fresh_block_sheds",
            "critical_deadline_misses",
            "order_ok",
        ),
    }

    @classmethod
    def validate(cls, doc: dict) -> list:
        """Schema problems (empty = conforming)."""
        problems = []
        if not isinstance(doc, dict):
            return [f"report is {type(doc).__name__}, not dict"]
        if doc.get("schema") != SCHEMA:
            problems.append(
                f"schema {doc.get('schema')!r} != required {SCHEMA!r}"
            )
        for f_ in cls.__dataclass_fields__:
            if f_ not in doc:
                problems.append(f"missing top-level key {f_!r}")
        for name, entry in (doc.get("endpoints") or {}).items():
            for k in cls._ENDPOINT_KEYS:
                if k not in entry:
                    problems.append(f"endpoints[{name!r}] missing {k!r}")
        for section, keys in cls._SECTION_KEYS.items():
            sub = doc.get(section)
            if not isinstance(sub, dict):
                continue  # absence already reported above
            for k in keys:
                if k not in sub:
                    problems.append(f"{section} missing {k!r}")
        return problems


@dataclass(frozen=True)
class FaultSpell:
    """One seeded scheduler fault, active on overload-phase slots
    [start, end) — the fleet is a list of these (module docstring)."""

    kind: str  # "burst" | "worker_stall" | "slow_consumer"
    start: int
    end: int
    magnitude: float

    def active(self, idx: int) -> bool:
        return self.start <= idx < self.end


def default_overload_spells(slots: int) -> tuple:
    """The seeded 4x-overload cocktail the acceptance gates run on:
    a sustained 4x burst, with a worker-stall + slow-consumer spell in
    the middle slots so backlog provably carries across slots."""
    mid_end = max(2, slots - 1)
    return (
        FaultSpell("burst", 0, slots, 4.0),
        FaultSpell("worker_stall", 1, mid_end, 2.0),  # ms per batch
        # 6 steps/slot is BELOW one slot's work (criticals + the batch
        # former's passes over the attestation cap), so backlog
        # provably carries into the next slot while the spell holds
        FaultSpell("slow_consumer", 1, mid_end, 6),
    )


@dataclass
class LoadgenConfig:
    vcs: int = 200  # simulated validator clients
    seed: int = 7
    slots: int = 8  # steady replay horizon (after warmup)
    slots_per_epoch: int = 4  # dwarf epochs (scenario_spec)
    n_validators: int = 16  # real validators backing the fleet
    warmup_epochs: int = 2  # build finality + warm caches first
    simulated_validators: int = 1_000_000  # network size the rates model
    # fraction of the simulated per-slot attestation rate actually
    # submitted as Work (1M/32 per slot is ~31k objects — the shape,
    # not the count, is what the observatory measures)
    gossip_scale: float = 1 / 64.0
    stale_fraction: float = 0.10  # arrive past their slot deadline (DOA)
    # admitted fresh but expire before a worker reaches them — the
    # deterministic in-queue-expiry (deadline-miss) denominator
    expiring_fraction: float = 0.05
    expiring_delay_s: float = 1e-4
    attestation_queue_cap: int = 384  # bounded: the burst overflows it
    attestation_batch_cap: int = 256
    http_workers: int = 8
    sse_subscribers: int = 2
    request_timeout_s: float = 10.0
    extra_slow_ms: float = 0.0  # per-batch verify stall (stress shapes)
    # ISSUE 13: the overload phase (0 disables). Spells default to
    # default_overload_spells(overload_slots).
    overload_slots: int = 4
    overload_spells: tuple = None
    # critical work injected AFTER each overload burst, proving the
    # priority chain under contention
    critical_blocks_per_slot: int = 2
    critical_segments_per_slot: int = 1
    critical_aggregates_per_slot: int = 8

    def spells(self) -> tuple:
        if self.overload_spells is not None:
            return tuple(self.overload_spells)
        return default_overload_spells(self.overload_slots)

    @property
    def gossip_per_slot(self) -> int:
        return max(
            1,
            int(
                self.simulated_validators
                / MAINNET_SLOTS_PER_EPOCH
                * self.gossip_scale
            ),
        )


# the SLO headline: duty pulls are what a million VCs block on
DUTY_ENDPOINTS = ("duties_attester", "duties_proposer", "duties_sync")


def _pcts_ms(xs: list) -> dict:
    """Nearest-rank percentiles in milliseconds (bench.py convention:
    p99 is never below the true 99th percentile)."""
    if not xs:
        return {"count": 0, "p50": None, "p95": None, "p99": None}
    xs = sorted(xs)
    n = len(xs)

    def rank(p):
        return xs[min(n - 1, max(0, math.ceil(n * p) - 1))]

    return {
        "count": n,
        "p50": round(statistics.median(xs) * 1e3, 3),
        "p95": round(rank(0.95) * 1e3, 3),
        "p99": round(rank(0.99) * 1e3, 3),
    }


def _counter_value(name: str, **labels) -> float:
    fam = metrics.get(name)
    if fam is None:
        return 0.0
    try:
        if labels:
            return fam.labels(**labels).value
        return fam.value
    except Exception:
        return 0.0


# ------------------------------------------------------------ the fleet


class _Fleet:
    """One node + API server + SSE subscribers under replay."""

    def __init__(self, cfg: LoadgenConfig):
        self.cfg = cfg
        self.sim = None
        self.server = None
        try:
            from ..node.beacon_processor import WorkType
            from ..node.http_api import ApiServer, BeaconApi
            from .simulator import Simulation, scenario_spec

            self.WorkType = WorkType
            self.sim = Simulation(
                n_nodes=1,
                n_validators=cfg.n_validators,
                spec=scenario_spec(cfg.slots_per_epoch),
                seed=cfg.seed,
                fake_signing=True,
            )
            self.node = self.sim.nodes[0]
            # bounded, validator-count-flavored queue for the replay:
            # the burst reliably overflows it, so the shed series has a
            # known-nonzero denominator (counts are tolerance-exact
            # run-to-run; see the module docstring)
            proc = self.node.processor
            proc.config.queue_capacities[WorkType.GOSSIP_ATTESTATION] = (
                cfg.attestation_queue_cap
            )
            proc.config.max_gossip_attestation_batch_size = (
                cfg.attestation_batch_cap
            )
            self.slot = 0
            for _ in range(cfg.warmup_epochs * cfg.slots_per_epoch):
                self.slot += 1
                self.sim.run_slot(self.slot)
            self.server = ApiServer(
                BeaconApi(self.node.chain, sync=self.node.sync),
                host="127.0.0.1",
                port=0,
            )
            self.server.start()
        except Exception as e:
            # a long-lived caller (bench) records the error and moves
            # on — never leak a half-built fleet's sockets/assembly
            self.close()
            raise LoadgenError(f"fleet failed to start: {e}") from e
        self._lock = threading.Lock()
        self._samples: dict = {}  # endpoint -> [seconds]
        self._errors: dict = {}  # endpoint -> count
        self._sse_counts: list = []
        self._sse_stop = threading.Event()
        self._sse_threads: list = []
        # ISSUE 13 fault-fleet state
        self._phase = "steady"
        self._duty_overload: list = []  # duty latencies, overload phase
        self._order_log: list = []  # (kind, slot) in execution order
        self._stall_s = 0.0  # worker_stall spell, read by batch closures

    # ---------------------------------------------------------- http side

    def _do_request(self, spec_: tuple) -> None:
        endpoint, method, path, body = spec_
        t0 = time.perf_counter()
        status = 0
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", self.server.port,
                timeout=self.cfg.request_timeout_s,
            )
            try:
                headers = {}
                if body is not None:
                    headers["Content-Type"] = "application/json"
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                resp.read()
                status = resp.status
            finally:
                conn.close()
        except Exception:
            status = 0
        dt = time.perf_counter() - t0
        with self._lock:
            self._samples.setdefault(endpoint, []).append(dt)
            if endpoint in DUTY_ENDPOINTS and self._phase == "overload":
                # the ratcheted overload SLO (duty p99 <= 250 ms while
                # the scheduler sheds) reads off this split
                self._duty_overload.append(dt)
            if not 200 <= status < 300:
                self._errors[endpoint] = self._errors.get(endpoint, 0) + 1

    def _slot_schedule(
        self, rng: random.Random, slot: int, first: bool = False
    ) -> list:
        """The seeded request mix one slot of VC traffic generates."""
        cfg = self.cfg
        spe = cfg.slots_per_epoch
        epoch = slot // spe
        out = []
        for vc in range(cfg.vcs):
            ids = json.dumps(
                [
                    str(vc % cfg.n_validators),
                    str((vc + 7) % cfg.n_validators),
                ]
            )
            if first or slot % spe == 0:
                # VC startup (first replay slot) and every epoch
                # rollover: the whole population re-pulls its duty
                # tables — the SLO headline always has samples, even on
                # replays too short to cross an epoch boundary
                out.append(
                    (
                        "duties_attester",
                        "POST",
                        f"/eth/v1/validator/duties/attester/{epoch}",
                        ids,
                    )
                )
                out.append(
                    (
                        "duties_proposer",
                        "GET",
                        f"/eth/v1/validator/duties/proposer/{epoch}",
                        None,
                    )
                )
                out.append(
                    (
                        "duties_sync",
                        "POST",
                        f"/eth/v1/validator/duties/sync/{epoch}",
                        ids,
                    )
                )
            r = rng.random()
            if r < 0.8:
                out.append(
                    ("headers_head", "GET", "/eth/v1/beacon/headers/head", None)
                )
            if r < 0.3:
                out.append(("syncing", "GET", "/eth/v1/node/syncing", None))
            if r < 0.2:
                out.append(
                    (
                        "state_root",
                        "GET",
                        "/eth/v1/beacon/states/head/root",
                        None,
                    )
                )
            if r < 0.1:
                out.append(
                    (
                        "validators",
                        "GET",
                        "/eth/v1/beacon/states/head/validators?id="
                        f"{vc % cfg.n_validators}",
                        None,
                    )
                )
            if r < 0.05:
                out.append(
                    (
                        "finality_checkpoints",
                        "GET",
                        "/eth/v1/beacon/states/head/finality_checkpoints",
                        None,
                    )
                )
        rng.shuffle(out)
        return out

    # --------------------------------------------------------- gossip side

    def _inject_gossip(
        self, rng: random.Random, slot: int, multiplier: float = 1.0
    ) -> int:
        """One slot's synthetic attestation burst: Work with
        slot-relative deadlines through the real scheduler + fake-BLS
        dispatch seam. Three seeded populations:

          stale     deadline already past — shed at the door (enqueue
                    expiry, reason=expired), deterministic count
          expiring  admitted fresh, deadline ~100us out — provably
                    expire IN-QUEUE before the drain reaches them
                    (deterministic dequeue sheds + deadline misses)
          fresh     deadline far out — processed, or evicted by
                    capacity pressure when the burst overflows the cap

        Returns the number submitted."""
        from ..crypto import bls
        from ..node.beacon_processor import Work

        cfg = self.cfg
        proc = self.node.processor
        n = max(1, int(cfg.gossip_per_slot * multiplier))
        extra = cfg.extra_slow_ms / 1e3

        def batch(payloads) -> bool:
            stall = self._stall_s + extra
            if stall:
                time.sleep(stall)  # worker_stall spell
            with self._lock:
                self._order_log.append(("attestation", self.slot))
            return bool(
                bls.verify_signature_sets(
                    payloads, backend="fake",
                    rand_scalars=[1] * len(payloads),
                )
            )

        def individual(p) -> None:
            bls.verify_signature_sets([p], backend="fake", rand_scalars=[1])

        for i in range(n):
            r = rng.random()
            now = time.perf_counter()
            if r < cfg.stale_fraction:
                deadline = now - 1e-4
            elif r < cfg.stale_fraction + cfg.expiring_fraction:
                deadline = now + cfg.expiring_delay_s
            else:
                deadline = now + 60.0
            proc.submit(
                Work(
                    kind=self.WorkType.GOSSIP_ATTESTATION,
                    process_individual=individual,
                    process_batch=batch,
                    payload=i,
                    slot=slot,
                    deadline=deadline,
                )
            )
        return n

    def _inject_critical(self) -> None:
        """Block/sync-critical + aggregate work submitted AFTER the
        burst (plus an order-log mark): the scheduler must serve these
        ahead of the queued attestation backlog — the priority-chain
        proof the `order_ok` flag condenses."""
        from ..node.beacon_processor import Work

        proc = self.node.processor
        cfg = self.cfg

        def mk(kindname):
            def run(_p):
                with self._lock:
                    self._order_log.append((kindname, self.slot))

            return run

        with self._lock:
            self._order_log.append(("mark", self.slot))
        for _ in range(cfg.critical_segments_per_slot):
            proc.submit(
                Work(
                    kind=self.WorkType.CHAIN_SEGMENT,
                    process_individual=mk("segment"),
                    slot=self.slot,
                )
            )
        for _ in range(cfg.critical_blocks_per_slot):
            proc.submit(
                Work(
                    kind=self.WorkType.GOSSIP_BLOCK,
                    process_individual=mk("block"),
                    slot=self.slot,
                )
            )
        for _ in range(cfg.critical_aggregates_per_slot):
            proc.submit(
                Work(
                    kind=self.WorkType.GOSSIP_AGGREGATE,
                    process_individual=mk("aggregate"),
                    slot=self.slot,
                    deadline=time.perf_counter() + 60.0,
                )
            )

    # ----------------------------------------------------- fault seams

    def _install_step_budget(self, budget: int):
        """slow_consumer spell: cap scheduler step() calls for the rest
        of this slot (covers the simulator's internal pump AND the
        explicit drain), so backlog provably carries across slots.
        Returns a restore callable."""
        proc = self.node.processor
        orig_step = proc.step
        remaining = [int(budget)]

        def budgeted() -> bool:
            if remaining[0] <= 0:
                return False  # consumer wedged: leave the backlog
            if orig_step():
                remaining[0] -= 1
                return True
            return False

        proc.step = budgeted

        def restore():
            del proc.step  # uncover the class method

        return restore

    def _drain(self) -> None:
        """One drain pass: flush due retried/delayed work, then step
        until idle (or until the slow-consumer budget wedges)."""
        proc = self.node.processor
        proc.pump_reprocess(time.perf_counter())
        while proc.step():
            pass

    def _drain_fully(self) -> None:
        """Close the books: flush the reprocess heap (future-due
        retries included) and every queue so received == processed +
        shed exactly when the counters are read."""
        proc = self.node.processor
        for _ in range(1000):  # attempts are bounded; this terminates
            moved = proc.pump_reprocess(time.perf_counter() + 3600.0)
            stepped = 0
            while proc.step():
                stepped += 1
            if not moved and not stepped and proc.pending_reprocess() == 0:
                break

    @staticmethod
    def _labeled_values(name: str) -> dict:
        fam = metrics.get(name)
        if fam is None:
            return {}
        return {lv: fam.labels(*lv).value for lv in fam.label_values()}

    # ------------------------------------------------------------ sse side

    def _sse_reader(self, idx: int) -> None:
        count = 0
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", self.server.port, timeout=2.0
            )
            conn.request(
                "GET", "/eth/v1/events?topics=head,block",
                headers={"Accept": "text/event-stream"},
            )
            resp = conn.getresponse()
            while not self._sse_stop.is_set():
                try:
                    line = resp.fp.readline()
                except (socket.timeout, OSError):
                    continue
                if not line:
                    break
                if line.startswith(b"event: "):
                    count += 1
            conn.close()
        except Exception:
            pass
        with self._lock:
            self._sse_counts.append(count)

    def start_sse(self) -> None:
        for i in range(self.cfg.sse_subscribers):
            t = threading.Thread(
                target=self._sse_reader, args=(i,), daemon=True
            )
            t.start()
            self._sse_threads.append(t)
        # subscriptions must exist before the first replayed slot's
        # events fire (the report counts delivered events)
        deadline = time.monotonic() + 2.0
        bus = self.node.chain.event_bus
        while (
            bus.subscriber_count() < self.cfg.sse_subscribers
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)

    def stop_sse(self) -> None:
        self._sse_stop.set()
        for t in self._sse_threads:
            t.join(timeout=5.0)

    # -------------------------------------------------------------- replay

    def replay(self) -> LoadReport:
        cfg = self.cfg
        # independent streams: the request mix and the gossip staleness
        # schedule stay reproducible regardless of each other
        rng_http = random.Random(cfg.seed)
        rng_gossip = random.Random(cfg.seed + 1)
        att = self.WorkType.GOSSIP_ATTESTATION.name
        before = {
            "received": _counter_value(
                "beacon_processor_work_received_total", queue=att
            ),
            "dropped": _counter_value(
                "beacon_processor_work_dropped_total", queue=att
            ),
            "processed": _counter_value(
                "beacon_processor_work_processed_total", queue=att
            ),
            "misses": _counter_value(
                "beacon_processor_deadline_misses_total", queue=att
            ),
            "sheds": self._labeled_values("beacon_processor_sheds_total"),
            "sse_sent": self._sse_sent_total(),
            "sse_drops": _counter_value(
                "http_sse_slow_clients_dropped_total"
            ),
            "hash_total": self._hash_compressions_total(),
            "hash_read": self._read_path_compressions(),
        }
        gossip_submitted = 0
        overload_submitted = 0
        spells = cfg.spells() if cfg.overload_slots > 0 else ()
        over_before = None
        t_start = time.perf_counter()
        self.start_sse()
        pool = ThreadPoolExecutor(max_workers=cfg.http_workers)
        try:
            for i in range(cfg.slots):
                self.slot += 1
                # 1. the chain advances (block production, events to SSE)
                self.sim.run_slot(self.slot)
                # 2. the slot's gossip burst lands (deterministic
                #    overflow of the bounded attestation queue)
                gossip_submitted += self._inject_gossip(
                    rng_gossip, self.slot
                )
                # 3. the slot's HTTP traffic fires while the node works
                #    the backlog off — requests contend with verification
                futures = [
                    pool.submit(self._do_request, s)
                    for s in self._slot_schedule(
                        rng_http, self.slot, first=(i == 0)
                    )
                ]
                self._drain()
                wait(futures, timeout=cfg.request_timeout_s * 4)
            # ------- overload phase: the seeded scheduler fault fleet
            self._phase = "overload"
            over_before = {
                "received": _counter_value(
                    "beacon_processor_work_received_total", queue=att
                ),
                "processed": _counter_value(
                    "beacon_processor_work_processed_total", queue=att
                ),
                "sheds": self._labeled_values(
                    "beacon_processor_sheds_total"
                ),
                "misses": self._labeled_values(
                    "beacon_processor_deadline_misses_total"
                ),
            }
            for j in range(cfg.overload_slots):
                mult, stall_ms, budget = 1.0, 0.0, None
                for sp in spells:
                    if not sp.active(j):
                        continue
                    if sp.kind == "burst":
                        mult *= sp.magnitude
                    elif sp.kind == "worker_stall":
                        stall_ms = max(stall_ms, sp.magnitude)
                    elif sp.kind == "slow_consumer":
                        budget = (
                            sp.magnitude
                            if budget is None
                            else min(budget, sp.magnitude)
                        )
                self._stall_s = stall_ms / 1e3
                restore = (
                    self._install_step_budget(budget)
                    if budget is not None
                    else None
                )
                try:
                    self.slot += 1
                    self.sim.run_slot(self.slot)
                    n = self._inject_gossip(
                        rng_gossip, self.slot, multiplier=mult
                    )
                    gossip_submitted += n
                    overload_submitted += n
                    # critical work lands AFTER the burst: the drain
                    # must serve it first anyway (priority chain)
                    self._inject_critical()
                    futures = [
                        pool.submit(self._do_request, s)
                        for s in self._slot_schedule(
                            rng_http, self.slot, first=(j == 0)
                        )
                    ]
                    self._drain()
                    wait(futures, timeout=cfg.request_timeout_s * 4)
                finally:
                    if restore is not None:
                        restore()
                    self._stall_s = 0.0
            # close the books before any counter is read: every
            # submitted item ends processed or shed, exactly once
            self._drain_fully()
        finally:
            pool.shutdown(wait=True)
            self.stop_sse()
            self._phase = "steady"
        wall = time.perf_counter() - t_start

        endpoints = {}
        duty_samples = []
        requests_total = errors_total = 0
        with self._lock:
            samples = {k: list(v) for k, v in self._samples.items()}
            errors = dict(self._errors)
            sse_counts = list(self._sse_counts)
        for name in sorted(samples):
            xs = samples[name]
            errs = errors.get(name, 0)
            requests_total += len(xs)
            errors_total += errs
            p = _pcts_ms(xs)
            endpoints[name] = {
                "requests": len(xs),
                "errors": errs,
                "p50_ms": p["p50"],
                "p95_ms": p["p95"],
                "p99_ms": p["p99"],
            }
            if name in DUTY_ENDPOINTS:
                duty_samples.extend(xs)

        received = (
            _counter_value(
                "beacon_processor_work_received_total", queue=att
            )
            - before["received"]
        )
        dropped = (
            _counter_value(
                "beacon_processor_work_dropped_total", queue=att
            )
            - before["dropped"]
        )
        processed = (
            _counter_value(
                "beacon_processor_work_processed_total", queue=att
            )
            - before["processed"]
        )
        misses = (
            _counter_value(
                "beacon_processor_deadline_misses_total", queue=att
            )
            - before["misses"]
        )
        by_reason = {}
        for (queue, reason), v in self._labeled_values(
            "beacon_processor_sheds_total"
        ).items():
            if queue != att:
                continue
            d = v - before["sheds"].get((queue, reason), 0.0)
            if d > 0:
                by_reason[reason] = int(d)
        return LoadReport(
            seed=cfg.seed,
            vcs=cfg.vcs,
            slots=cfg.slots,
            simulated_validators=cfg.simulated_validators,
            gossip_submitted=gossip_submitted,
            wall_s=round(wall, 3),
            requests_total=requests_total,
            errors_total=errors_total,
            endpoints=endpoints,
            duty_response_ms=_pcts_ms(duty_samples),
            shed={
                "received": int(received),
                "dropped": int(dropped),
                "rate": round(dropped / received, 6) if received else 0.0,
                "by_reason": by_reason,
            },
            deadline={
                "processed": int(processed),
                "misses": int(misses),
                "rate": round(misses / processed, 6) if processed else 0.0,
            },
            sse={
                "subscribers": len(sse_counts),
                "events_received": int(sum(sse_counts)),
                "events_sent": int(
                    self._sse_sent_total() - before["sse_sent"]
                ),
                "slow_client_drops": int(
                    _counter_value("http_sse_slow_clients_dropped_total")
                    - before["sse_drops"]
                ),
            },
            hash={
                "compressions": int(
                    self._hash_compressions_total() - before["hash_total"]
                ),
                "read_path": {
                    ep: int(v - before["hash_read"].get(ep, 0.0))
                    for ep, v in self._read_path_compressions().items()
                    if v - before["hash_read"].get(ep, 0.0) > 0
                },
            },
            overload=self._overload_section(
                over_before, overload_submitted, spells
            ),
        )

    def _overload_section(
        self, over_before, submitted: int, spells: tuple
    ) -> dict:
        """The graceful-degradation scoreboard for the overload phase:
        per-queue sheds by reason, per-queue in-queue expiries, the
        overload-phase duty SLO, and the condensed acceptance flags
        (fresh_block_sheds == 0, critical_deadline_misses == 0,
        order_ok, attestation_shed_rate > 0)."""
        from ..node.beacon_processor import (
            WORK_CLASS,
            PriorityClass,
            WorkType,
        )

        cfg = self.cfg
        base = {
            "slots": cfg.overload_slots,
            "burst_multiplier": max(
                [sp.magnitude for sp in spells if sp.kind == "burst"],
                default=1.0,
            ),
            "spells": [asdict(sp) for sp in spells],
            "gossip_submitted": int(submitted),
        }
        if over_before is None:  # overload disabled or replay aborted
            base.update(
                duty_response_ms=_pcts_ms([]),
                sheds={},
                deadline_misses={},
                attestation_shed_rate=0.0,
                fresh_block_sheds=0,
                critical_deadline_misses=0,
                critical_processed=0,
                order_ok=False,
            )
            return base
        att = WorkType.GOSSIP_ATTESTATION.name
        sheds: dict = {}
        for (queue, reason), v in self._labeled_values(
            "beacon_processor_sheds_total"
        ).items():
            d = v - over_before["sheds"].get((queue, reason), 0.0)
            if d > 0:
                sheds.setdefault(queue, {})[reason] = int(d)
        misses: dict = {}
        for lv, v in self._labeled_values(
            "beacon_processor_deadline_misses_total"
        ).items():
            d = v - over_before["misses"].get(lv, 0.0)
            if d > 0:
                misses[lv[0]] = int(d)
        critical = {
            t.name
            for t, c in WORK_CLASS.items()
            if c is PriorityClass.BLOCK_SYNC_CRITICAL
        }
        received = (
            _counter_value(
                "beacon_processor_work_received_total", queue=att
            )
            - over_before["received"]
        )
        att_shed = sum(sheds.get(att, {}).values())
        with self._lock:
            duty = list(self._duty_overload)
            log = list(self._order_log)
        base.update(
            duty_response_ms=_pcts_ms(duty),
            sheds=sheds,
            deadline_misses=misses,
            attestation_shed_rate=(
                round(att_shed / received, 6) if received else 0.0
            ),
            fresh_block_sheds=sum(
                n
                for q, rs in sheds.items()
                if q in critical
                for n in rs.values()
            ),
            critical_deadline_misses=sum(
                m for q, m in misses.items() if q in critical
            ),
            critical_processed=sum(
                1 for kind, _s in log if kind in ("block", "segment")
            ),
            order_ok=self._order_ok(log),
        )
        return base

    @staticmethod
    def _order_ok(log: list) -> bool:
        """Priority-chain proof from the execution-order log: within
        each injection window (entries after a 'mark'), once an
        attestation batch has been served no critical/aggregate item
        may follow — everything above the attestation class that was
        queued at injection time was served first."""
        windows: list = []
        cur = None
        for kind, _slot in log:
            if kind == "mark":
                cur = []
                windows.append(cur)
            elif cur is not None:
                cur.append(kind)
        if not windows:
            return False
        for w in windows:
            if "attestation" in w:
                first_att = w.index("attestation")
                if any(k != "attestation" for k in w[first_att:]):
                    return False
        return True

    @staticmethod
    def _sse_sent_total() -> float:
        fam = metrics.get("http_sse_events_sent_total")
        if fam is None:
            return 0.0
        return sum(fam.labels(*lv).value for lv in fam.label_values())

    @staticmethod
    def _hash_compressions_total() -> float:
        """All measured SHA-256 compressions so far (ISSUE 11 census
        counters) — the replay delta is the run's hashing bill."""
        fam = metrics.get("state_hash_compressions_total")
        if fam is None:
            return 0.0
        return sum(fam.labels(*lv).value for lv in fam.label_values())

    @staticmethod
    def _read_path_compressions() -> dict:
        fam = metrics.get("http_request_hash_compressions_total")
        if fam is None:
            return {}
        return {lv[0]: fam.labels(*lv).value for lv in fam.label_values()}

    def close(self) -> None:
        if self.server is not None:
            try:
                self.server.stop()
            except Exception:
                pass
        if self.sim is not None:
            try:
                self.sim.close()
            except Exception:
                pass


def run_load(cfg: LoadgenConfig = None, **kw) -> LoadReport:
    """Build the fleet, replay the seeded traffic shape, return the
    report. Raises LoadgenError when the fleet can't start (bench.py
    degrades to recording the error instead of the section)."""
    cfg = cfg or LoadgenConfig(**kw)
    # distinct Perfetto track per run: two exported traces diff
    # side-by-side instead of merging into one anonymous process
    tracing.next_run_id()
    fleet = _Fleet(cfg)
    try:
        return fleet.replay()
    finally:
        fleet.close()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="deterministic traffic-replay load harness"
    )
    ap.add_argument("--vcs", type=int, default=200)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--validators", type=int, default=16)
    ap.add_argument(
        "--simulated-validators", type=int, default=1_000_000
    )
    ap.add_argument("--gossip-scale", type=float, default=1 / 64.0)
    ap.add_argument("--http-workers", type=int, default=8)
    ap.add_argument("--sse-subscribers", type=int, default=2)
    ap.add_argument(
        "--overload-slots", type=int, default=4,
        help="length of the seeded 4x-overload fault-fleet phase "
        "(0 disables)",
    )
    args = ap.parse_args(argv)
    try:
        report = run_load(
            LoadgenConfig(
                vcs=args.vcs,
                seed=args.seed,
                slots=args.slots,
                n_validators=args.validators,
                simulated_validators=args.simulated_validators,
                gossip_scale=args.gossip_scale,
                http_workers=args.http_workers,
                sse_subscribers=args.sse_subscribers,
                overload_slots=args.overload_slots,
            )
        )
    except LoadgenError as e:
        print(json.dumps({"error": str(e), "schema": SCHEMA}))
        return 1
    doc = report.to_dict()
    problems = LoadReport.validate(doc)
    if problems:
        doc["schema_problems"] = problems
    print(json.dumps(doc, indent=2))
    return 1 if problems else 0
