"""Conformance-vector generation + replay (testing/ef_tests analog,
reference handler.rs:61-97).

Generation is DETERMINISTIC, so the committed regression pin is the
tiny root manifest (tests/vector_roots.json), not megabytes of state
blobs: the suite regenerates the vectors and any transition change
that alters a post-state flips its root against the manifest.

The reference freezes spec-team vectors and replays them; this
framework freezes ITS OWN golden vectors (generated once, committed)
so every later refactor of the transition replays byte-identical
cases — the regression-oracle role. Layout, one directory per case:

    <suite>/<case>/pre.ssz        BeaconState before
    <suite>/<case>/blocks_0.ssz.. SignedBeaconBlocks to apply in order
    <suite>/<case>/post.ssz       expected BeaconState after
    <suite>/<case>/meta.json      {"spec": ..., "description": ...}

Cases cover: empty-slot advance, single block, multi-block with a
skipped slot, an epoch boundary, and (electra spec) a block carrying an
EL deposit request.
"""

from __future__ import annotations

import json
from pathlib import Path

from ..consensus import state_transition as st
from ..consensus import types as T
from ..consensus.spec import ChainSpec, mainnet_spec


def _electra_mainnet() -> ChainSpec:
    spec = mainnet_spec()
    spec.fork_epochs = dict(spec.fork_epochs)
    spec.fork_epochs["electra"] = 0
    return spec


def _produce(spec, state, slot, mutate_body=None):
    """A valid (unsigned-crypto) block on `state` at `slot`; advances
    the state."""
    if state.slot < slot:
        st.process_slots(spec, state, slot)
    proposer = st.get_beacon_proposer_index(spec, state)
    body = T.BeaconBlockBody.default()
    body.randao_reveal = b"\xc0" + b"\x00" * 95
    body.eth1_data = state.eth1_data
    body.execution_payload = st.mock_execution_payload(spec, state)
    if mutate_body is not None:
        mutate_body(body)
    # _process_slot filled the cached header's state_root, so its root
    # IS the canonical parent root now
    block = T.BeaconBlock.make(
        slot=slot,
        proposer_index=proposer,
        parent_root=state.latest_block_header.hash_tree_root(),
        state_root=b"\x00" * 32,
        body=body,
    )
    st.process_block(spec, state, block, verify_signatures=False)
    block.state_root = state.hash_tree_root()
    return T.SignedBeaconBlock.make(
        message=block, signature=b"\xc0" + b"\x00" * 95
    )


def generate(out_dir, spec: ChainSpec = None, validators: int = 16) -> list:
    """Write the suite; returns case names. Deterministic — a second
    run reproduces identical bytes (interop keys, fixed graffiti)."""
    spec = spec or mainnet_spec()
    out = Path(out_dir)
    cases = []

    def emit(name, pre, blocks, post, description):
        d = out / name
        d.mkdir(parents=True, exist_ok=True)
        (d / "pre.ssz").write_bytes(pre.serialize())
        for i, b in enumerate(blocks):
            (d / f"blocks_{i}.ssz").write_bytes(
                T.SignedBeaconBlock.serialize(b)
            )
        (d / "post.ssz").write_bytes(post.serialize())
        (d / "meta.json").write_text(
            json.dumps(
                {
                    "spec": spec.config_name,
                    "electra_epoch": spec.fork_epochs.get("electra"),
                    "description": description,
                    "blocks": len(blocks),
                    "post_root": "0x" + post.hash_tree_root().hex(),
                }
            )
        )
        cases.append(name)

    genesis = st.interop_genesis_state(spec, st.interop_pubkeys(validators))

    # 1: pure slot advance across an epoch boundary
    pre = genesis.copy()
    post = pre.copy()
    st.process_slots(spec, post, spec.preset.slots_per_epoch + 1)
    emit("slots_epoch_boundary", pre, [], post,
         "process_slots across one epoch boundary")

    # 2: one block at slot 1
    pre = genesis.copy()
    work = pre.copy()
    b1 = _produce(spec, work, 1)
    emit("single_block", pre, [b1], work, "one empty-body block")

    # 3: two blocks with a skipped slot between
    pre = genesis.copy()
    work = pre.copy()
    blocks = [_produce(spec, work, 1), _produce(spec, work, 3)]
    emit("skipped_slot", pre, blocks, work,
         "blocks at slots 1 and 3 (slot 2 skipped)")

    # 4 (electra): a block carrying an EL deposit request
    espec = _electra_mainnet()
    egen = st.interop_genesis_state(espec, st.interop_pubkeys(validators))
    pre = egen.copy()
    work = pre.copy()

    def add_request(body):
        body.execution_requests = T.ExecutionRequests.make(
            deposits=[
                T.DepositRequest.make(
                    pubkey=bytes(work.validators[2].pubkey),
                    withdrawal_credentials=bytes(
                        work.validators[2].withdrawal_credentials
                    ),
                    amount=10**9,
                    signature=b"\x00" * 96,
                    index=0,
                )
            ],
            withdrawals=[],
            consolidations=[],
        )

    eb = _produce(espec, work, 1, mutate_body=add_request)
    d = Path(out_dir) / "electra_deposit_request"
    d.mkdir(parents=True, exist_ok=True)
    (d / "pre.ssz").write_bytes(pre.serialize())
    (d / "blocks_0.ssz").write_bytes(T.SignedBeaconBlock.serialize(eb))
    (d / "post.ssz").write_bytes(work.serialize())
    (d / "meta.json").write_text(
        json.dumps(
            {
                "spec": espec.config_name,
                "electra_epoch": 0,
                "description": "EL deposit request enters the pending queue",
                "blocks": 1,
                "post_root": "0x" + work.hash_tree_root().hex(),
            }
        )
    )
    cases.append("electra_deposit_request")
    return cases


def replay_case(case_dir) -> None:
    """Handler: load pre, apply blocks (or slot-advance to post.slot),
    byte-compare against post (ef_tests cases::run)."""
    d = Path(case_dir)
    meta = json.loads((d / "meta.json").read_text())
    spec = mainnet_spec()
    if meta.get("electra_epoch") == 0:
        spec = _electra_mainnet()
    state = T.BeaconState.deserialize((d / "pre.ssz").read_bytes())
    post_raw = (d / "post.ssz").read_bytes()
    post = T.BeaconState.deserialize(post_raw)
    i = 0
    while (d / f"blocks_{i}.ssz").exists():
        signed = T.SignedBeaconBlock.deserialize(
            (d / f"blocks_{i}.ssz").read_bytes()
        )
        block = signed.message
        if state.slot < block.slot:
            st.process_slots(spec, state, int(block.slot))
        st.process_block(spec, state, block, verify_signatures=False)
        i += 1
    if i == 0 and state.slot < post.slot:
        st.process_slots(spec, state, int(post.slot))
    got_root = state.hash_tree_root()
    want_root = bytes.fromhex(meta["post_root"][2:])
    if got_root != want_root:
        raise AssertionError(
            f"{d.name}: post-state root mismatch "
            f"(got 0x{got_root.hex()[:16]}, want 0x{want_root.hex()[:16]})"
        )
    if state.serialize() != post_raw:
        raise AssertionError(f"{d.name}: post-state bytes differ")
