"""In-process multi-node simulator — testing/simulator analog.

Spins N FULL node assemblies (BeaconChain + BeaconProcessor +
NetworkService + NetworkBeaconProcessor + SyncManager) and their
validator clients in one process on the in-process hub, exactly the
reference's posture (testing/simulator/src/basic_sim.rs:36-40 runs N
production BNs+VCs on one tokio runtime; node_test_rig/src/lib.rs:1-36).

The validator set is split across nodes; every block and attestation
travels over GOSSIP (not direct chain calls), so the simulation
exercises verification pipelines, fork choice, the naive aggregation
pool, the operation pool, range sync and peer scoring the way a real
network does. The accelerated "slot clock" is the driver loop calling
per-slot phases back-to-back (speed_up_factor role, basic_sim.rs:36).

Checks mirror simulator/src/checks.rs: liveness (head advances),
consistency (all heads equal when connected), and finality (finalized
epoch advances past the target), plus an optional mid-run
partition/heal fault (fallback_sim's node-kill analog on the hub's
partition seam)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ..consensus import state_transition as st
from ..consensus import types as T
from ..consensus.spec import ChainSpec, mainnet_spec
from ..crypto.bls.keys import SecretKey
from ..node.beacon_chain import BeaconChain
from ..node.beacon_processor import BeaconProcessor
from ..network.gossip import (
    TOPIC_ATTESTATION_SUBNET,
    TOPIC_BLOCK,
    topic_for,
)
from ..network.network_beacon_processor import NetworkBeaconProcessor
from ..network.subnet_service import compute_subnet_for_attestation
from ..network.sync import SyncManager
from ..network.service import NetworkService
from ..network.transport import InProcessHub
from ..validator import LocalKeystoreSigner, ValidatorClient, ValidatorStore
from ..validator.client import InProcessBeaconNode

ATTESTATION_SUBNET_COUNT = 64


class GossipBeaconNode(InProcessBeaconNode):
    """BeaconNodeApi whose publish side goes over gossip — what the
    reference VC's HTTP publish endpoints do on a real BN."""

    def __init__(self, chain, nbp, spec):
        super().__init__(chain)
        self.nbp = nbp
        self.spec = spec

    def publish_block(self, signed_block):
        # local import first (proposer's own head), then gossip
        self.chain.process_block(signed_block)
        self.nbp.publish_block(signed_block)

    def publish_attestation(self, attestation):
        super().publish_attestation(attestation)  # local pipeline
        state = self.chain.head_state()
        cps = st.get_committee_count_per_slot(
            self.spec,
            state,
            st.compute_epoch_at_slot(self.spec, int(attestation.data.slot)),
        )
        subnet = compute_subnet_for_attestation(
            self.spec, cps, int(attestation.data.slot), int(attestation.data.index)
        )
        self.nbp.publish_attestation(attestation, subnet=subnet)


@dataclass
class SimChecks:
    head_slots: list = field(default_factory=list)
    finalized_epoch: int = 0
    consistent_heads: bool = True


class SimNode:
    """One full BN+VC assembly on the hub."""

    def __init__(self, hub, name, spec, genesis_state, keys, fork_digest):
        self.name = name
        self.chain = BeaconChain(spec, genesis_state, bls_backend="fake")
        self.processor = BeaconProcessor()
        self.service = NetworkService(hub, name)
        self.service.subscribe(topic_for(TOPIC_BLOCK, fork_digest))
        for subnet in range(ATTESTATION_SUBNET_COUNT):
            self.service.subscribe(
                topic_for(TOPIC_ATTESTATION_SUBNET, fork_digest, subnet)
            )
        self.nbp = NetworkBeaconProcessor(
            self.chain, self.processor, self.service, fork_digest=fork_digest
        )
        self.sync = SyncManager(self.chain, self.processor, self.service, self.nbp)
        store = ValidatorStore(spec, self.chain.genesis_validators_root)
        for k in keys:
            store.add_validator(LocalKeystoreSigner(k))
        self.vc = ValidatorClient(
            spec, store, GossipBeaconNode(self.chain, self.nbp, spec)
        )

    def pump(self) -> int:
        n = 0
        for ev in self.service.poll():
            self.nbp.handle_gossip(ev.peer_id, ev.topic, ev.data)
            n += 1
        while self.processor.step():
            n += 1
        return n


class Simulation:
    """N nodes, full-mesh connectivity, validators split round-robin.

    `transport="inproc"` (default) runs all nodes on one InProcessHub —
    fast, and the only mode supporting the partition fault seam.
    `transport="libp2p"` gives every node its own Libp2pEndpoint on a
    real localhost socket: gossip and sync travel as
    mss/noise/yamux/gossipsub-protobuf frames on the wire, the same
    stack `cli bn` runs by default."""

    def __init__(
        self,
        n_nodes: int = 4,
        n_validators: int = 32,
        spec: ChainSpec = None,
        electra_fork_epoch: int = None,
        transport: str = "inproc",
    ):
        self.spec = spec or mainnet_spec()
        if electra_fork_epoch is not None:
            self.spec.fork_epochs = dict(self.spec.fork_epochs)
            self.spec.fork_epochs["electra"] = electra_fork_epoch
        self.transport = transport
        keys = [SecretKey.from_seed(i.to_bytes(4, "big")) for i in range(n_validators)]
        pubkeys = [k.public_key().to_bytes() for k in keys]
        genesis = st.interop_genesis_state(self.spec, pubkeys)
        digest = b"\x00" * 4
        self.nodes = []
        if transport == "libp2p":
            from ..network.libp2p_transport import Libp2pHub

            self.hub = None
            for i in range(n_nodes):
                self.nodes.append(
                    SimNode(
                        Libp2pHub(),
                        f"node{i}",
                        self.spec,
                        genesis.copy(),
                        keys[i::n_nodes],
                        digest,
                    )
                )
            # full mesh over real sockets: dial once per pair; the
            # accepting side grafts via on_peer_connected
            for i, a in enumerate(self.nodes):
                for b in self.nodes[i + 1 :]:
                    a.service.connect_remote(*b.service.endpoint.addr)
        else:
            self.hub = InProcessHub()
            for i in range(n_nodes):
                self.nodes.append(
                    SimNode(
                        self.hub,
                        f"node{i}",
                        self.spec,
                        genesis.copy(),
                        keys[i::n_nodes],
                        digest,
                    )
                )
            for i, a in enumerate(self.nodes):
                for b in self.nodes[i + 1 :]:
                    a.service.connect_peer(b.service)

    def settle(self, rounds: int = 50) -> None:
        import time as _time

        # over sockets a quiescent poll doesn't mean the network is
        # drained — frames may be in flight; require a few consecutive
        # idle rounds with a small wait between them
        idle_needed = 3 if self.transport == "libp2p" else 1
        idle = 0
        for _ in range(rounds):
            if sum(n.pump() for n in self.nodes) == 0:
                idle += 1
                if idle >= idle_needed:
                    break
                _time.sleep(0.05)
            else:
                idle = 0

    def run_slot(self, slot: int) -> None:
        for n in self.nodes:
            n.chain.on_slot(slot)
        for n in self.nodes:
            n.vc.on_slot_start(slot)       # propose (duty holder only)
        self.settle()
        for n in self.nodes:
            n.vc.on_slot_third(slot)       # attest
        self.settle()
        for n in self.nodes:
            n.vc.on_slot_two_thirds(slot)  # aggregate (local pools)
        self.settle()

    def run(
        self,
        until_epoch: int,
        partition: tuple = None,
        heal_margin_epochs: int = 2,
    ) -> SimChecks:
        """Drive slots until `until_epoch` ends. `partition`
        = (victim_index, start_slot, end_slot): the victim node is cut
        from every peer between those slots, then healed and
        range-synced back (fault injection, transport.py's partition
        seam)."""
        spe = self.spec.preset.slots_per_epoch
        last_slot = until_epoch * spe
        checks = SimChecks()
        victim = None
        if partition and self.transport != "inproc":
            raise ValueError(
                "partition fault injection needs the in-process hub"
            )
        for slot in range(1, last_slot + 1):
            if partition and slot == partition[1]:
                victim = self.nodes[partition[0]]
                for other in self.nodes:
                    if other is not victim:
                        self.hub.partition(victim.name, other.name)
            if partition and slot == partition[2]:
                for other in self.nodes:
                    if other is not victim:
                        self.hub.heal(victim.name, other.name)
                for other in self.nodes:
                    if other is not victim:
                        victim.sync.add_peer(other.name)
                self.settle()
                victim.sync.tick()
                self.settle()
            self.run_slot(slot)
            checks.head_slots.append(
                max(int(n.chain.head.slot) for n in self.nodes)
            )
        self.settle()
        heads = {bytes(n.chain.head.root) for n in self.nodes}
        checks.consistent_heads = len(heads) == 1
        checks.finalized_epoch = max(
            int(n.chain.head_state().finalized_checkpoint.epoch)
            for n in self.nodes
        )
        return checks

    def close(self) -> None:
        """Tear down socket transports (no-op for the in-process hub)."""
        for n in self.nodes:
            ep = n.service.endpoint
            if hasattr(ep, "close"):
                ep.close()
