"""In-process multi-node simulator + deterministic chaos-scenario
fleet — testing/simulator analog grown past the reference (ISSUE 7).

Spins N FULL node assemblies (BeaconChain + BeaconProcessor +
NetworkService + NetworkBeaconProcessor + SyncManager) and their
validator clients in one process on the in-process hub, exactly the
reference's posture (testing/simulator/src/basic_sim.rs:36-40 runs N
production BNs+VCs on one tokio runtime; node_test_rig/src/lib.rs:1-36).

The validator set is split across nodes; every block and attestation
travels over GOSSIP (not direct chain calls), so the simulation
exercises verification pipelines, fork choice, the naive aggregation
pool, the operation pool, per-chain range sync and peer scoring the way
a real network does. The accelerated "slot clock" is the driver loop
calling per-slot phases back-to-back (speed_up_factor role,
basic_sim.rs:36); every node's SyncManager ticks once per slot, the
production node loop's cadence.

Checks mirror simulator/src/checks.rs: liveness (head advances),
consistency (all heads equal when connected), finality (finalized
epoch advances past the target) — plus convergence tracking: the first
slot after the last fault window at which every node agrees on one
head.

Faults are first-class (`Fault` subclasses passed to `run(faults=...)`,
each a seeded, deterministic, in-process scenario seam):

  Partition            cut a node group from the rest (both ways), heal
                       + re-handshake at window end
  Partition(oneway=)   asymmetric cut: the group can speak but not
                       hear — requests leave, responses vanish (the
                       stall-detection shape)
  LateProposer         the duty holder's block is imported + gossiped
                       one slot late (no proposer boost, attesters vote
                       the old head)
  EquivocatingProposer the duty holder signs TWO conflicting blocks for
                       its slot and gossips both
  WithholdingPeer      a node keeps advertising its head but serves
                       empty (or garbage) BlocksByRange/Root
  OfflineSpell         a node group's validators go silent (validator
                       churn; >=1/3 silent = a non-finality spell)

tests/test_scenarios.py drives the fleet fast on the minimal preset in
tier-1; tests/test_simulator.py keeps the slow mainnet-preset runs.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field, replace
from typing import Optional

from ..consensus import state_transition as st
from ..consensus import types as T
from ..consensus.spec import MAINNET_PRESET, ChainSpec, mainnet_spec
from ..crypto.bls.keys import SecretKey
from ..node.beacon_chain import BeaconChain
from ..node.beacon_processor import BeaconProcessor, BeaconProcessorConfig
from ..network.gossip import (
    TOPIC_AGGREGATE,
    TOPIC_ATTESTATION_SUBNET,
    TOPIC_BLOCK,
    topic_for,
)
from ..network.network_beacon_processor import NetworkBeaconProcessor
from ..network.rpc import Protocol, ResponseCode
from ..network.subnet_service import compute_subnet_for_attestation
from ..network.sync import SyncManager
from ..network.service import NetworkService
from ..network.transport import InProcessHub
from ..validator import (
    FakeSigner,
    LocalKeystoreSigner,
    ValidatorClient,
    ValidatorStore,
)
from ..validator.client import InProcessBeaconNode

ATTESTATION_SUBNET_COUNT = 64


def scenario_spec(slots_per_epoch: int = 8) -> ChainSpec:
    """Fast-scenario spec: epochs shrink to `slots_per_epoch` so
    justification/finality cycles complete in a few dozen slots, while
    every SSZ-size constant stays MAINNET (the type layer is bound to
    the mainnet preset; slots_per_epoch only drives epoch math, and the
    one list limit derived from it — eth1_data_votes — is an upper
    bound a shorter voting period can't exceed)."""
    return ChainSpec(
        preset=replace(
            MAINNET_PRESET, name="scenario", slots_per_epoch=slots_per_epoch
        )
    )


class GossipBeaconNode(InProcessBeaconNode):
    """BeaconNodeApi whose publish side goes over gossip — what the
    reference VC's HTTP publish endpoints do on a real BN. The block
    publish path carries a fault seam: a scenario hook may consume the
    publish (delay it, twin it, drop it)."""

    def __init__(self, chain, nbp, spec, node=None):
        super().__init__(chain)
        self.nbp = nbp
        self.spec = spec
        self.node = node  # SimNode back-ref for fault hooks

    def publish_block(self, signed_block):
        hook = getattr(self.node, "block_publish_hook", None)
        if hook is not None and hook(self.node, signed_block):
            return  # the fault seam consumed this publish
        # local import first (proposer's own head), then gossip
        self.chain.process_block(signed_block)
        self.nbp.publish_block(signed_block)

    def publish_aggregate(self, signed_aggregate):
        super().publish_aggregate(signed_aggregate)  # local verify + pools
        # fan out over the aggregate topic: peers route it through the
        # AGGREGATE priority lane (class 1) of their schedulers
        self.nbp.publish_aggregate(signed_aggregate)

    def publish_attestation(self, attestation):
        super().publish_attestation(attestation)  # local pipeline
        state = self.chain.head_state()
        cps = st.get_committee_count_per_slot(
            self.spec,
            state,
            st.compute_epoch_at_slot(self.spec, int(attestation.data.slot)),
        )
        subnet = compute_subnet_for_attestation(
            self.spec, cps, int(attestation.data.slot), int(attestation.data.index)
        )
        self.nbp.publish_attestation(attestation, subnet=subnet)


@dataclass
class SimChecks:
    head_slots: list = field(default_factory=list)
    finalized_epoch: int = 0
    min_finalized_epoch: int = 0
    consistent_heads: bool = True
    # first slot >= the last fault window's end at which every node
    # agreed on one head (None = never converged)
    convergence_slot: Optional[int] = None
    final_heads: list = field(default_factory=list)
    # finalized epoch observed at each epoch boundary (non-finality
    # spell assertions read the plateau out of this)
    finalized_by_epoch: dict = field(default_factory=dict)


class SimNode:
    """One full BN+VC assembly on the hub."""

    def __init__(self, hub, name, spec, genesis_state, keys, fork_digest,
                 chain=None, fake_signing=False):
        self.name = name
        self.chain = chain if chain is not None else BeaconChain(
            spec, genesis_state, bls_backend="fake"
        )
        # validator-count-derived queue capacities (dwarf fleets land
        # on the floors; the priority chain is what the scenarios test)
        self.processor = BeaconProcessor(
            BeaconProcessorConfig.for_validator_count(
                len(genesis_state.validators) if genesis_state is not None
                else 0,
                slots_per_epoch=spec.preset.slots_per_epoch,
            )
        )
        self.service = NetworkService(hub, name)
        self.service.subscribe(topic_for(TOPIC_BLOCK, fork_digest))
        self.service.subscribe(topic_for(TOPIC_AGGREGATE, fork_digest))
        for subnet in range(ATTESTATION_SUBNET_COUNT):
            self.service.subscribe(
                topic_for(TOPIC_ATTESTATION_SUBNET, fork_digest, subnet)
            )
        self.nbp = NetworkBeaconProcessor(
            self.chain, self.processor, self.service, fork_digest=fork_digest
        )
        self.sync = SyncManager(self.chain, self.processor, self.service, self.nbp)
        store = ValidatorStore(spec, self.chain.genesis_validators_root)
        signer = FakeSigner if fake_signing else LocalKeystoreSigner
        for k in keys:
            store.add_validator(signer(k))
        self.vc = ValidatorClient(
            spec, store, GossipBeaconNode(self.chain, self.nbp, spec, node=self)
        )
        # fault seams
        self.block_publish_hook = None  # callable(node, signed) -> bool
        self.offline = False  # validators silent (OfflineSpell)

    def pump(self) -> int:
        n = 0
        for ev in self.service.poll():
            self.nbp.handle_gossip(ev.peer_id, ev.topic, ev.data)
            n += 1
        # bounced sync-critical work (bounded retry-with-requeue)
        # re-enters the live queues before the drain
        n += self.processor.pump_reprocess(time.perf_counter())
        while self.processor.step():
            n += 1
        return n


# ------------------------------------------------------------------ faults


class Fault:
    """One deterministic fault seam; `run()` drives the hooks."""

    def on_slot_start(self, sim: "Simulation", slot: int) -> None:
        pass

    def on_slot_end(self, sim: "Simulation", slot: int) -> None:
        pass

    @property
    def horizon(self) -> int:
        """Last slot at which this fault is active (convergence is only
        measured after every fault's horizon)."""
        return 0


class Partition(Fault):
    """Cut `group` (node indices) from the rest between start and end
    slot. `oneway=True` drops only frames INTO the group (the group
    speaks but cannot hear). Heal re-handshakes both directions so
    range sync learns the other side's target."""

    def __init__(self, group, start_slot: int, end_slot: int,
                 oneway: bool = False):
        self.group = [group] if isinstance(group, int) else list(group)
        self.start_slot = start_slot
        self.end_slot = end_slot
        self.oneway = oneway

    @property
    def horizon(self) -> int:
        return self.end_slot

    def _pairs(self, sim):
        members = {sim.nodes[i].name for i in self.group}
        for i in self.group:
            victim = sim.nodes[i]
            for other in sim.nodes:
                if other.name not in members:
                    yield victim, other

    def on_slot_start(self, sim, slot: int) -> None:
        if slot == self.start_slot:
            for victim, other in self._pairs(sim):
                if self.oneway:
                    sim.hub.partition_oneway(other.name, victim.name)
                else:
                    sim.hub.partition(victim.name, other.name)
        if slot == self.end_slot:
            for victim, other in self._pairs(sim):
                if self.oneway:
                    sim.hub.heal_oneway(other.name, victim.name)
                else:
                    sim.hub.heal(victim.name, other.name)
                # full re-graft (scores may have disconnected peers
                # while their requests black-holed) + fresh handshakes:
                # the status exchange is what classifies each side into
                # the other's head chain for range sync
                victim.service.connect_peer(other.service)
            sim.settle()
            for victim, other in self._pairs(sim):
                victim.sync.add_peer(other.name)
                other.sync.add_peer(victim.name)
            sim.settle()
            for victim, _ in self._pairs(sim):
                victim.sync.tick()
            sim.settle()


class LateProposer(Fault):
    """Blocks produced at `slots` are imported + gossiped one slot
    late: attesters vote the previous head that slot, the block arrives
    past its slot (no proposer boost) — the classic late-block reorg
    shape."""

    def __init__(self, slots):
        self.slots = set(slots)
        self._delayed: list = []

    @property
    def horizon(self) -> int:
        return max(self.slots) + 1 if self.slots else 0

    def on_slot_start(self, sim, slot: int) -> None:
        for node, signed in self._delayed:
            node.chain.process_block(signed)
            node.nbp.publish_block(signed)
        self._delayed.clear()
        if slot in self.slots:
            def hook(node, signed):
                self._delayed.append((node, signed))
                return True

            for n in sim.nodes:
                n.block_publish_hook = hook
        else:
            for n in sim.nodes:
                n.block_publish_hook = None


class EquivocatingProposer(Fault):
    """The duty holder at each of `slots` signs TWO conflicting blocks
    (distinct graffiti => distinct state roots) and gossips both — the
    proposer-equivocation attack. Both import everywhere; fork choice
    arbitrates one winner deterministically."""

    def __init__(self, slots):
        self.slots = set(slots)

    @property
    def horizon(self) -> int:
        return max(self.slots) if self.slots else 0

    def on_slot_start(self, sim, slot: int) -> None:
        if slot not in self.slots:
            for n in sim.nodes:
                n.block_publish_hook = None
            return

        def hook(node, signed):
            msg = signed.message
            twin = None
            try:
                twin_msg = node.chain.produce_block(
                    int(msg.slot),
                    randao_reveal=bytes(msg.body.randao_reveal),
                    graffiti=b"\x66" * 32,
                )
                twin = T.SignedBeaconBlock.make(
                    message=twin_msg, signature=bytes(signed.signature)
                )
            except Exception:
                pass  # equivocation is best-effort; the honest block flows
            node.chain.process_block(signed)
            node.nbp.publish_block(signed)
            if twin is not None:
                node.nbp.publish_block(twin)
            return True

        for n in sim.nodes:
            n.block_publish_hook = hook


class WithholdingPeer(Fault):
    """Node `node` keeps its status honest but serves empty
    (garbage=False) or undecodable (garbage=True) block responses —
    the advertise-and-withhold peer range sync must route around."""

    def __init__(self, node: int, start_slot: int, end_slot: int,
                 garbage: bool = False):
        self.node = node
        self.start_slot = start_slot
        self.end_slot = end_slot
        self.garbage = garbage
        self._saved: dict = {}

    @property
    def horizon(self) -> int:
        return self.end_slot

    def on_slot_start(self, sim, slot: int) -> None:
        rpc = sim.nodes[self.node].service.rpc
        if slot == self.start_slot:
            if self.garbage:
                def handler(peer, body):
                    return ResponseCode.SUCCESS, [b"\xff\xfegarbage"]
            else:
                def handler(peer, body):
                    return ResponseCode.SUCCESS, []
            for proto in (Protocol.BLOCKS_BY_RANGE, Protocol.BLOCKS_BY_ROOT):
                self._saved[proto] = rpc.handlers.get(proto)
                rpc.register(proto, handler)
        if slot == self.end_slot:
            for proto, h in self._saved.items():
                if h is not None:
                    rpc.register(proto, h)
            self._saved.clear()


class OfflineSpell(Fault):
    """The validators of `group` go silent for the window (no
    proposals, no attestations): validator churn when < 1/3 of stake,
    a non-finality spell when >= 1/3."""

    def __init__(self, group, start_slot: int, end_slot: int):
        self.group = [group] if isinstance(group, int) else list(group)
        self.start_slot = start_slot
        self.end_slot = end_slot

    @property
    def horizon(self) -> int:
        return self.end_slot

    def on_slot_start(self, sim, slot: int) -> None:
        if slot == self.start_slot:
            for i in self.group:
                sim.nodes[i].offline = True
        if slot == self.end_slot:
            for i in self.group:
                sim.nodes[i].offline = False


# ------------------------------------------------------------------ sim


class Simulation:
    """N nodes, full-mesh connectivity, validators split round-robin.

    `transport="inproc"` (default) runs all nodes on one InProcessHub —
    fast, and the only mode supporting the fault seams.
    `transport="libp2p"` gives every node its own Libp2pEndpoint on a
    real localhost socket: gossip and sync travel as
    mss/noise/yamux/gossipsub-protobuf frames on the wire, the same
    stack `cli bn` runs by default.

    `seed` feeds `self.rng` — scenarios derive any randomized fault
    scheduling from it, so every run is reproducible."""

    def __init__(
        self,
        n_nodes: int = 4,
        n_validators: int = 32,
        spec: ChainSpec = None,
        electra_fork_epoch: int = None,
        transport: str = "inproc",
        seed: int = 0,
        sync_batch_timeout: float = 1.0,
        fake_signing: bool = False,
    ):
        self.spec = spec or mainnet_spec()
        if electra_fork_epoch is not None:
            self.spec.fork_epochs = dict(self.spec.fork_epochs)
            self.spec.fork_epochs["electra"] = electra_fork_epoch
        self.transport = transport
        self.rng = random.Random(seed)
        self.keys = [
            SecretKey.from_seed(i.to_bytes(4, "big"))
            for i in range(n_validators)
        ]
        pubkeys = [k.public_key().to_bytes() for k in self.keys]
        genesis = st.interop_genesis_state(self.spec, pubkeys)
        self.genesis = genesis
        self.fork_digest = b"\x00" * 4
        self.nodes = []
        if transport == "libp2p":
            from ..network.libp2p_transport import Libp2pHub

            self.hub = None
            for i in range(n_nodes):
                self.nodes.append(
                    SimNode(
                        Libp2pHub(),
                        f"node{i}",
                        self.spec,
                        genesis.copy(),
                        self.keys[i::n_nodes],
                        self.fork_digest,
                        fake_signing=fake_signing,
                    )
                )
            # full mesh over real sockets: dial once per pair; the
            # accepting side grafts via on_peer_connected
            for i, a in enumerate(self.nodes):
                for b in self.nodes[i + 1 :]:
                    a.service.connect_remote(*b.service.endpoint.addr)
        else:
            self.hub = InProcessHub()
            for i in range(n_nodes):
                self.nodes.append(
                    SimNode(
                        self.hub,
                        f"node{i}",
                        self.spec,
                        genesis.copy(),
                        self.keys[i::n_nodes],
                        self.fork_digest,
                        fake_signing=fake_signing,
                    )
                )
            for i, a in enumerate(self.nodes):
                for b in self.nodes[i + 1 :]:
                    a.service.connect_peer(b.service)
            # initial status handshakes: every node learns every peer's
            # chain status up front (discovery+status exchange role), so
            # range sync has targets the moment someone falls behind
            for a in self.nodes:
                a.sync.batch_timeout = sync_batch_timeout
                for b in self.nodes:
                    if a is not b:
                        a.sync.add_peer(b.name)
            self.settle()

    def add_checkpoint_node(self, source_idx: int = 0) -> SimNode:
        """Join a FRESH node mid-run via weak-subjectivity checkpoint
        sync off `source_idx`'s finalized checkpoint: it follows the
        head via range sync immediately and backfills history
        genesis-ward — under whatever gossip load the run applies."""
        if self.transport != "inproc":
            raise ValueError("checkpoint join needs the in-process hub")
        src = self.nodes[source_idx].chain
        fin_root = src.fork_choice.finalized_checkpoint[1]
        anchor_block = src.store.get_block(fin_root)
        anchor_state = src.state_for_block(fin_root)
        chain = BeaconChain.from_checkpoint(
            self.spec, anchor_state.copy(), anchor_block, bls_backend="fake"
        )
        node = SimNode(
            self.hub,
            f"node{len(self.nodes)}",
            self.spec,
            None,
            [],
            self.fork_digest,
            chain=chain,
        )
        node.sync.batch_timeout = self.nodes[0].sync.batch_timeout
        node.chain.on_slot(max(int(n.chain.current_slot) for n in self.nodes))
        self.nodes.append(node)
        for other in self.nodes[:-1]:
            node.service.connect_peer(other.service)
        self.settle()
        for other in self.nodes[:-1]:
            node.sync.add_peer(other.name)
            other.sync.add_peer(node.name)
        self.settle()
        node.sync.tick()
        self.settle()
        return node

    def settle(self, rounds: int = 50) -> None:
        import time as _time

        # over sockets a quiescent poll doesn't mean the network is
        # drained — frames may be in flight; require a few consecutive
        # idle rounds with a small wait between them
        idle_needed = 3 if self.transport == "libp2p" else 1
        idle = 0
        for _ in range(rounds):
            if sum(n.pump() for n in self.nodes) == 0:
                idle += 1
                if idle >= idle_needed:
                    break
                _time.sleep(0.05)
            else:
                idle = 0

    def run_slot(self, slot: int) -> None:
        for n in self.nodes:
            n.chain.on_slot(slot)
        for n in self.nodes:
            if not n.offline:
                n.vc.on_slot_start(slot)       # propose (duty holder only)
        self.settle()
        for n in self.nodes:
            if not n.offline:
                n.vc.on_slot_third(slot)       # attest
        self.settle()
        for n in self.nodes:
            if not n.offline:
                n.vc.on_slot_two_thirds(slot)  # aggregate (local pools)
        self.settle()
        # the node loop ticks sync every pump (node/client.py tick());
        # once per slot is the accelerated-clock equivalent
        for n in self.nodes:
            n.sync.tick()
        self.settle()

    def heads(self) -> set:
        return {bytes(n.chain.head.root) for n in self.nodes}

    def converge(self, max_rounds: int = 64) -> bool:
        """Post-run drain: keep ticking sync + settling until every
        node agrees on one head (or rounds run out). Range sync needs
        a few request->process->request cycles to walk a long gap."""
        for _ in range(max_rounds):
            if len(self.heads()) == 1:
                return True
            for n in self.nodes:
                n.sync.tick()
            self.settle()
        return len(self.heads()) == 1

    def run(
        self,
        until_epoch: int,
        partition: tuple = None,
        heal_margin_epochs: int = 2,
        faults: list = None,
    ) -> SimChecks:
        """Drive slots until `until_epoch` ends. `partition`
        = (victim_index, start_slot, end_slot) is legacy sugar for
        `faults=[Partition([victim_index], start, end)]`."""
        spe = self.spec.preset.slots_per_epoch
        last_slot = until_epoch * spe
        checks = SimChecks()
        faults = list(faults or [])
        if partition:
            faults.append(
                Partition([partition[0]], partition[1], partition[2])
            )
        if faults and self.transport != "inproc":
            raise ValueError("fault injection needs the in-process hub")
        fault_horizon = max((f.horizon for f in faults), default=0)
        for slot in range(1, last_slot + 1):
            for f in faults:
                f.on_slot_start(self, slot)
            self.run_slot(slot)
            for f in faults:
                f.on_slot_end(self, slot)
            checks.head_slots.append(
                max(int(n.chain.head.slot) for n in self.nodes)
            )
            if slot % spe == 0:
                checks.finalized_by_epoch[slot // spe] = max(
                    int(n.chain.head_state().finalized_checkpoint.epoch)
                    for n in self.nodes
                )
            if (
                checks.convergence_slot is None
                and slot >= fault_horizon
                and len(self.heads()) == 1
            ):
                checks.convergence_slot = slot
        self.settle()
        if (
            checks.convergence_slot is None
            and last_slot >= fault_horizon
            and len(self.heads()) == 1
        ):
            # the final settle finished the job inside the run window
            checks.convergence_slot = last_slot
        if len(self.heads()) != 1:
            # post-run drain: extra sync rounds may still heal the fleet
            # (consistent_heads reflects it) but convergence_slot stays
            # None — convergence did NOT happen during the run
            self.converge()
        checks.final_heads = sorted(h.hex() for h in self.heads())
        checks.consistent_heads = len(self.heads()) == 1
        checks.finalized_epoch = max(
            int(n.chain.head_state().finalized_checkpoint.epoch)
            for n in self.nodes
        )
        checks.min_finalized_epoch = min(
            int(n.chain.head_state().finalized_checkpoint.epoch)
            for n in self.nodes
        )
        return checks

    def close(self) -> None:
        """Tear down socket transports (no-op for the in-process hub)."""
        for n in self.nodes:
            ep = n.service.endpoint
            if hasattr(ep, "close"):
                ep.close()
