"""Bulk validator lifecycle against the VC keymanager API
(validator_manager analog; reference validator_manager/src/{create,
import,move}.rs).

`create` derives N EIP-2333 keys from a wallet seed into keystore
JSONs; `import_keystores` pushes them to a running VC's keymanager API;
`move_validators` performs the safe migration dance: DELETE on the
source VC (which stops signing and returns the slashing-protection
interchange) then import on the destination WITH that interchange, so
the low/high watermarks travel with the key.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional

from ..crypto.keystore.key_derivation import (
    derive_path,
    validator_signing_path,
)
from ..crypto.keystore.keystore import Keystore
from ..crypto.bls.keys import SecretKey


class VcApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class ValidatorClientHttpClient:
    """Typed client for the VC keymanager API (the `eth2` crate's
    ValidatorClientHttpClient role)."""

    def __init__(self, base_url: str, token: str, timeout: float = 10.0):
        self._base = base_url.rstrip("/")
        self._token = token
        self.timeout = timeout

    def _request(self, method: str, path: str, body: Optional[dict] = None):
        req = urllib.request.Request(
            self._base + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
        )
        req.add_header("Authorization", f"Bearer {self._token}")
        if body is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else {}
        except urllib.error.HTTPError as e:
            raise VcApiError(e.code, e.read().decode(errors="replace"))
        except (urllib.error.URLError, OSError) as e:
            raise VcApiError(0, f"connection failed: {e}")

    def list_keystores(self) -> list:
        return self._request("GET", "/eth/v1/keystores")["data"]

    def import_keystores(
        self,
        keystores: list,
        passwords: list,
        slashing_protection: Optional[str] = None,
    ) -> list:
        body = {"keystores": keystores, "passwords": passwords}
        if slashing_protection is not None:
            body["slashing_protection"] = slashing_protection
        return self._request("POST", "/eth/v1/keystores", body)["data"]

    def delete_keystores(self, pubkeys: list) -> dict:
        return self._request(
            "DELETE", "/eth/v1/keystores", {"pubkeys": pubkeys}
        )

    def set_validator_settings(self, pubkey: str, settings: dict) -> None:
        """Per-validator proposal settings via the keymanager API
        (feerecipient endpoint; other knobs ride the same route family)."""
        if "fee_recipient" in settings:
            self._request(
                "POST",
                f"/eth/v1/validator/{pubkey}/feerecipient",
                {"ethaddress": settings["fee_recipient"]},
            )


# ---------------------------------------------------------------- create


def create_validators(
    seed: bytes,
    count: int,
    password: str,
    first_index: int = 0,
    scrypt_n: int = 262144,
) -> list:
    """validator_manager create: N (keystore_json, pubkey_hex) pairs
    derived at m/12381/3600/i/0/0."""
    out = []
    for i in range(first_index, first_index + count):
        path = validator_signing_path(i)
        sk = SecretKey(derive_path(seed, path))
        ks = Keystore.encrypt(sk, password, path=path, scrypt_n=scrypt_n)
        out.append((ks.to_json(), "0x" + ks.pubkey.hex()))
    return out


def create_validators_with_deposits(
    seed: bytes,
    count: int,
    password: str,
    *,
    first_index: int = 0,
    amount_gwei: int = 32 * 10**9,
    fork_version: bytes = b"\x00\x00\x00\x00",
    withdrawal_address: Optional[bytes] = None,
    scrypt_n: int = 262144,
) -> tuple:
    """The reference `validator_manager create` output in full
    (create_validators.rs): keystores PLUS the standard
    deposit_data.json entries (the shape the staking deposit-cli
    produces and launchpads consume — pinned against deposit-cli
    vectors in tests/test_external_vectors.py).

    Returns ([(keystore_json, pubkey_hex)], [deposit_entry_dict]).
    withdrawal_address: 0x01-credentialed EL address; None derives the
    BLS (0x00) withdrawal credential from the EIP-2334 withdrawal key.
    """
    from ..consensus import types as T
    from ..crypto.keystore.key_derivation import validator_withdrawal_path

    keystores = []
    deposits = []
    domain = _deposit_domain(fork_version)
    for i in range(first_index, first_index + count):
        path = validator_signing_path(i)
        sk = SecretKey(derive_path(seed, path))
        ks = Keystore.encrypt(sk, password, path=path, scrypt_n=scrypt_n)
        pk = ks.pubkey
        keystores.append((ks.to_json(), "0x" + pk.hex()))
        if withdrawal_address is not None:
            wc = b"\x01" + b"\x00" * 11 + withdrawal_address
        else:
            import hashlib

            wk = SecretKey(derive_path(seed, validator_withdrawal_path(i)))
            wc = b"\x00" + hashlib.sha256(
                wk.public_key().to_bytes()
            ).digest()[1:]
        msg = T.DepositMessage.make(
            pubkey=pk, withdrawal_credentials=wc, amount=amount_gwei
        )
        msg_root = T.DepositMessage.hash_tree_root(msg)
        from ..consensus.types import SigningData

        signing_root = SigningData.make(
            object_root=msg_root, domain=domain
        ).hash_tree_root()
        sig = sk.sign(signing_root).to_bytes()
        data = T.DepositData.make(
            pubkey=pk,
            withdrawal_credentials=wc,
            amount=amount_gwei,
            signature=sig,
        )
        deposits.append(
            {
                "pubkey": pk.hex(),
                "withdrawal_credentials": wc.hex(),
                "amount": amount_gwei,
                "signature": sig.hex(),
                "deposit_message_root": msg_root.hex(),
                "deposit_data_root": T.DepositData.hash_tree_root(data).hex(),
                "fork_version": fork_version.hex(),
                "network_name": "mainnet",
                "deposit_cli_version": "lighthouse-tpu-vm",
            }
        )
    return keystores, deposits


def _deposit_domain(fork_version: bytes) -> bytes:
    from ..consensus import types as T

    fd = T.ForkData.make(
        current_version=fork_version, genesis_validators_root=b"\x00" * 32
    )
    return b"\x03\x00\x00\x00" + T.ForkData.hash_tree_root(fd)[:28]


# -------------------------------------------------------- validators file


def import_from_validators_file(
    client: ValidatorClientHttpClient, entries: list, password: str
) -> list:
    """The reference's --validators-file import flow
    (import_validators.rs): entries are
    {enabled, voting_keystore (json str or dict), fee_recipient?,
    gas_limit?, builder_proposals?}; disabled entries are skipped, and
    per-validator proposal settings are pushed after the key lands."""
    keystores, passwords, extras = [], [], []
    for e in entries:
        if not e.get("enabled", True):
            continue
        ks = e["voting_keystore"]
        keystores.append(ks if isinstance(ks, str) else json.dumps(ks))
        passwords.append(e.get("password", password))
        extras.append(e)
    statuses = client.import_keystores(keystores, passwords)
    for e, status in zip(extras, statuses):
        if status.get("status") not in ("imported", "duplicate"):
            continue
        ks = e["voting_keystore"]
        pk = (
            json.loads(ks)["pubkey"] if isinstance(ks, str) else ks["pubkey"]
        )
        if not pk.startswith("0x"):
            pk = "0x" + pk
        applied, unsupported = {}, []
        if "fee_recipient" in e:
            applied["fee_recipient"] = e["fee_recipient"]
        for knob in ("gas_limit", "builder_proposals"):
            if knob in e:
                unsupported.append(knob)
        if applied:
            try:
                client.set_validator_settings(pk, applied)
            except VcApiError as err:
                status["settings_error"] = str(err)
        if unsupported:
            # NEVER silently drop an operator's intent: surface what the
            # keymanager API here cannot carry yet
            status["settings_unsupported"] = unsupported
    return statuses


# ---------------------------------------------------------------- move


def move_validators(
    src: ValidatorClientHttpClient,
    dst: ValidatorClientHttpClient,
    pubkeys: list,
    keystores: list,
    passwords: list,
) -> list:
    """The migration dance: stop-and-export on src, import-with-
    watermarks on dst. `keystores` are the JSONs for the moved keys
    (the API's delete does not return key material)."""
    deleted = src.delete_keystores(pubkeys)
    interchange = deleted.get("slashing_protection")
    statuses = dst.import_keystores(
        keystores, passwords, slashing_protection=interchange
    )
    return statuses
