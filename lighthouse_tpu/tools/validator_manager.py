"""Bulk validator lifecycle against the VC keymanager API
(validator_manager analog; reference validator_manager/src/{create,
import,move}.rs).

`create` derives N EIP-2333 keys from a wallet seed into keystore
JSONs; `import_keystores` pushes them to a running VC's keymanager API;
`move_validators` performs the safe migration dance: DELETE on the
source VC (which stops signing and returns the slashing-protection
interchange) then import on the destination WITH that interchange, so
the low/high watermarks travel with the key.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Optional

from ..crypto.keystore.key_derivation import (
    derive_path,
    validator_signing_path,
)
from ..crypto.keystore.keystore import Keystore
from ..crypto.bls.keys import SecretKey


class VcApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class ValidatorClientHttpClient:
    """Typed client for the VC keymanager API (the `eth2` crate's
    ValidatorClientHttpClient role)."""

    def __init__(self, base_url: str, token: str, timeout: float = 10.0):
        self._base = base_url.rstrip("/")
        self._token = token
        self.timeout = timeout

    def _request(self, method: str, path: str, body: Optional[dict] = None):
        req = urllib.request.Request(
            self._base + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
        )
        req.add_header("Authorization", f"Bearer {self._token}")
        if body is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else {}
        except urllib.error.HTTPError as e:
            raise VcApiError(e.code, e.read().decode(errors="replace"))
        except (urllib.error.URLError, OSError) as e:
            raise VcApiError(0, f"connection failed: {e}")

    def list_keystores(self) -> list:
        return self._request("GET", "/eth/v1/keystores")["data"]

    def import_keystores(
        self,
        keystores: list,
        passwords: list,
        slashing_protection: Optional[str] = None,
    ) -> list:
        body = {"keystores": keystores, "passwords": passwords}
        if slashing_protection is not None:
            body["slashing_protection"] = slashing_protection
        return self._request("POST", "/eth/v1/keystores", body)["data"]

    def delete_keystores(self, pubkeys: list) -> dict:
        return self._request(
            "DELETE", "/eth/v1/keystores", {"pubkeys": pubkeys}
        )


# ---------------------------------------------------------------- create


def create_validators(
    seed: bytes,
    count: int,
    password: str,
    first_index: int = 0,
    scrypt_n: int = 262144,
) -> list:
    """validator_manager create: N (keystore_json, pubkey_hex) pairs
    derived at m/12381/3600/i/0/0."""
    out = []
    for i in range(first_index, first_index + count):
        path = validator_signing_path(i)
        sk = SecretKey(derive_path(seed, path))
        ks = Keystore.encrypt(sk, password, path=path, scrypt_n=scrypt_n)
        out.append((ks.to_json(), "0x" + ks.pubkey.hex()))
    return out


# ---------------------------------------------------------------- move


def move_validators(
    src: ValidatorClientHttpClient,
    dst: ValidatorClientHttpClient,
    pubkeys: list,
    keystores: list,
    passwords: list,
) -> list:
    """The migration dance: stop-and-export on src, import-with-
    watermarks on dst. `keystores` are the JSONs for the moved keys
    (the API's delete does not return key material)."""
    deleted = src.delete_keystores(pubkeys)
    interchange = deleted.get("slashing_protection")
    statuses = dst.import_keystores(
        keystores, passwords, slashing_protection=interchange
    )
    return statuses
