"""500k-validator scale probe (VERDICT r2 #10).

Generates a synthetic N-validator post-altair state (no real crypto —
pubkeys are unique opaque bytes; epoch processing never checks them),
then measures the hot regime the north star names:

  - one full epoch transition (process_epoch, the single-pass analog,
    consensus/state_processing/src/per_epoch_processing/single_pass.rs)
  - one slot's committee resolution (get_beacon_committee for every
    committee of a slot — the attestation-verification lookup path)
  - proposer index for one slot
  - state copy (BeaconState.copy) — the per-block fork-state cost

Run:  python -m lighthouse_tpu.tools.scale_probe [n_validators]
Numbers land in BASELINE.md §"scale probe".
"""

from __future__ import annotations

import sys
import time

from ..consensus import state_transition as st
from ..consensus import types as T
from ..consensus.spec import mainnet_spec


def build_state(n: int):
    spec = mainnet_spec()
    state = st.empty_genesis_shell(spec, genesis_time=0)
    eb = spec.max_effective_balance
    validators = []
    balances = []
    for i in range(n):
        validators.append(
            T.Validator.make(
                pubkey=i.to_bytes(8, "little") * 6,
                withdrawal_credentials=b"\x01" + b"\x00" * 31,
                effective_balance=eb,
                slashed=False,
                activation_eligibility_epoch=0,
                activation_epoch=0,
                exit_epoch=st.FAR_FUTURE_EPOCH,
                withdrawable_epoch=st.FAR_FUTURE_EPOCH,
            )
        )
        balances.append(eb)
    state.validators = validators
    state.balances = balances
    n_active = len(validators)
    state.previous_epoch_participation = [7] * n_active  # full participation
    state.current_epoch_participation = [7] * n_active
    state.inactivity_scores = [0] * n_active
    # mid-chain posture: slot at an epoch tail, checkpoints wired
    spe = spec.preset.slots_per_epoch
    state.slot = 10 * spe - 1
    state.finalized_checkpoint = T.Checkpoint.make(epoch=8, root=b"\x08" * 32)
    state.current_justified_checkpoint = T.Checkpoint.make(
        epoch=9, root=b"\x09" * 32
    )
    state.previous_justified_checkpoint = T.Checkpoint.make(
        epoch=8, root=b"\x08" * 32
    )
    state.justification_bits = [True, True, True, True]
    return spec, state


def probe(n: int = 500_000) -> dict:
    out = {"validators": n}
    t0 = time.perf_counter()
    spec, state = build_state(n)
    out["build_s"] = round(time.perf_counter() - t0, 2)

    t0 = time.perf_counter()
    st.process_epoch(spec, state)
    out["epoch_transition_s"] = round(time.perf_counter() - t0, 2)

    state.slot += 1
    epoch = st.get_current_epoch(spec, state)
    t0 = time.perf_counter()
    cps = st.get_committee_count_per_slot(spec, state, epoch)
    members = 0
    for idx in range(cps):
        members += len(
            st.get_beacon_committee(spec, state, int(state.slot), idx)
        )
    out["slot_committees"] = cps
    out["slot_committee_members"] = members
    # cold = first slot of the epoch: pays the O(n) active-set scan +
    # the vectorized whole-list shuffle, both cached for the epoch
    out["slot_committee_resolution_cold_s"] = round(
        time.perf_counter() - t0, 4
    )
    state.slot += 1
    t0 = time.perf_counter()
    for idx in range(cps):
        st.get_beacon_committee(spec, state, int(state.slot), idx)
    # warm = every later slot of the epoch: permutation-slice only
    out["slot_committee_resolution_warm_s"] = round(
        time.perf_counter() - t0, 4
    )
    state.slot -= 1

    t0 = time.perf_counter()
    st.get_beacon_proposer_index(spec, state)
    out["proposer_index_s"] = round(time.perf_counter() - t0, 4)

    t0 = time.perf_counter()
    copied = state.copy()
    out["state_copy_s"] = round(time.perf_counter() - t0, 4)

    # CoW aliasing cost check: mutate the copy, re-copy — the spine
    # stays O(chunks) regardless of how many copies exist
    copied.balances[0] += 1
    t0 = time.perf_counter()
    copied.copy()
    out["state_copy_after_mutation_s"] = round(time.perf_counter() - t0, 4)
    return out


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 500_000
    print(probe(n))
