"""Chain analytics daemon (watch analog; reference watch/src/lib.rs —
Postgres there, sqlite here, same job: poll a BN's REST API, record
canonical blocks, and answer packing/participation/proposer queries).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Optional

from ..common import logging as clog
from ..common.eth2 import ApiClientError, BeaconNodeHttpClient
from ..consensus import types as T

log = clog.get_logger("watch")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS canonical_blocks (
    slot INTEGER PRIMARY KEY,
    root TEXT NOT NULL,
    proposer INTEGER NOT NULL,
    attestation_count INTEGER NOT NULL,
    deposit_count INTEGER NOT NULL,
    exit_count INTEGER NOT NULL,
    sync_participation INTEGER,
    graffiti TEXT
);
CREATE INDEX IF NOT EXISTS blocks_by_proposer
    ON canonical_blocks (proposer);
"""


class WatchDB:
    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._db.executescript(_SCHEMA)

    # -------------------------------------------------------- writes

    def record_block(self, signed_block, root: bytes) -> None:
        msg = signed_block.message
        body = msg.body
        sync_bits = body.sync_aggregate.sync_committee_bits
        graffiti = bytes(body.graffiti).rstrip(b"\x00")
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO canonical_blocks VALUES "
                "(?,?,?,?,?,?,?,?)",
                (
                    int(msg.slot),
                    "0x" + root.hex(),
                    int(msg.proposer_index),
                    len(body.attestations),
                    len(body.deposits),
                    len(body.voluntary_exits),
                    sum(1 for b in sync_bits if b),
                    graffiti.decode(errors="replace"),
                ),
            )
            self._db.commit()

    # -------------------------------------------------------- queries

    def highest_slot(self) -> Optional[int]:
        row = self._db.execute(
            "SELECT MAX(slot) FROM canonical_blocks"
        ).fetchone()
        return row[0]

    def lowest_slot(self) -> Optional[int]:
        row = self._db.execute(
            "SELECT MIN(slot) FROM canonical_blocks"
        ).fetchone()
        return row[0]

    def block_packing(self) -> dict:
        """watch block_packing role: attestation fill statistics."""
        rows = self._db.execute(
            "SELECT COUNT(*), AVG(attestation_count), MIN(attestation_count),"
            " MAX(attestation_count) FROM canonical_blocks"
        ).fetchone()
        return {
            "blocks": rows[0],
            "avg_attestations": rows[1],
            "min_attestations": rows[2],
            "max_attestations": rows[3],
        }

    def proposer_counts(self) -> dict:
        return dict(
            self._db.execute(
                "SELECT proposer, COUNT(*) FROM canonical_blocks"
                " GROUP BY proposer"
            ).fetchall()
        )

    def sync_participation(self) -> Optional[float]:
        row = self._db.execute(
            "SELECT AVG(sync_participation) FROM canonical_blocks"
            " WHERE sync_participation IS NOT NULL"
        ).fetchone()
        return row[0]


class WatchService:
    """The updater task: follow the head backwards until known ground."""

    def __init__(self, client: BeaconNodeHttpClient, db: WatchDB):
        self.client = client
        self.db = db

    def update(self, max_blocks: int = 64) -> int:
        """One poll round; returns blocks newly recorded. Walks head →
        known ground, then resumes the historical backfill below the
        lowest recorded slot, so a fresh DB on an old chain converges to
        full coverage over successive rounds instead of abandoning the
        gap at max_blocks."""
        try:
            head = self.client.header("head")
        except ApiClientError as e:
            log.warning("watch poll failed", error=str(e))
            return 0
        known = self.db.highest_slot()
        recorded = self._walk(head["slot"], floor=known, budget=max_blocks)
        low = self.db.lowest_slot()
        if recorded < max_blocks and low is not None and low > 0:
            recorded += self._walk(
                low - 1, floor=None, budget=max_blocks - recorded
            )
        return recorded

    def _walk(self, slot: int, floor, budget: int) -> int:
        recorded = 0
        while slot >= 0 and recorded < budget:
            if floor is not None and slot <= floor:
                break
            try:
                raw = self.client.block_ssz(str(slot))
            except ApiClientError as e:
                if e.status == 404:
                    slot -= 1  # genuinely skipped slot
                    continue
                # transport/BN failure: abort the round — decrementing
                # past it would permanently drop a real block
                log.warning("watch fetch failed", slot=slot, error=str(e))
                break
            signed = T.SignedBeaconBlock.deserialize(raw)
            root = signed.message.hash_tree_root()
            self.db.record_block(signed, root)
            recorded += 1
            slot = int(signed.message.slot) - 1
        return recorded
