"""Chain analytics daemon (watch analog; reference watch/src/lib.rs —
Postgres there, sqlite here, same job: poll a BN's REST API, record
canonical blocks, and answer packing/participation/proposer queries).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Optional

from ..common import logging as clog
from ..common.eth2 import ApiClientError, BeaconNodeHttpClient
from ..consensus import types as T

log = clog.get_logger("watch")

_SCHEMA = """
CREATE TABLE IF NOT EXISTS canonical_blocks (
    slot INTEGER PRIMARY KEY,
    root TEXT NOT NULL,
    proposer INTEGER NOT NULL,
    attestation_count INTEGER NOT NULL,
    deposit_count INTEGER NOT NULL,
    exit_count INTEGER NOT NULL,
    sync_participation INTEGER,
    graffiti TEXT
);
CREATE INDEX IF NOT EXISTS blocks_by_proposer
    ON canonical_blocks (proposer);
-- per-included-attestation record (watch suboptimal_attestations role:
-- inclusion delay is the lateness signal blocks alone can provide)
CREATE TABLE IF NOT EXISTS block_attestations (
    block_slot INTEGER NOT NULL,
    att_slot INTEGER NOT NULL,
    committee_index INTEGER NOT NULL,
    inclusion_delay INTEGER NOT NULL
);
CREATE INDEX IF NOT EXISTS atts_by_att_slot
    ON block_attestations (att_slot, committee_index);
-- periodic registry snapshot (watch validators table role)
CREATE TABLE IF NOT EXISTS validator_snapshots (
    snapshot_slot INTEGER NOT NULL,
    validator_index INTEGER NOT NULL,
    status TEXT NOT NULL,
    balance INTEGER NOT NULL,
    PRIMARY KEY (snapshot_slot, validator_index)
);
-- proposer reward per canonical block (watch block_rewards role)
CREATE TABLE IF NOT EXISTS block_rewards (
    slot INTEGER PRIMARY KEY,
    proposer INTEGER NOT NULL,
    total INTEGER NOT NULL,
    attestations INTEGER NOT NULL,
    sync_aggregate INTEGER NOT NULL
);
-- per-block client fingerprint (watch blockprint role: the reference
-- daemon calls an external classifier service; offline analog below)
CREATE TABLE IF NOT EXISTS block_fingerprints (
    slot INTEGER PRIMARY KEY,
    proposer INTEGER NOT NULL,
    client TEXT NOT NULL
);
CREATE INDEX IF NOT EXISTS fingerprints_by_proposer
    ON block_fingerprints (proposer);
"""

# graffiti substrings the major clients stamp by default — the
# zero-dependency slice of blockprint (the reference ships the
# classifier as a separate ML service; watch/src only records its
# verdicts, which is the shape mirrored here)
_CLIENT_MARKS = (
    ("lighthouse", "lighthouse"),
    ("teku", "teku"),
    ("nimbus", "nimbus"),
    ("prysm", "prysm"),
    ("lodestar", "lodestar"),
    ("grandine", "grandine"),
    ("erigon", "caplin"),
)


def classify_client(graffiti: str) -> str:
    g = graffiti.lower()
    for mark, name in _CLIENT_MARKS:
        if mark in g:
            return name
    return "unknown"


def _committee_index(att) -> int:
    """Pre-electra: data.index. Electra (EIP-7549): data.index is
    constitutionally 0 and the committee rides committee_bits — record
    the first set bit (single-committee aggregates in this framework's
    canonical shape)."""
    bits = getattr(att, "committee_bits", None)
    if bits is not None:
        for i, b in enumerate(bits):
            if b:
                return i
    return int(att.data.index)


class WatchDB:
    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._db.executescript(_SCHEMA)

    # -------------------------------------------------------- writes

    def record_block(self, signed_block, root: bytes) -> None:
        msg = signed_block.message
        body = msg.body
        sync_bits = body.sync_aggregate.sync_committee_bits
        graffiti = bytes(body.graffiti).rstrip(b"\x00")
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO canonical_blocks VALUES "
                "(?,?,?,?,?,?,?,?)",
                (
                    int(msg.slot),
                    "0x" + root.hex(),
                    int(msg.proposer_index),
                    len(body.attestations),
                    len(body.deposits),
                    len(body.voluntary_exits),
                    sum(1 for b in sync_bits if b),
                    graffiti.decode(errors="replace"),
                ),
            )
            self._db.execute(
                "INSERT OR REPLACE INTO block_fingerprints VALUES (?,?,?)",
                (
                    int(msg.slot),
                    int(msg.proposer_index),
                    classify_client(graffiti.decode(errors="replace")),
                ),
            )
            self._db.execute(
                "DELETE FROM block_attestations WHERE block_slot = ?",
                (int(msg.slot),),
            )
            self._db.executemany(
                "INSERT INTO block_attestations VALUES (?,?,?,?)",
                [
                    (
                        int(msg.slot),
                        int(a.data.slot),
                        _committee_index(a),
                        int(msg.slot) - int(a.data.slot),
                    )
                    for a in body.attestations
                ],
            )
            self._db.commit()

    def record_validator_snapshot(self, slot: int, entries: list) -> None:
        """entries: beacon-API validator dicts (index/status/balance)."""
        with self._lock:
            self._db.executemany(
                "INSERT OR REPLACE INTO validator_snapshots VALUES (?,?,?,?)",
                [
                    (
                        int(slot),
                        int(e["index"]),
                        e["status"],
                        int(e["balance"]),
                    )
                    for e in entries
                ],
            )
            self._db.commit()

    def record_reward(self, slot: int, reward: dict) -> None:
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO block_rewards VALUES (?,?,?,?,?)",
                (
                    int(slot),
                    int(reward["proposer_index"]),
                    int(reward["total"]),
                    int(reward.get("attestations", 0)),
                    int(reward.get("sync_aggregate", 0)),
                ),
            )
            self._db.commit()

    # -------------------------------------------------------- queries

    def highest_slot(self) -> Optional[int]:
        row = self._db.execute(
            "SELECT MAX(slot) FROM canonical_blocks"
        ).fetchone()
        return row[0]

    def lowest_slot(self) -> Optional[int]:
        row = self._db.execute(
            "SELECT MIN(slot) FROM canonical_blocks"
        ).fetchone()
        return row[0]

    def block_packing(self) -> dict:
        """watch block_packing role: attestation fill statistics."""
        rows = self._db.execute(
            "SELECT COUNT(*), AVG(attestation_count), MIN(attestation_count),"
            " MAX(attestation_count) FROM canonical_blocks"
        ).fetchone()
        return {
            "blocks": rows[0],
            "avg_attestations": rows[1],
            "min_attestations": rows[2],
            "max_attestations": rows[3],
        }

    def proposer_counts(self) -> dict:
        return dict(
            self._db.execute(
                "SELECT proposer, COUNT(*) FROM canonical_blocks"
                " GROUP BY proposer"
            ).fetchall()
        )

    def sync_participation(self) -> Optional[float]:
        row = self._db.execute(
            "SELECT AVG(sync_participation) FROM canonical_blocks"
            " WHERE sync_participation IS NOT NULL"
        ).fetchone()
        return row[0]

    def inclusion_delay_stats(self) -> dict:
        """The suboptimal-attestation signal: how late attestations land."""
        rows = self._db.execute(
            "SELECT COUNT(*), AVG(inclusion_delay), MAX(inclusion_delay),"
            " SUM(inclusion_delay > 1) FROM block_attestations"
        ).fetchone()
        return {
            "attestations": rows[0],
            "avg_delay": rows[1],
            "max_delay": rows[2],
            "late": rows[3] or 0,
        }

    def missed_slots(self) -> list:
        """Canonical gaps between lowest and highest recorded slots —
        the proposer-miss surface (watch's missed-block detection)."""
        lo, hi = self.lowest_slot(), self.highest_slot()
        if lo is None or hi is None:
            return []
        have = {
            r[0]
            for r in self._db.execute(
                "SELECT slot FROM canonical_blocks"
            ).fetchall()
        }
        return [s for s in range(lo, hi + 1) if s not in have]

    def reward_stats(self) -> dict:
        rows = self._db.execute(
            "SELECT COUNT(*), AVG(total), MIN(total), MAX(total)"
            " FROM block_rewards"
        ).fetchone()
        return {
            "blocks": rows[0],
            "avg_total": rows[1],
            "min_total": rows[2],
            "max_total": rows[3],
        }

    def client_distribution(self) -> dict:
        """Blockprint-style network share: blocks per classified
        client (watch blockprint_blocks query role)."""
        return dict(
            self._db.execute(
                "SELECT client, COUNT(*) FROM block_fingerprints"
                " GROUP BY client"
            ).fetchall()
        )

    def proposer_clients(self) -> dict:
        """Most recent fingerprint per proposer (the validators'
        blockprint column)."""
        rows = self._db.execute(
            "SELECT proposer, client FROM block_fingerprints"
            " ORDER BY slot"
        ).fetchall()
        return {p: c for p, c in rows}

    def packing_by_proposer(self) -> dict:
        """Per-proposer attestation packing (watch block_packing drilled
        to the proposer level: who ships thin blocks)."""
        return {
            p: {"blocks": n, "avg_attestations": avg}
            for p, n, avg in self._db.execute(
                "SELECT proposer, COUNT(*), AVG(attestation_count)"
                " FROM canonical_blocks GROUP BY proposer"
            ).fetchall()
        }

    def attestation_inclusion_by_slot(self) -> dict:
        """Included-attestation counts keyed by the attested slot —
        gaps against the committee schedule are the per-slot
        participation signal (suboptimal_attestations aggregate)."""
        return dict(
            self._db.execute(
                "SELECT att_slot, COUNT(*) FROM block_attestations"
                " GROUP BY att_slot"
            ).fetchall()
        )

    def balance_history(self, validator_index: int) -> list:
        return self._db.execute(
            "SELECT snapshot_slot, balance FROM validator_snapshots"
            " WHERE validator_index = ? ORDER BY snapshot_slot",
            (validator_index,),
        ).fetchall()


class WatchService:
    """The updater task: follow the head backwards until known ground."""

    def __init__(self, client: BeaconNodeHttpClient, db: WatchDB):
        self.client = client
        self.db = db
        self._last_snapshot: Optional[int] = None

    def update(
        self, max_blocks: int = 64, snapshot_every: int = 32
    ) -> int:
        """One poll round; returns blocks newly recorded. Walks head →
        known ground, then resumes the historical backfill below the
        lowest recorded slot, so a fresh DB on an old chain converges to
        full coverage over successive rounds instead of abandoning the
        gap at max_blocks. Also records per-block proposer rewards and a
        validator-registry snapshot every `snapshot_every` slots (the
        reference daemon's block_rewards + validators updaters)."""
        try:
            head = self.client.header("head")
        except ApiClientError as e:
            log.warning("watch poll failed", error=str(e))
            return 0
        known = self.db.highest_slot()
        recorded = self._walk(head["slot"], floor=known, budget=max_blocks)
        low = self.db.lowest_slot()
        if recorded < max_blocks and low is not None and low > 0:
            recorded += self._walk(
                low - 1, floor=None, budget=max_blocks - recorded
            )
        head_slot = int(head["slot"])
        last_snap = self._last_snapshot
        if last_snap is None or head_slot - last_snap >= snapshot_every:
            try:
                self.db.record_validator_snapshot(
                    head_slot, self.client.validators_bulk()
                )
                self._last_snapshot = head_slot
            except ApiClientError as e:
                log.warning("validator snapshot failed", error=str(e))
        return recorded

    def _walk(self, slot: int, floor, budget: int) -> int:
        recorded = 0
        while slot >= 0 and recorded < budget:
            if floor is not None and slot <= floor:
                break
            try:
                raw = self.client.block_ssz(str(slot))
            except ApiClientError as e:
                if e.status == 404:
                    slot -= 1  # genuinely skipped slot
                    continue
                # transport/BN failure: abort the round — decrementing
                # past it would permanently drop a real block
                log.warning("watch fetch failed", slot=slot, error=str(e))
                break
            signed = T.SignedBeaconBlock.deserialize(raw)
            root = signed.message.hash_tree_root()
            self.db.record_block(signed, root)
            try:
                self.db.record_reward(
                    int(signed.message.slot),
                    self.client.block_rewards("0x" + root.hex()),
                )
            except ApiClientError:
                pass  # parent state pruned: packing stats still land
            recorded += 1
            slot = int(signed.message.slot) - 1
        return recorded
