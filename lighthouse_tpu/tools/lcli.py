"""Dev swiss-army knife (lcli analog; reference lcli/src/main.rs:
transition-blocks, skip-slots, parse_ssz, interop-genesis).

Each operation is a plain function over SSZ bytes so tests drive them
directly; the CLI wires files/stdout around them.
"""

from __future__ import annotations

import json

from ..consensus import state_transition as st
from ..consensus import types as T
from ..consensus import light_client as lc
from ..consensus import data_column as dc
from ..consensus.spec import ChainSpec

# the parse-ssz type registry (lcli parse_ssz's type_name match)
SSZ_TYPES = {
    "SignedBeaconBlock": T.SignedBeaconBlock,
    "BeaconBlock": T.BeaconBlock,
    "BeaconState": T.BeaconState,
    "Attestation": T.Attestation,
    "IndexedAttestation": T.IndexedAttestation,
    "SignedAggregateAndProof": T.SignedAggregateAndProof,
    "BeaconBlockHeader": T.BeaconBlockHeader,
    "SignedBeaconBlockHeader": T.SignedBeaconBlockHeader,
    "BlobSidecar": T.BlobSidecar,
    "DataColumnSidecar": dc.DataColumnSidecar,
    "SyncCommittee": T.SyncCommittee,
    "LightClientBootstrap": lc.LightClientBootstrap,
    "LightClientUpdate": lc.LightClientUpdate,
    "LightClientFinalityUpdate": lc.LightClientFinalityUpdate,
    "LightClientOptimisticUpdate": lc.LightClientOptimisticUpdate,
}


def transition_blocks(
    spec: ChainSpec, pre_ssz: bytes, block_ssz: bytes, no_signature_verification: bool = False
) -> bytes:
    """lcli transition-blocks: run one block through the transition.
    Signatures verify by DEFAULT (the reference's posture) — an
    invalid-signature block must not 'transition successfully' unless
    the caller explicitly opts out."""
    state = T.BeaconState.deserialize(pre_ssz)
    signed = T.SignedBeaconBlock.deserialize(block_ssz)
    block = signed.message
    if state.slot < block.slot:
        st.process_slots(spec, state, int(block.slot))
    st.process_block(
        spec, state, block, verify_signatures=not no_signature_verification
    )
    return state.serialize()


def skip_slots(spec: ChainSpec, pre_ssz: bytes, slots: int) -> bytes:
    """lcli skip-slots: advance a state through empty slots."""
    state = T.BeaconState.deserialize(pre_ssz)
    st.process_slots(spec, state, int(state.slot) + slots)
    return state.serialize()


def parse_ssz(type_name: str, raw: bytes) -> dict:
    """lcli parse_ssz: decode and render as JSON-able python."""
    ctype = SSZ_TYPES.get(type_name)
    if ctype is None:
        raise ValueError(
            f"unknown type {type_name!r}; known: {sorted(SSZ_TYPES)}"
        )
    return _to_jsonable(ctype.deserialize(raw))


def _to_jsonable(value):
    from ..consensus.ssz import SSZValue

    if isinstance(value, SSZValue):
        ctype = object.__getattribute__(value, "_type")
        return {
            fname: _to_jsonable(getattr(value, fname))
            for fname, _ in ctype.fields
        }
    if isinstance(value, (bytes, bytearray)):
        return "0x" + bytes(value).hex()
    if isinstance(value, (list, tuple)):
        return [_to_jsonable(v) for v in value]
    if isinstance(value, bool):
        return value
    if isinstance(value, int):
        return str(value)  # beacon-API style stringed uints
    return value


def interop_genesis(spec: ChainSpec, count: int, genesis_time: int = 0) -> bytes:
    """lcli interop-genesis: deterministic-key genesis state SSZ."""
    pubkeys = st.interop_pubkeys(count)
    return st.interop_genesis_state(spec, pubkeys, genesis_time).serialize()


def pretty_ssz(type_name: str, raw: bytes) -> str:
    return json.dumps(parse_ssz(type_name, raw), indent=2)


# ------------------------------------------------------ round-4 toolbox


def generate_bootnode_enr(
    private_key_hex: str,
    ip: str,
    udp_port: int,
    tcp_port: int,
    fork_digest: bytes = b"\x00" * 4,
) -> dict:
    """lcli generate-bootnode-enr: a signed EIP-778 record with the
    eth2 ENRForkID field (next fork = far-future), plus the node id."""
    from ..network.enr import Enr

    sk = bytes.fromhex(private_key_hex.replace("0x", ""))
    eth2 = fork_digest + b"\x00" * 4 + (2**64 - 1).to_bytes(8, "little")
    enr = Enr.build(
        sk,
        seq=1,
        ip=bytes(int(p) for p in ip.split(".")),
        udp=udp_port,
        tcp=tcp_port,
        eth2=eth2,
        attnets=b"\x00" * 8,
    )
    return {"enr": enr.to_text(), "node_id": "0x" + enr.node_id().hex()}


def state_root(pre_ssz: bytes) -> str:
    """lcli state-root: hash_tree_root of a BeaconState SSZ."""
    return "0x" + T.BeaconState.deserialize(pre_ssz).hash_tree_root().hex()


def block_root(block_ssz: bytes) -> str:
    """lcli block-root: hash_tree_root of a SignedBeaconBlock's message."""
    return (
        "0x"
        + T.SignedBeaconBlock.deserialize(block_ssz)
        .message.hash_tree_root()
        .hex()
    )


def insecure_validators(count: int, first_index: int = 0) -> list:
    """lcli insecure-validators: the interop deterministic keypairs as
    {privkey, pubkey} hex entries (testnet bootstrapping)."""
    from ..crypto.bls.keys import SecretKey

    out = []
    for i in range(first_index, first_index + count):
        sk = st.interop_secret_key(i)
        out.append(
            {
                "index": i,
                "privkey": "0x%064x" % sk.scalar,
                "pubkey": "0x" + sk.public_key().to_bytes().hex(),
            }
        )
    return out


def change_genesis_time(pre_ssz: bytes, genesis_time: int) -> bytes:
    """lcli change-genesis-time: re-stamp a genesis state (testnet
    restarts reuse the state with a fresh clock)."""
    state = T.BeaconState.deserialize(pre_ssz)
    state.genesis_time = int(genesis_time)
    return state.serialize()


def check_deposit_data(entry: dict) -> dict:
    """lcli check-deposit-data: validate one staking deposit-cli entry —
    pubkey/signature well-formed, deposit-message signature verifies
    under the deposit domain of the entry's fork_version, and both
    roots recompute. Returns {valid, errors}."""
    from ..crypto.bls.keys import PublicKey, Signature
    from ..crypto import bls

    errors = []
    try:
        pk_b = bytes.fromhex(entry["pubkey"].replace("0x", ""))
        wc = bytes.fromhex(entry["withdrawal_credentials"].replace("0x", ""))
        sig_b = bytes.fromhex(entry["signature"].replace("0x", ""))
        amount = int(entry["amount"])
        fork_version = bytes.fromhex(
            entry.get("fork_version", "00000000").replace("0x", "")
        )
    except (KeyError, ValueError) as e:
        return {"valid": False, "errors": [f"malformed entry: {e}"]}
    try:
        pk = PublicKey.from_bytes(pk_b)
    except Exception as e:
        return {"valid": False, "errors": [f"bad pubkey: {e}"]}
    try:
        sig = Signature.from_bytes(sig_b)
    except Exception as e:
        return {"valid": False, "errors": [f"bad signature: {e}"]}
    msg = T.DepositMessage.make(
        pubkey=pk_b, withdrawal_credentials=wc, amount=amount
    )
    msg_root = T.DepositMessage.hash_tree_root(msg)
    if "deposit_message_root" in entry:
        want = bytes.fromhex(entry["deposit_message_root"].replace("0x", ""))
        if want != msg_root:
            errors.append("deposit_message_root mismatch")
    data = T.DepositData.make(
        pubkey=pk_b,
        withdrawal_credentials=wc,
        amount=amount,
        signature=sig_b,
    )
    if "deposit_data_root" in entry:
        want = bytes.fromhex(entry["deposit_data_root"].replace("0x", ""))
        if want != T.DepositData.hash_tree_root(data):
            errors.append("deposit_data_root mismatch")
    from ..consensus.domains import compute_domain, compute_signing_root

    domain = compute_domain(
        ChainSpec().domain_deposit, fork_version, b"\x00" * 32
    )
    signing_root = compute_signing_root(msg, domain)
    if not bls.verify(sig, pk, signing_root):
        errors.append("deposit signature invalid")
    return {"valid": not errors, "errors": errors}


def indexed_attestation(
    spec: ChainSpec, state_ssz: bytes, attestation_ssz: bytes
) -> dict:
    """lcli indexed-attestations: resolve an attestation's committee
    bits against a state into the indexed form."""
    state = T.BeaconState.deserialize(state_ssz)
    att = T.Attestation.deserialize(attestation_ssz)
    indices = st.get_attesting_indices(spec, state, att)
    indexed = T.IndexedAttestation.make(
        attesting_indices=sorted(indices),
        data=att.data,
        signature=bytes(att.signature),
    )
    return _to_jsonable(indexed)


def create_payload_header(
    block_hash: bytes, timestamp: int, fee_recipient: bytes = b"\x00" * 20
) -> bytes:
    """lcli create-payload-header: a merge-testnet genesis
    ExecutionPayloadHeader SSZ with the given terminal block hash."""
    h = T.ExecutionPayloadHeader.default()
    h.block_hash = block_hash
    h.timestamp = int(timestamp)
    h.fee_recipient = fee_recipient
    return h.serialize()


def mnemonic_validators(
    mnemonic: str, count: int, first_index: int = 0, passphrase: str = ""
) -> list:
    """lcli mnemonic-validators: EIP-2334 signing keys from a BIP-39
    mnemonic (the path every launchpad wallet uses; pinned against
    deposit-cli vectors in tests/test_external_vectors.py)."""
    from ..crypto.keystore.key_derivation import (
        derive_path,
        mnemonic_to_seed,
        validator_signing_path,
    )
    from ..crypto.bls.keys import SecretKey

    seed = mnemonic_to_seed(mnemonic, passphrase)
    out = []
    for i in range(first_index, first_index + count):
        sk = SecretKey(derive_path(seed, validator_signing_path(i)))
        out.append(
            {
                "index": i,
                "path": validator_signing_path(i),
                "pubkey": "0x" + sk.public_key().to_bytes().hex(),
            }
        )
    return out


def new_testnet(
    spec: ChainSpec,
    validator_count: int,
    genesis_time: int,
    *,
    altair_epoch: int = 0,
    bellatrix_epoch: int = 0,
    capella_epoch: int = 0,
    deneb_epoch: int = 0,
    electra_epoch: int = 0,
) -> dict:
    """lcli new-testnet: a deployable testnet bundle — config.yaml
    fields + the genesis state SSZ (base64 would bloat; returned raw
    under 'genesis_ssz')."""
    pubkeys = st.interop_pubkeys(validator_count)
    state = st.interop_genesis_state(spec, pubkeys, genesis_time)
    config = {
        "CONFIG_NAME": "lighthouse-tpu-testnet",
        "MIN_GENESIS_ACTIVE_VALIDATOR_COUNT": validator_count,
        "MIN_GENESIS_TIME": genesis_time,
        "GENESIS_FORK_VERSION": "0x"
        + spec.genesis_fork_version.hex(),
        "ALTAIR_FORK_EPOCH": altair_epoch,
        "BELLATRIX_FORK_EPOCH": bellatrix_epoch,
        "CAPELLA_FORK_EPOCH": capella_epoch,
        "DENEB_FORK_EPOCH": deneb_epoch,
        "ELECTRA_FORK_EPOCH": electra_epoch,
        "SECONDS_PER_SLOT": spec.seconds_per_slot,
        "SLOTS_PER_EPOCH": spec.preset.slots_per_epoch,
    }
    return {
        "config": config,
        "genesis_ssz": state.serialize(),
        "genesis_validators_root": "0x"
        + bytes(state.genesis_validators_root).hex(),
    }
