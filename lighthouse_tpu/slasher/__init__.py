"""Slasher (slasher/ crate analog): double-vote, surround-vote, and
double-proposal detection over batched ingest."""

from .slasher import Slasher, SlasherConfig

__all__ = ["Slasher", "SlasherConfig"]
