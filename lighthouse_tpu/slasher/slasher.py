"""Slashing detection (slasher/src/array.rs, attestation_queue.rs,
block_queue.rs analogs).

Surround detection uses the reference's min/max-target arrays, held as
numpy vectors per validator so both the membership UPDATE and the
surround CHECK are O(window) vectorized ops instead of per-epoch loops
(array.rs chunked min/max targets, built for exactly this access
pattern — and the same layout a device kernel would batch over
validators):

  min_target[e] = min target among v's attestations with source > e
      new (s, t) SURROUNDS an old vote   iff min_target[s] < t
  max_target[e] = max target among v's attestations with source < e
      new (s, t) IS SURROUNDED by an old iff max_target[s] > t

Ingest is queue-then-batch like the reference: `queue_attestation` /
`queue_block_header` buffer, `process_queued` runs detection for the
whole batch (slasher/service ties this to block import,
beacon_chain.rs:4306).

Persistence (slasher/src/database/mod.rs role): pass `db` (any
node.store.KVStore — the native C++ engine included) and every vote,
proposal and min/max-target chunk is written through via
slasher/database.py; queued-but-unprocessed items are journaled and
REPLAYED on restart, and per-validator history is lazily reloaded, so a
surround vote recorded before a restart is still detected after it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..consensus import types as T

_NO_MIN = np.iinfo(np.int64).max  # sentinel: no attestation recorded
_NO_MAX = -1


@dataclass
class SlasherConfig:
    history_length: int = 4096  # epochs of surround history (config.rs)
    slots_per_epoch: int = 32  # preset-dependent (minimal uses 8)
    max_db_attestations: int = 1 << 20


@dataclass
class _ValidatorHistory:
    min_targets: np.ndarray
    max_targets: np.ndarray
    # the absolute epoch arrays[0] represents: the window SLIDES as the
    # chain advances (no wraparound blind spot past history_length)
    offset: int = 0
    # window-chunk indices touched since the last flush
    dirty: set = field(default_factory=set)
    # target_epoch -> (data_root, attestation) for double votes +
    # materializing slashings
    by_target: dict = field(default_factory=dict)
    # (source, target) list for locating the surround counterparty
    votes: list = field(default_factory=list)


class Slasher:
    def __init__(self, config: SlasherConfig = None, db=None):
        self.config = config or SlasherConfig()
        self._validators: dict[int, _ValidatorHistory] = {}
        # (proposer, slot) -> (header_root, signed_header)
        self._proposals: dict[tuple, tuple] = {}
        self._att_queue: list = []
        self._block_queue: list = []
        # detected slashings, deduped by content root
        self.attester_slashings: dict[bytes, object] = {}
        self.proposer_slashings: dict[bytes, object] = {}
        self.db = None
        if db is not None:
            from .database import SlasherDB

            self.db = SlasherDB(db) if not isinstance(db, SlasherDB) else db
            self._proposals = self.db.load_proposals()
            # crash replay: anything journaled but not processed
            for kind, payload, key in self.db.drain_queue():
                if kind == b"a":
                    self._att_queue.append(
                        (T.IndexedAttestation.deserialize(payload), key)
                    )
                else:
                    self._block_queue.append(
                        (T.SignedBeaconBlockHeader.deserialize(payload), key)
                    )

    # ------------------------------------------------------------ ingest

    def queue_attestation(self, indexed_att) -> None:
        """Batch ingest buffer (attestation_queue.rs); journaled when
        persistent so a crash between queue and process replays it."""
        key = None
        if self.db is not None:
            key = self.db.enqueue(
                b"a", T.IndexedAttestation.serialize(indexed_att)
            )
        self._att_queue.append((indexed_att, key))

    def queue_block_header(self, signed_header) -> None:
        key = None
        if self.db is not None:
            key = self.db.enqueue(
                b"b", T.SignedBeaconBlockHeader.serialize(signed_header)
            )
        self._block_queue.append((signed_header, key))

    def process_queued(self) -> tuple:
        """Drain the queues; returns (new_attester_slashings,
        new_proposer_slashings) found in this batch."""
        new_att, new_prop = [], []
        atts, self._att_queue = self._att_queue, []
        blocks, self._block_queue = self._block_queue, []
        for ia, _ in atts:
            new_att.extend(self._process_attestation(ia))
        for sh, _ in blocks:
            s = self._process_block_header(sh)
            if s is not None:
                new_prop.append(s)
        # commit order: chunks/attestations FIRST, then the journal —
        # a crash in between replays (idempotent) rather than losing
        # votes from the on-disk detection arrays
        self._flush_dirty()
        if self.db is not None:
            for _, key in atts:
                if key is not None:
                    self.db.dequeue(key)
            for _, key in blocks:
                if key is not None:
                    self.db.dequeue(key)
        return new_att, new_prop

    def _flush_dirty(self) -> None:
        if self.db is None:
            return
        for v, hist in self._validators.items():
            if hist.dirty:
                self.db.store_chunks(
                    v,
                    hist.min_targets,
                    hist.max_targets,
                    hist.offset,
                    hist.dirty,
                )
                hist.dirty.clear()

    # ------------------------------------------------------------ blocks

    def _process_block_header(self, signed_header):
        h = signed_header.message
        key = (int(h.proposer_index), int(h.slot))
        root = h.hash_tree_root()
        prev = self._proposals.get(key)
        if prev is None:
            self._proposals[key] = (root, signed_header)
            if self.db is not None:
                self.db.store_proposal(key[0], key[1], signed_header)
            return None
        prev_root, prev_signed = prev
        if prev_root == root:
            return None
        slashing = T.ProposerSlashing.make(
            signed_header_1=prev_signed, signed_header_2=signed_header
        )
        sroot = T.ProposerSlashing.hash_tree_root(slashing)
        if sroot in self.proposer_slashings:
            return None
        self.proposer_slashings[sroot] = slashing
        return slashing

    # ------------------------------------------------------------ votes

    def _history(self, v: int) -> _ValidatorHistory:
        hist = self._validators.get(v)
        if hist is None:
            w = self.config.history_length
            loaded = self.db.load_history(v, w) if self.db else None
            if loaded is not None:
                mins, maxs, offset = loaded
                hist = _ValidatorHistory(
                    min_targets=mins, max_targets=maxs, offset=offset
                )
                for target, root, source, att in self.db.load_attestations(v):
                    hist.by_target[target] = (bytes(root), att)
                    hist.votes.append((source, target))
            else:
                hist = _ValidatorHistory(
                    min_targets=np.full(w, _NO_MIN, dtype=np.int64),
                    max_targets=np.full(w, _NO_MAX, dtype=np.int64),
                )
            self._validators[v] = hist
        return hist

    def _slide_window(self, hist: _ValidatorHistory, epoch: int) -> None:
        """Keep `epoch` addressable: slide the window forward, dropping
        the oldest entries (sliding-base equivalent of the reference's
        chunk pruning — no absolute-epoch blind spot past the window)."""
        w = self.config.history_length
        if epoch < hist.offset + w:
            return
        shift = epoch - (hist.offset + w) + 1
        if shift >= w:
            hist.min_targets.fill(_NO_MIN)
            hist.max_targets.fill(_NO_MAX)
        else:
            hist.min_targets[:-shift] = hist.min_targets[shift:]
            hist.min_targets[-shift:] = _NO_MIN
            hist.max_targets[:-shift] = hist.max_targets[shift:]
            hist.max_targets[-shift:] = _NO_MAX
        hist.offset += shift
        if self.db is not None:
            from .database import CHUNK

            hist.dirty.update(range(0, -(-w // CHUNK)))

    def _process_attestation(self, indexed_att) -> list:
        data = indexed_att.data
        source = int(data.source.epoch)
        target = int(data.target.epoch)
        root = T.AttestationData.hash_tree_root(data)
        w = self.config.history_length
        found = []
        for v in indexed_att.attesting_indices:
            v = int(v)
            hist = self._history(v)
            self._slide_window(hist, max(source, target))
            # 1. double vote: same target, different data
            prev = hist.by_target.get(target)
            if prev is not None and prev[0] != root:
                found.append(self._emit_double(v, prev[1], indexed_att))
            # 2. surround checks via the arrays (both directions);
            # sources older than the window have no surround history
            idx = source - hist.offset
            if 0 <= idx < w:
                if hist.min_targets[idx] < target:
                    other = self._find_vote(hist, lambda s, t: s > source and t < target)
                    if other is not None:
                        found.append(
                            self._emit_surround(v, indexed_att, other)
                        )
                if hist.max_targets[idx] > target:
                    other = self._find_vote(hist, lambda s, t: s < source and t > target)
                    if other is not None:
                        found.append(
                            self._emit_surround(v, other, indexed_att)
                        )
            # 3. record the vote (vectorized slice updates in window
            # coordinates: min over epochs < source, max over > source)
            if prev is None:
                hist.by_target[target] = (root, indexed_att)
                hist.votes.append((source, target))
                lo_end = max(0, min(idx, w))
                changed = []
                if lo_end > 0:
                    lo = hist.min_targets[:lo_end]
                    if self.db is not None:
                        changed.append(np.flatnonzero(lo > target))
                    np.minimum(lo, target, out=lo)
                hi_start = max(0, idx + 1)
                if hi_start < w:
                    hi = hist.max_targets[hi_start:]
                    if self.db is not None:
                        changed.append(
                            np.flatnonzero(hi < target) + hi_start
                        )
                    np.maximum(hi, target, out=hi)
                if self.db is not None:
                    self.db.store_attestation(
                        v, target, root, source, indexed_att
                    )
                    from .database import CHUNK

                    for arr in changed:
                        if len(arr):
                            hist.dirty.update(
                                range(arr[0] // CHUNK, arr[-1] // CHUNK + 1)
                            )
        return [s for s in found if s is not None]

    def _find_vote(self, hist: _ValidatorHistory, pred):
        for s, t in hist.votes:
            if pred(s, t):
                entry = hist.by_target.get(t)
                if entry is not None:
                    return entry[1]
        return None

    def _emit_double(self, v: int, att_1, att_2):
        return self._emit(att_1, att_2)

    def _emit_surround(self, v: int, surrounder, surrounded):
        """attestation_1 surrounds attestation_2 (spec is_slashable
        ordering: is_slashable_attestation_data(data_1, data_2))."""
        return self._emit(surrounder, surrounded)

    def _emit(self, att_1, att_2):
        slashing = T.AttesterSlashing.make(
            attestation_1=att_1, attestation_2=att_2
        )
        root = T.AttesterSlashing.hash_tree_root(slashing)
        if root in self.attester_slashings:
            return None
        self.attester_slashings[root] = slashing
        return slashing

    # ------------------------------------------------------------ pruning

    def prune(self, current_epoch: int) -> None:
        """Drop history beyond the window (migrate.rs role)."""
        cutoff = max(0, current_epoch - self.config.history_length)
        for v, hist in self._validators.items():
            hist.votes = [(s, t) for s, t in hist.votes if t >= cutoff]
            dropped = [t for t in hist.by_target if t < cutoff]
            for t in dropped:
                del hist.by_target[t]
                if self.db is not None:
                    self.db.delete_attestation(v, t)
        dropped_props = [
            k
            for k in self._proposals
            if k[1] < cutoff * self.config.slots_per_epoch
        ]
        for k in dropped_props:
            del self._proposals[k]
            if self.db is not None:
                self.db.delete_proposal(k[0], k[1])
