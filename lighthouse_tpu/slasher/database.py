"""Persistent slasher storage over the node's KV engine
(slasher/src/database/mod.rs analog; backends there are MDBX/LMDB/redb —
here any lighthouse_tpu.node.store.KVStore, including the native C++
engine, native/kvstore.cpp).

Layout (array.rs's chunked min/max targets, made durable):

  column b"slc" — min/max-target chunks:
      key = validator u64be || chunk_index u32be
      val = CHUNK x (min_target i64le || max_target i64le)
    Chunks are CHUNK epochs wide in WINDOW coordinates; only dirty
    chunks are rewritten on update (the reference's chunked write
    batching, array.rs).

  column b"slo" — per-validator window offset:
      key = validator u64be ; val = offset u64le

  column b"sla" — recorded attestations:
      key = validator u64be || target u64be
      val = data_root 32B || source u64le || ssz(IndexedAttestation)

  column b"slp" — proposals:
      key = proposer u64be || slot u64be ; val = ssz(SignedHeader)

  column b"slq" — ingest queue (crash replay):
      key = kind 1B || seq u64be ; val = ssz payload
    Entries are appended by queue_* and deleted after process_queued
    commits its batch — a restart replays anything still queued
    (attestation_queue.rs durability the reference gets from running
    detection inside a txn).
"""

from __future__ import annotations

import struct

import numpy as np

from ..consensus import types as T

CHUNK = 256


class SlasherDB:
    """Thin column codec over a KVStore; the Slasher owns the policy."""

    def __init__(self, kv):
        self.kv = kv
        self._seq = 0
        for key in self.kv.keys(b"slq"):
            self._seq = max(self._seq, struct.unpack(">Q", key[1:9])[0] + 1)

    # ------------------------------------------------------------ chunks

    def load_history(self, v: int, window: int):
        """-> (min_targets, max_targets, offset) or None if absent."""
        off_raw = self.kv.get(b"slo", struct.pack(">Q", v))
        if off_raw is None:
            return None
        n_chunks = -(-window // CHUNK)
        mins = np.full(n_chunks * CHUNK, np.iinfo(np.int64).max, np.int64)
        maxs = np.full(n_chunks * CHUNK, -1, np.int64)
        for c in range(n_chunks):
            raw = self.kv.get(b"slc", struct.pack(">QI", v, c))
            if raw is None:
                continue
            arr = np.frombuffer(raw, dtype=np.int64).reshape(-1, 2)
            mins[c * CHUNK : c * CHUNK + len(arr)] = arr[:, 0]
            maxs[c * CHUNK : c * CHUNK + len(arr)] = arr[:, 1]
        return (
            mins[:window].copy(),
            maxs[:window].copy(),
            struct.unpack("<Q", off_raw)[0],
        )

    def store_chunks(self, v: int, mins, maxs, offset: int, dirty) -> None:
        """Write offset + the dirty chunk set (None -> all chunks)."""
        self.kv.put(b"slo", struct.pack(">Q", v), struct.pack("<Q", offset))
        window = len(mins)
        chunks = (
            range(-(-window // CHUNK)) if dirty is None else sorted(dirty)
        )
        for c in chunks:
            lo = c * CHUNK
            hi = min(lo + CHUNK, window)
            arr = np.empty((hi - lo, 2), np.int64)
            arr[:, 0] = mins[lo:hi]
            arr[:, 1] = maxs[lo:hi]
            self.kv.put(
                b"slc", struct.pack(">QI", v, c), arr.tobytes()
            )

    # ------------------------------------------------------- attestations

    def store_attestation(self, v: int, target: int, root: bytes, source: int, att) -> None:
        self.kv.put(
            b"sla",
            struct.pack(">QQ", v, target),
            bytes(root) + struct.pack("<Q", source) + T.IndexedAttestation.serialize(att),
        )

    def load_attestations(self, v: int):
        """-> list of (target, root, source, att) for validator v."""
        out = []
        prefix = struct.pack(">Q", v)
        for key in list(self.kv.keys(b"sla")):
            if not key.startswith(prefix):
                continue
            target = struct.unpack(">Q", key[8:16])[0]
            raw = self.kv.get(b"sla", key)
            if raw is None:
                continue
            root = raw[:32]
            source = struct.unpack("<Q", raw[32:40])[0]
            att = T.IndexedAttestation.deserialize(raw[40:])
            out.append((target, root, source, att))
        return out

    def delete_attestation(self, v: int, target: int) -> None:
        self.kv.delete(b"sla", struct.pack(">QQ", v, target))

    # ---------------------------------------------------------- proposals

    def store_proposal(self, proposer: int, slot: int, signed_header) -> None:
        self.kv.put(
            b"slp",
            struct.pack(">QQ", proposer, slot),
            T.SignedBeaconBlockHeader.serialize(signed_header),
        )

    def load_proposals(self):
        out = {}
        for key in list(self.kv.keys(b"slp")):
            raw = self.kv.get(b"slp", key)
            if raw is None:
                continue
            proposer, slot = struct.unpack(">QQ", key)
            sh = T.SignedBeaconBlockHeader.deserialize(raw)
            out[(proposer, slot)] = (
                T.BeaconBlockHeader.hash_tree_root(sh.message),
                sh,
            )
        return out

    def delete_proposal(self, proposer: int, slot: int) -> None:
        self.kv.delete(b"slp", struct.pack(">QQ", proposer, slot))

    # -------------------------------------------------------------- queue

    def enqueue(self, kind: bytes, payload: bytes) -> bytes:
        key = kind + struct.pack(">Q", self._seq)
        self._seq += 1
        self.kv.put(b"slq", key, payload)
        return key

    def drain_queue(self):
        """-> list of (kind, payload, key), oldest first."""
        keys = sorted(self.kv.keys(b"slq"), key=lambda k: k[1:9])
        out = []
        for key in keys:
            raw = self.kv.get(b"slq", key)
            if raw is not None:
                out.append((key[:1], raw, key))
        return out

    def dequeue(self, key: bytes) -> None:
        self.kv.delete(b"slq", key)
