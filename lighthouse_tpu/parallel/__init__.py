"""Multi-chip parallelism: mesh construction and sharded batch verify.

The reference's distributed plane is libp2p between hosts (SURVEY.md
§5.8); the TPU build adds the plane the reference never needed — XLA
collectives over ICI inside a pod slice. The one large axis in this
workload is the signature-set batch (SURVEY.md §5.7), so the design
shards it: each device runs the full per-set pipeline on its shard, and
only two tiny objects cross the interconnect per batch — one Fp12
Miller-product ([2,3,2,36] int32) and one Jacobian G2 partial sum —
via all_gather, followed by a replicated final exponentiation.
"""

__all__ = ["make_mesh", "sharded_verify_fn"]


def __getattr__(name):
    # Lazy: importing .verify pulls the kernel modules, whose
    # module-level jnp constants INITIALIZE the default jax backend.
    # `python -m lighthouse_tpu.parallel.bench` must be able to
    # re-assert jax_platforms (a tunnel PJRT plugin can preset it via
    # sitecustomize) BEFORE that happens — eager package imports here
    # would initialize the tunnel backend first and block on the chip.
    if name in __all__:
        from . import verify

        return getattr(verify, name)
    raise AttributeError(name)
