"""Multi-chip parallelism: mesh construction and sharded batch verify.

The reference's distributed plane is libp2p between hosts (SURVEY.md
§5.8); the TPU build adds the plane the reference never needed — XLA
collectives over ICI inside a pod slice. The one large axis in this
workload is the signature-set batch (SURVEY.md §5.7), so the design
shards it: each device runs the full per-set pipeline on its shard, and
only two tiny objects cross the interconnect per batch — one Fp12
Miller-product ([2,3,2,36] int32) and one Jacobian G2 partial sum —
via all_gather, followed by a replicated final exponentiation.
"""

from .verify import make_mesh, sharded_verify_fn

__all__ = ["make_mesh", "sharded_verify_fn"]
