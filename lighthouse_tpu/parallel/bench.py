"""Mesh-scaling bench for the sharded verifier (VERDICT r4 weak #8:
the ≥192k sets/s north star rides a ~Ndev multiplier that had no
measurement mode). Run on any host:

    python -m lighthouse_tpu.parallel.bench [n_sets] [n_devices]

On the CPU image this measures the virtual 8-device mesh (correctness
+ plumbing, NOT a perf claim — virtual devices share one core); on a
real TPU slice the same entry point prints the actual multiplier the
north star depends on.

Kept OUT of parallel/verify.py deliberately: that file is part of the
dryrun export fingerprint (__graft_entry__), and editing it would
invalidate the cached mesh module the driver's dryrun loads.
"""

from __future__ import annotations

import json
import os
import time

import jax
import numpy as np

# a TPU-tunnel PJRT plugin may override jax_platforms at interpreter
# startup (sitecustomize), making the JAX_PLATFORMS env var a no-op;
# re-assert it via jax.config BEFORE any backend initializes (same
# posture as tests/conftest.py) so `JAX_PLATFORMS=cpu python -m ...`
# actually runs on the virtual CPU mesh instead of blocking on the chip
_plat = os.environ.get("JAX_PLATFORMS")
if _plat:
    jax.config.update("jax_platforms", _plat)


def _mesh_callable(mesh, args):
    """The mesh program for `args`: the dryrun's serialized jax.export
    module when one matches (skips the ~13-30 min trace+lower on a
    single core — BASELINE.md ops notes), else a fresh jit. Exported
    modules need mesh-placed operands; wrap placement in."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from . import verify as PV

    here = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    try:
        from ..crypto.bls.backends import tpu as TB

        fp = TB.source_fingerprint(
            extra_paths=[
                os.path.join(here, "lighthouse_tpu/parallel/verify.py")
            ]
        )
        path = os.path.join(
            here, ".graft_export", f"verify_mesh_{mesh.size}_{fp}.bin"
        )
        if os.path.exists(path):
            from jax import export as jexport

            with open(path, "rb") as f:
                call = jexport.deserialize(f.read()).call

            def placed_call(*a):
                placed = [
                    jax.device_put(
                        x,
                        NamedSharding(
                            mesh, P(*([None] * (x.ndim - 1) + ["batch"]))
                        ),
                    )
                    for x in a
                ]
                return call(*placed)

            # validate shapes with one probe call; fall back on mismatch
            placed_call(*args)
            return placed_call, True
    except Exception:
        pass
    return jax.jit(PV.sharded_verify_fn(mesh)), False


def bench_mesh(
    n_sets: int = 1024,
    n_devices: int = None,
    iters: int = 3,
    include_single: bool = True,
) -> dict:
    from ..crypto import bls
    from ..crypto.bls.backends import tpu as TB
    from ..crypto.bls.keys import SecretKey, SignatureSet
    from . import verify as PV

    sk = SecretKey.from_seed(b"\x31" * 4)
    msgs = [b"mesh-bench-%d" % (i % 4) for i in range(n_sets)]
    sets = [
        SignatureSet.single_pubkey(sk.sign(m), sk.public_key(), m)
        for m in msgs
    ]
    scalars = bls.gen_batch_scalars(n_sets)
    args = TB.prepare_batch(sets, scalars)

    mesh = PV.make_mesh(n_devices)
    fn, via_export = _mesh_callable(mesh, args)
    ok = bool(np.asarray(jax.block_until_ready(fn(*args))))  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jax.block_until_ready(fn(*args))
    mesh_dt = (time.perf_counter() - t0) / iters

    result = {
        "n_sets": n_sets,
        "n_devices": mesh.size,
        "backend": jax.default_backend(),
        "ok": ok and bool(np.asarray(out)),
        "via_export": via_export,
        "mesh_p50_s": round(mesh_dt, 4),
        "mesh_sets_per_s": round(n_sets / mesh_dt, 1),
    }
    if include_single:
        single = TB.verify_callable(args[0].shape[-1])
        jax.block_until_ready(single(*args))  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(single(*args))
        single_dt = (time.perf_counter() - t0) / iters
        result["single_p50_s"] = round(single_dt, 4)
        result["single_sets_per_s"] = round(n_sets / single_dt, 1)
        result["mesh_multiplier"] = round(single_dt / mesh_dt, 2)
    return result


if __name__ == "__main__":
    import sys

    n_sets = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    n_devices = int(sys.argv[2]) if len(sys.argv) > 2 else None
    print(json.dumps(bench_mesh(n_sets, n_devices)))
