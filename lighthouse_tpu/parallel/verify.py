"""Sharded batch BLS verification over a jax.sharding.Mesh.

Layout: all per-set inputs sharded on the TRAILING lane axis (the
round-3 lane-major layout — batch rides the 128-wide lane dimension,
ops/lane/__init__.py); per-device `local_phase` (hash-to-curve,
subgroup checks, ladders, local Miller product, local signature sum)
needs NO communication; the cross-device step is one all_gather of an
Fp12 value and one of a G2 point per batch — a few KB over ICI — then
every device finishes redundantly (replicated final exp) so the verdict
is replicated.

This is the scaling seam BASELINE.json names ("shards SignatureSet
batches across a TPU pod slice"): throughput scales with devices because
the heavy math never leaves the shard.
"""

import inspect
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..crypto.bls.backends import tpu as TB
from ..ops.lane import jacobian as J, pairing as OP


def _shard_map(f, *, mesh, in_specs, out_specs, relaxed_replication):
    """Version-portable shard_map: the replication-checking kwarg was
    renamed check_rep -> check_vma across JAX releases, and the modern
    entry point moved from jax.experimental.shard_map to jax.shard_map.
    Feature-detect instead of pinning a spelling (VERDICT r1 #1)."""
    sm = getattr(jax, "shard_map", None)
    if sm is None:  # pragma: no cover - older JAX
        from jax.experimental.shard_map import shard_map as sm
    params = inspect.signature(sm).parameters
    kw = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    for name in ("check_vma", "check_rep"):
        if name in params:
            kw[name] = not relaxed_replication
            break
    else:
        raise RuntimeError(
            "shard_map exposes neither check_vma nor check_rep; "
            "update _shard_map for this JAX version"
        )
    return sm(f, **kw)


def make_mesh(n_devices: int = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), ("batch",))


def sharded_verify_fn(mesh: Mesh):
    """Build the jitted sharded verifier for `mesh`. Inputs are the same
    8 arrays as backends.tpu._verify_kernel (lane-major: batch on the
    trailing axis); batch divisible by mesh size (bucketing already pads
    to powers of two)."""
    ndev = mesh.devices.size
    # shard every array on its trailing (lane) axis
    last = lambda r: P(*([None] * (r - 1) + ["batch"]))
    in_specs = (
        last(2),  # apk_x [W, S]
        last(2),  # apk_y
        last(3),  # sig_x [2, W, S]
        last(3),  # sig_y
        last(3),  # t0
        last(3),  # t1
        last(2),  # rbits [64, S]
        last(1),  # pad [S]
    )

    # check_vma off: the kernel's scan carries are zeros-initialized
    # inside the shard (unvarying) while bodies produce batch-varying
    # values — semantically fine (zeros are trivially replicated), but
    # jax's varying-manual-axes typing would demand pvary at every
    # scan init throughout the kernel stack.
    @jax.jit
    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        relaxed_replication=True,
    )
    def kernel(apk_x, apk_y, sig_x, sig_y, t0, t1, rbits, pad):
        f_local, s_local, sub_ok = TB.local_phase(
            apk_x, apk_y, sig_x, sig_y, t0, t1, rbits, pad
        )
        # cross-device: gather tiny partials onto the lane axis, finish
        # redundantly. all_gather(axis=-1, tiled) turns the [.., 1]
        # per-device partials into [.., ndev] lane stacks.
        f_all = jax.lax.all_gather(f_local, "batch", axis=f_local.ndim - 1, tiled=True)
        f_prod = OP.lane_product(f_all, ndev)
        s_all = tuple(
            jax.lax.all_gather(c, "batch", axis=c.ndim - 1, tiled=True)
            for c in s_local
        )
        s_agg = J.lane_sum(J.FP2, s_all, ndev)
        ok_all = jnp.all(jax.lax.all_gather(sub_ok, "batch"))
        return TB.finish_phase(f_prod, s_agg, ok_all)

    return kernel
