"""The validator-client loop (validator_services attestation_service /
block_service analog): per slot —

  slot start : propose if we hold the proposer duty (block_service)
  slot + 1/3 : produce/sign/publish attestations (attestation_service)
  slot + 2/3 : aggregate-and-proof for aggregator duties

The beacon node boundary is a small interface (`BeaconNodeApi`) the
in-process node implements by direct chain calls; a typed HTTP client
implements the same methods across processes (common/eth2 role). Every
signature goes through the ValidatorStore, i.e. the slashing DB.
"""

from __future__ import annotations

from typing import Optional

from ..consensus import state_transition as st
from ..consensus import types as T
from ..consensus.spec import ChainSpec
from .duties import DutiesService
from .signing_method import RemoteSignerError
from .slashing_protection import SlashingProtectionError
from .validator_store import ValidatorStore


class BeaconNodeApi:
    """What the VC needs from a BN (the eth2 typed-client surface the
    services use)."""

    def head_state(self):
        raise NotImplementedError

    def produce_block(self, slot: int, randao_reveal: bytes, graffiti=None):
        raise NotImplementedError

    def publish_block(self, signed_block) -> None:
        raise NotImplementedError

    def attestation_data(self, slot: int, committee_index: int):
        raise NotImplementedError

    def publish_attestation(self, attestation) -> None:
        raise NotImplementedError

    def aggregate_for(self, data, committee_bits=None) -> Optional[object]:
        raise NotImplementedError

    def publish_aggregate(self, signed_aggregate) -> None:
        raise NotImplementedError

    def is_aggregator(self, committee_len: int, proof: bytes) -> bool:
        raise NotImplementedError

    def sync_committee_positions(self, validator_index: int) -> dict:
        raise NotImplementedError

    def publish_sync_message(self, msg) -> None:
        raise NotImplementedError

    def is_sync_aggregator(self, proof: bytes) -> bool:
        raise NotImplementedError

    def sync_contribution_for(self, slot, block_root, subcommittee):
        raise NotImplementedError

    def publish_sync_contribution(self, signed_contribution) -> None:
        raise NotImplementedError

    def head_root(self) -> bytes:
        raise NotImplementedError


class InProcessBeaconNode(BeaconNodeApi):
    """Direct chain wiring (the testing/simulator posture)."""

    def __init__(self, chain):
        self.chain = chain

    def head_state(self):
        return self.chain.head_state()

    def produce_block(self, slot, randao_reveal, graffiti=None):
        return self.chain.produce_block(
            slot, randao_reveal=randao_reveal, graffiti=graffiti
        )

    def publish_block(self, signed_block):
        self.chain.process_block(signed_block)

    def attestation_data(self, slot, committee_index):
        """produce_attestation_data role: head vote + justified source +
        epoch-boundary target."""
        chain = self.chain
        state = chain.head_state()
        adv = state
        if adv.slot < slot:
            adv = state.copy()
            st.process_slots(chain.spec, adv, slot)
        epoch = st.compute_epoch_at_slot(chain.spec, slot)
        boundary_slot = st.compute_start_slot_at_epoch(chain.spec, epoch)
        if chain.head.slot > boundary_slot:
            # spec get_block_root: the LATEST block at-or-before the
            # boundary (state.block_roots carries the last root through
            # skipped slots — a plain slot lookup would miss them)
            target_root = st.get_block_root_at_slot(
                chain.spec, adv, boundary_slot
            )
        else:
            target_root = chain.head.root
        return T.AttestationData.make(
            slot=slot,
            index=committee_index,
            beacon_block_root=chain.head.root,
            source=T.Checkpoint.make(
                epoch=adv.current_justified_checkpoint.epoch,
                root=bytes(adv.current_justified_checkpoint.root),
            ),
            target=T.Checkpoint.make(epoch=epoch, root=target_root),
        )

    def publish_attestation(self, attestation):
        v = self.chain.verify_attestation_for_gossip(attestation)
        self.chain.batch_verify_attestations([v])

    def aggregate_for(self, data, committee_bits=None):
        return self.chain.agg_pool.get_aggregate(data, committee_bits)

    def publish_aggregate(self, signed_aggregate):
        self.chain.verify_aggregate_for_gossip(signed_aggregate)

    def is_aggregator(self, committee_len, proof):
        return self.chain._is_aggregator(committee_len, proof)

    def sync_committee_positions(self, validator_index):
        return self.chain.sync_committee_positions(validator_index)

    def publish_sync_message(self, msg):
        self.chain.verify_sync_message_for_gossip(msg)

    def is_sync_aggregator(self, proof):
        return self.chain._is_sync_aggregator(proof)

    def sync_contribution_for(self, slot, block_root, subcommittee):
        return self.chain.agg_pool.get_contribution(
            slot, block_root, subcommittee
        )

    def publish_sync_contribution(self, signed_contribution):
        self.chain.verify_sync_contribution_for_gossip(signed_contribution)

    def head_root(self):
        return self.chain.head.root


class ValidatorClient:
    def __init__(
        self,
        spec: ChainSpec,
        store: ValidatorStore,
        bn: BeaconNodeApi,
        graffiti_provider=None,
        preparation_service=None,
    ):
        self.spec = spec
        self.store = store
        self.bn = bn
        # fee-recipient preparation + builder registrations, run once
        # per epoch from the slot loop (validator_services wiring)
        self.preparation = preparation_service
        self._prepared_epochs: set[int] = set()
        # pubkey -> Optional[32 bytes] (GraffitiFile.graffiti_for /
        # keymanager overrides); None falls back to the BN default
        self.graffiti_provider = graffiti_provider
        self.duties = DutiesService(
            spec, store, lambda: bn.head_state()
        )
        self._polled_epochs: set[int] = set()
        self.produced_blocks = 0
        self.published_attestations = 0
        self.published_aggregates = 0
        self.published_sync_messages = 0
        self.published_sync_contributions = 0
        self.slashing_vetoes = 0

    # ------------------------------------------------------------ duties

    def _ensure_duties(self, epoch: int) -> None:
        """Poll this epoch (and the next, for lookahead) once each
        (duties_service poll loop)."""
        for e in (epoch, epoch + 1):
            if e not in self._polled_epochs:
                self.duties.poll_epoch(e, self.bn.is_aggregator)
                self._polled_epochs.add(e)

    # ------------------------------------------------------------ slot loop

    def on_slot_start(self, slot: int) -> None:
        """Block proposal (block_service)."""
        epoch = st.compute_epoch_at_slot(self.spec, slot)
        self._ensure_duties(epoch)
        try:
            self._propose(slot, epoch)
        finally:
            # preparation runs AFTER the proposal work: registrations
            # feed the NEXT proposal's builder bid, and a slow signer
            # or builder endpoint (seconds of HTTP) must never delay
            # the block we owe this slot
            self._run_preparation(epoch)

    def _run_preparation(self, epoch: int) -> None:
        if self.preparation is None or epoch in self._prepared_epochs:
            return
        self._prepared_epochs.add(epoch)
        try:
            self.preparation.prepare_proposers()
            self.preparation.register_with_builder(epoch)
        except Exception as e:  # noqa: BLE001 — never fatal, retried
            from ..common import logging as clog

            clog.get_logger("vc").warning(
                "preparation round failed; will retry", error=str(e)
            )
            self._prepared_epochs.discard(epoch)

    def _propose(self, slot: int, epoch: int) -> None:
        duty = self.duties.proposer_duty_at(slot)
        if duty is None:
            return
        fork = self.bn.head_state().fork
        reveal = self.store.sign_randao(duty.pubkey, epoch, fork)
        graffiti = (
            self.graffiti_provider(duty.pubkey)
            if self.graffiti_provider is not None
            else None
        )
        block = self.bn.produce_block(slot, reveal, graffiti=graffiti)
        try:
            signed = self.store.sign_block(duty.pubkey, block, fork)
        except SlashingProtectionError:
            self.slashing_vetoes += 1
            return
        self.bn.publish_block(signed)
        self.produced_blocks += 1

    def on_slot_third(self, slot: int) -> None:
        """Attestation production at slot+1/3 (attestation_service)."""
        epoch = st.compute_epoch_at_slot(self.spec, slot)
        self._ensure_duties(epoch)
        fork = self.bn.head_state().fork
        by_committee: dict[int, object] = {}
        for duty in self.duties.attester_duties_at(slot):
            cached = by_committee.get(duty.committee_index)
            if cached is None:
                raw = self.bn.attestation_data(slot, duty.committee_index)
                cached = self._fork_shape(slot, raw, duty.committee_index)
                by_committee[duty.committee_index] = cached
            data, committee_bits = cached
            try:
                sig = self.store.sign_attestation(duty.pubkey, data, fork)
            except SlashingProtectionError:
                self.slashing_vetoes += 1
                continue
            except RemoteSignerError:
                continue  # one signer outage must not abort the slot
            bits = [
                i == duty.committee_position
                for i in range(duty.committee_length)
            ]
            att = T.Attestation.make(
                aggregation_bits=bits,
                data=data,
                signature=sig,
                # canonical internal shape: all-zero bits pre-electra
                # (types.Attestation doc) — None would poison block
                # packing and SSZ roots downstream
                committee_bits=committee_bits
                or [False] * self.spec.preset.max_committees_per_slot,
            )
            try:
                self.bn.publish_attestation(att)
            except Exception:
                # one rejected attestation (e.g. already covered by an
                # observed aggregate) must not abort the slot's other
                # duties
                continue
            self.published_attestations += 1

    def _fork_shape(self, slot: int, data, committee_index: int) -> tuple:
        """EIP-7549 shaping: post-electra the committee index moves
        from data.index into committee_bits (data.index = 0); the
        signed root therefore changes — shaping must happen BEFORE
        signing and slashing-DB recording."""
        if not self.spec.electra_enabled(
            st.compute_epoch_at_slot(self.spec, slot)
        ):
            return data, None
        shaped = T.AttestationData.make(
            slot=data.slot,
            index=0,
            beacon_block_root=bytes(data.beacon_block_root),
            source=data.source,
            target=data.target,
        )
        bits = [
            i == committee_index
            for i in range(self.spec.preset.max_committees_per_slot)
        ]
        return shaped, bits

    def _managed_validators(self, state) -> dict:
        """pubkey -> validator index for keys this VC holds (hoisted
        set: the registry scan must be O(V+K), not O(V*K))."""
        managed_set = set(self.store.pubkeys())
        return {
            bytes(v.pubkey): i
            for i, v in enumerate(state.validators)
            if bytes(v.pubkey) in managed_set
        }

    def on_slot_third_sync(self, slot: int) -> None:
        """Sync-committee message production (sync_committee_service):
        every managed validator in the current committee signs the head
        root at slot+1/3, alongside attestations."""
        state = self.bn.head_state()
        fork = state.fork
        head_root = self.bn.head_root()
        for pubkey, vidx in self._managed_validators(state).items():
            if not self.bn.sync_committee_positions(vidx):
                continue
            try:
                sig = self.store.sign_sync_committee_message(
                    pubkey, slot, head_root, fork
                )
            except Exception:
                continue
            msg = T.SyncCommitteeMessage.make(
                slot=slot,
                beacon_block_root=head_root,
                validator_index=vidx,
                signature=sig,
            )
            try:
                self.bn.publish_sync_message(msg)
                self.published_sync_messages += 1
            except Exception:
                continue

    def on_slot_two_thirds_sync(self, slot: int) -> None:
        """Sync contribution-and-proof for sync aggregator duties."""
        state = self.bn.head_state()
        fork = state.fork
        head_root = self.bn.head_root()
        for pubkey, vidx in self._managed_validators(state).items():
            for subcommittee in self.bn.sync_committee_positions(vidx):
                # cheap check first: no contribution -> no signing work
                contribution = self.bn.sync_contribution_for(
                    slot, head_root, subcommittee
                )
                if contribution is None:
                    continue
                proof = self.store.sync_selection_proof(
                    pubkey, slot, subcommittee, fork
                )
                if not self.bn.is_sync_aggregator(proof):
                    continue
                msg = T.ContributionAndProof.make(
                    aggregator_index=vidx,
                    contribution=contribution,
                    selection_proof=proof,
                )
                sig = self.store.sign_contribution_and_proof(
                    pubkey, msg, fork
                )
                signed = T.SignedContributionAndProof.make(
                    message=msg, signature=sig
                )
                try:
                    self.bn.publish_sync_contribution(signed)
                    self.published_sync_contributions += 1
                except Exception:
                    pass

    def on_slot_two_thirds(self, slot: int) -> None:
        """Aggregate-and-proof publication for aggregator duties."""
        fork = self.bn.head_state().fork
        for duty in self.duties.attester_duties_at(slot):
            if not duty.is_aggregator:
                continue
            raw = self.bn.attestation_data(slot, duty.committee_index)
            data, committee_bits = self._fork_shape(
                slot, raw, duty.committee_index
            )
            aggregate = self.bn.aggregate_for(data, committee_bits)
            if aggregate is None:
                continue
            msg = T.AggregateAndProof.make(
                aggregator_index=duty.validator_index,
                aggregate=aggregate,
                selection_proof=duty.selection_proof,
            )
            sig = self.store.sign_aggregate_and_proof(duty.pubkey, msg, fork)
            signed = T.SignedAggregateAndProof.make(message=msg, signature=sig)
            try:
                self.bn.publish_aggregate(signed)
                self.published_aggregates += 1
            except Exception:
                pass  # e.g. another aggregator already observed

    def run_slot(self, slot: int) -> None:
        """Drive all phases for tests/simulators."""
        self.on_slot_start(slot)
        self.on_slot_third(slot)
        self.on_slot_third_sync(slot)
        self.on_slot_two_thirds(slot)
        self.on_slot_two_thirds_sync(slot)
