"""Doppelganger detection (doppelganger_service analog, SURVEY.md §2.4,
§5.3).

Starting a VC whose keys are live elsewhere gets a validator slashed.
The reference holds every newly-added validator out of signing for ~2
full epochs while polling the BN's liveness endpoint; any sighting is
fatal (doppelganger_service/src/lib.rs:1-16: "assume that the worst
case will happen"). States per validator:

  epoch_checks < DEFAULT_REMAINING  → held (store keeps its hold)
  sighting observed                 → PERMANENT hold + shutdown request
  checks exhausted, no sightings    → hold cleared, signing enabled

The BN boundary is `liveness(epoch, indices) -> set(live indices)` —
the beacon API's /eth/v1/validator/liveness role, answered from the
chain's observed-attester sets.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..common import logging as clog

log = clog.get_logger("doppelganger")

# epochs of clean liveness observations required before signing
DEFAULT_REMAINING_DETECTION_EPOCHS = 2


class DoppelgangerDetected(Exception):
    def __init__(self, indices):
        super().__init__(f"doppelganger(s) detected for indices {sorted(indices)}")
        self.indices = set(indices)


class DoppelgangerService:
    def __init__(
        self,
        store,
        liveness: Callable[[int, list], set],
        index_of: Callable[[bytes], Optional[int]],
        remaining_epochs: int = DEFAULT_REMAINING_DETECTION_EPOCHS,
    ):
        """store: ValidatorStore (holds + clears); liveness: BN seam;
        index_of: pubkey → validator index (None until deposited)."""
        self.store = store
        self.liveness = liveness
        self.index_of = index_of
        self._remaining: dict[bytes, int] = {}
        self.default_remaining = remaining_epochs
        self.detected: set = set()

    def register(self, pubkey: bytes) -> None:
        """Put a validator under observation (the store must have been
        given doppelganger_hold=True for it)."""
        self._remaining[bytes(pubkey)] = self.default_remaining

    def unregister(self, pubkey: bytes) -> None:
        """Stop observing a key (keymanager DELETE) — a key migrated to
        another machine must not trip detection here afterwards."""
        self._remaining.pop(bytes(pubkey), None)

    def under_observation(self, pubkey: bytes) -> bool:
        return self._remaining.get(bytes(pubkey), 0) > 0

    def on_epoch(self, prior_epoch: int) -> list:
        """Run one detection round against the COMPLETED epoch. Returns
        pubkeys newly cleared for signing. Raises DoppelgangerDetected
        on any sighting (caller shuts the VC down — reference behavior)."""
        if not self._remaining:
            return []
        watched = {}
        for pk in list(self._remaining):
            idx = self.index_of(pk)
            if idx is not None:
                watched[idx] = pk
        live = self.liveness(prior_epoch, sorted(watched)) if watched else set()
        if live:
            hits = {watched[i] for i in live if i in watched}
            if hits:
                self.detected |= {bytes(h) for h in hits}
                log.error(
                    "DOPPELGANGER DETECTED — refusing to ever sign",
                    count=len(hits),
                )
                raise DoppelgangerDetected(
                    {self.index_of(pk) for pk in hits}
                )
        cleared = []
        for pk in list(self._remaining):
            # a validator with no index yet cannot have attested; its
            # observation window still counts down (it also can't sign)
            self._remaining[pk] -= 1
            if self._remaining[pk] <= 0:
                del self._remaining[pk]
                self.store.clear_doppelganger(pk)
                cleared.append(pk)
        if cleared:
            log.info("doppelganger holds cleared", count=len(cleared))
        return cleared
