"""Preparation service — validator_services/src/preparation_service.rs.

Two duties, both ahead of proposal slots:

1. **Fee-recipient preparation**: push (validator_index, fee_recipient)
   for every managed validator to the BN each epoch (the BN forwards
   them into payload attributes / prepare_beacon_proposer).
2. **Builder registration**: when an external builder is configured,
   sign ValidatorRegistrationData (DOMAIN_APPLICATION_BUILDER, epoch-
   independent domain) per validator and submit the batch to the
   builder (via the BN in the reference; directly to the builder client
   here — same wire contract).

Registrations are re-sent when stale (the reference refreshes every
epoch; builders expire them)."""

from __future__ import annotations

import time
from typing import Callable, Optional

from ..consensus import types as T
from ..consensus.domains import compute_domain, compute_signing_root

# builder specs: domain type 0x00000001, genesis fork, empty root
DOMAIN_APPLICATION_BUILDER = bytes.fromhex("00000001")
DEFAULT_GAS_LIMIT = 30_000_000


class PreparationService:
    def __init__(
        self,
        spec,
        store,
        beacon_node=None,
        builder_client=None,
        fee_recipient_for: Optional[Callable] = None,
        default_fee_recipient: bytes = b"\x00" * 20,
        gas_limit_for: Optional[Callable] = None,
        now: Callable = None,
    ):
        self.spec = spec
        self.store = store
        self.bn = beacon_node
        self.builder = builder_client
        self.fee_recipient_for = fee_recipient_for or (
            lambda pk: default_fee_recipient
        )
        # per-validator gas limit (keymanager /gas_limit routes feed
        # this in the wired client; defaults otherwise)
        self.gas_limit_for = gas_limit_for or (
            lambda pk: DEFAULT_GAS_LIMIT
        )
        self._now = now or (lambda: int(time.time()))
        self._registered_at: dict[bytes, int] = {}

    # ------------------------------------------------------------ duties

    def prepare_proposers(self) -> list:
        """(index-less) fee-recipient preparation batch -> BN."""
        prep = []
        for pk in self.store.pubkeys():
            prep.append(
                {
                    "pubkey": bytes(pk),
                    "fee_recipient": bytes(self.fee_recipient_for(pk)),
                }
            )
        if self.bn is not None and hasattr(self.bn, "prepare_proposers"):
            self.bn.prepare_proposers(prep)
        return prep

    def register_with_builder(self, epoch: int) -> int:
        """Sign + submit builder registrations for all managed keys.
        Returns the number submitted (0 when no builder configured)."""
        if self.builder is None:
            return 0
        regs = []
        now = self._now()
        for pk in self.store.pubkeys():
            if self._registered_at.get(bytes(pk)) == epoch:
                continue  # fresh this epoch
            reg = T.ValidatorRegistrationData.make(
                fee_recipient=bytes(self.fee_recipient_for(pk)),
                gas_limit=int(self.gas_limit_for(pk)),
                timestamp=now,
                pubkey=bytes(pk),
            )
            domain = compute_domain(
                DOMAIN_APPLICATION_BUILDER,
                self.spec.genesis_fork_version,
                b"\x00" * 32,
            )
            root = compute_signing_root(
                T.ValidatorRegistrationData.make(
                    fee_recipient=bytes(reg.fee_recipient),
                    gas_limit=int(reg.gas_limit),
                    timestamp=int(reg.timestamp),
                    pubkey=bytes(reg.pubkey),
                ),
                domain,
            )
            sig = self.store.sign_application(bytes(pk), root)
            regs.append(
                (
                    bytes(pk),
                    {
                        "pubkey": "0x" + bytes(pk).hex(),
                        "fee_recipient": "0x"
                        + bytes(reg.fee_recipient).hex(),
                        "gas_limit": str(int(reg.gas_limit)),
                        "timestamp": str(now),
                        "signature": "0x" + sig.to_bytes().hex(),
                    },
                )
            )
        if regs:
            # mark registered only AFTER the submit succeeds, so a
            # failed batch is retried on the next tick of the epoch
            self.builder.register_validators([r for _, r in regs])
            for pk, _ in regs:
                self._registered_at[pk] = epoch
        return len(regs)

    def on_epoch(self, epoch: int) -> None:
        """Epoch tick: failures are contained (the reference logs and
        retries next epoch; registration retries NEXT TICK since
        _registered_at is only advanced on success)."""
        from ..execution.builder_client import BuilderError

        self.prepare_proposers()
        try:
            self.register_with_builder(epoch)
        except BuilderError:
            pass
