"""Validator client components (validator_client/* analogs).

  signing_method     — local-key / remote-signer seam (signing_method crate)
  validator_store    — signing orchestration gated by slashing protection
                       (validator_store/src/lib.rs:575,671)
  duties             — attester/proposer duty computation + precomputed
                       selection proofs (validator_services/duties_service.rs)
  client             — the per-slot service loop: propose, attest at 1/3,
                       aggregate at 2/3 (attestation_service / block_service)
  slashing_protection— EIP-3076 SQLite watermarks (slashing_protection crate)
"""

from .slashing_protection import SlashingProtectionDB, SlashingProtectionError
from .signing_method import FakeSigner, LocalKeystoreSigner, SigningMethod
from .validator_store import ValidatorStore
from .duties import AttesterDuty, DutiesService, ProposerDuty
from .client import ValidatorClient

__all__ = [
    "SlashingProtectionDB",
    "SlashingProtectionError",
    "SigningMethod",
    "FakeSigner",
    "LocalKeystoreSigner",
    "ValidatorStore",
    "DutiesService",
    "AttesterDuty",
    "ProposerDuty",
    "ValidatorClient",
]
