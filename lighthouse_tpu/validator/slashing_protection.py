"""Slashing protection database (validator_client/slashing_protection
analog): SQLite low/high-watermark checks before EVERY signature, plus
EIP-3076 interchange import/export.

The reference's invariant (slashing_protection crate): a validator may
never sign (a) two different blocks at the same or lower slot, (b) an
attestation whose source is older than a previously signed source
(surround-vulnerable), or (c) an attestation whose target is at or
below a previously signed target (double/surrounded). Enforced here
with the same conservative monotonic-watermark scheme.
"""

from __future__ import annotations

import json
import sqlite3
import threading
from typing import Optional


class SlashingProtectionError(Exception):
    pass


_SCHEMA = """
CREATE TABLE IF NOT EXISTS validators (
    id INTEGER PRIMARY KEY,
    pubkey BLOB UNIQUE NOT NULL
);
CREATE TABLE IF NOT EXISTS signed_blocks (
    validator_id INTEGER NOT NULL REFERENCES validators(id),
    slot INTEGER NOT NULL,
    signing_root BLOB,
    UNIQUE (validator_id, slot)
);
CREATE TABLE IF NOT EXISTS signed_attestations (
    validator_id INTEGER NOT NULL REFERENCES validators(id),
    source_epoch INTEGER NOT NULL,
    target_epoch INTEGER NOT NULL,
    signing_root BLOB,
    UNIQUE (validator_id, target_epoch)
);
"""


class SlashingProtectionDB:
    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.executescript(_SCHEMA)
        self._lock = threading.Lock()

    # ------------------------------------------------------------ registry

    def register_validator(self, pubkey: bytes) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR IGNORE INTO validators (pubkey) VALUES (?)",
                (bytes(pubkey),),
            )
            self._conn.commit()

    def _vid(self, pubkey: bytes) -> int:
        row = self._conn.execute(
            "SELECT id FROM validators WHERE pubkey = ?", (bytes(pubkey),)
        ).fetchone()
        if row is None:
            raise SlashingProtectionError("validator not registered")
        return row[0]

    # ------------------------------------------------------------ blocks

    def check_and_insert_block_proposal(
        self, pubkey: bytes, slot: int, signing_root: bytes
    ) -> None:
        """Raise unless signing this proposal is safe; record it."""
        with self._lock:
            vid = self._vid(pubkey)
            row = self._conn.execute(
                "SELECT slot, signing_root FROM signed_blocks "
                "WHERE validator_id = ? AND slot = ?",
                (vid, slot),
            ).fetchone()
            if row is not None:
                if row[1] == signing_root:
                    return  # exact re-sign of the same block: safe
                raise SlashingProtectionError(
                    f"double block proposal at slot {slot}"
                )
            max_slot = self._conn.execute(
                "SELECT MAX(slot) FROM signed_blocks WHERE validator_id = ?",
                (vid,),
            ).fetchone()[0]
            if max_slot is not None and slot <= max_slot:
                raise SlashingProtectionError(
                    f"slot {slot} not above watermark {max_slot}"
                )
            self._conn.execute(
                "INSERT INTO signed_blocks VALUES (?, ?, ?)",
                (vid, slot, signing_root),
            )
            self._conn.commit()

    # ------------------------------------------------------------ attestations

    def check_and_insert_attestation(
        self,
        pubkey: bytes,
        source_epoch: int,
        target_epoch: int,
        signing_root: bytes,
    ) -> None:
        if source_epoch > target_epoch:
            raise SlashingProtectionError("source after target")
        with self._lock:
            vid = self._vid(pubkey)
            row = self._conn.execute(
                "SELECT source_epoch, signing_root FROM signed_attestations "
                "WHERE validator_id = ? AND target_epoch = ?",
                (vid, target_epoch),
            ).fetchone()
            if row is not None:
                if row[1] == signing_root and row[0] == source_epoch:
                    return  # exact duplicate: safe
                raise SlashingProtectionError(
                    f"double vote for target {target_epoch}"
                )
            ms, mt = self._conn.execute(
                "SELECT MAX(source_epoch), MAX(target_epoch) "
                "FROM signed_attestations WHERE validator_id = ?",
                (vid,),
            ).fetchone()
            if ms is not None and source_epoch < ms:
                raise SlashingProtectionError(
                    f"surround-vulnerable: source {source_epoch} < {ms}"
                )
            if mt is not None and target_epoch <= mt:
                raise SlashingProtectionError(
                    f"target {target_epoch} not above watermark {mt}"
                )
            self._conn.execute(
                "INSERT INTO signed_attestations VALUES (?, ?, ?, ?)",
                (vid, source_epoch, target_epoch, signing_root),
            )
            self._conn.commit()

    # ------------------------------------------------------------ interchange

    def export_interchange(self, genesis_validators_root: bytes) -> dict:
        """EIP-3076 interchange format export."""
        out = {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x"
                + bytes(genesis_validators_root).hex(),
            },
            "data": [],
        }
        with self._lock:
            for vid, pubkey in self._conn.execute(
                "SELECT id, pubkey FROM validators"
            ).fetchall():
                blocks = [
                    {
                        "slot": str(slot),
                        **(
                            {"signing_root": "0x" + sr.hex()}
                            if sr
                            else {}
                        ),
                    }
                    for slot, sr in self._conn.execute(
                        "SELECT slot, signing_root FROM signed_blocks "
                        "WHERE validator_id = ?",
                        (vid,),
                    ).fetchall()
                ]
                atts = [
                    {
                        "source_epoch": str(se),
                        "target_epoch": str(te),
                        **(
                            {"signing_root": "0x" + sr.hex()}
                            if sr
                            else {}
                        ),
                    }
                    for se, te, sr in self._conn.execute(
                        "SELECT source_epoch, target_epoch, signing_root "
                        "FROM signed_attestations WHERE validator_id = ?",
                        (vid,),
                    ).fetchall()
                ]
                out["data"].append(
                    {
                        "pubkey": "0x" + pubkey.hex(),
                        "signed_blocks": blocks,
                        "signed_attestations": atts,
                    }
                )
        return out

    def import_interchange(self, obj: dict) -> int:
        """Import (merge, keeping the most restrictive watermarks)."""
        count = 0
        for entry in obj.get("data", []):
            pubkey = bytes.fromhex(entry["pubkey"][2:])
            self.register_validator(pubkey)
            for b in entry.get("signed_blocks", []):
                try:
                    self.check_and_insert_block_proposal(
                        pubkey,
                        int(b["slot"]),
                        bytes.fromhex(b["signing_root"][2:])
                        if "signing_root" in b
                        else b"",
                    )
                except SlashingProtectionError:
                    pass  # keep existing, more restrictive record
            for a in entry.get("signed_attestations", []):
                try:
                    self.check_and_insert_attestation(
                        pubkey,
                        int(a["source_epoch"]),
                        int(a["target_epoch"]),
                        bytes.fromhex(a["signing_root"][2:])
                        if "signing_root" in a
                        else b"",
                    )
                except SlashingProtectionError:
                    pass
            count += 1
        return count

    def to_json(self, genesis_validators_root: bytes) -> str:
        return json.dumps(self.export_interchange(genesis_validators_root))
