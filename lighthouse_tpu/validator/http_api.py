"""VC management API — the keymanager spec + metrics
(validator_client/http_api + http_metrics analog, SURVEY.md §2.4).

Endpoints (the keymanager standard the reference implements):

  GET    /eth/v1/keystores                      list local keys
  POST   /eth/v1/keystores                      import keystores
  DELETE /eth/v1/keystores                      delete + export slashing data
  GET/POST/DELETE /eth/v1/validator/{pubkey}/feerecipient
  GET/POST/DELETE /eth/v1/validator/{pubkey}/graffiti
  GET    /lighthouse/version
  GET    /metrics                               prometheus text

Auth: `Authorization: Bearer <token>`; the token is written to
`api-token.txt` in the VC dir on startup (http_api/src/api_secret.rs
posture). Route logic is framework-free like node/http_api.
"""

from __future__ import annotations

import hmac
import json
import re
import secrets as _secrets
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional

from ..common import metrics
from ..crypto.keystore.keystore import Keystore, KeystoreError
from .signing_method import LocalKeystoreSigner

API_TOKEN_FILE = "api-token.txt"
# one source of truth for the default builder-registration gas limit
from .preparation_service import DEFAULT_GAS_LIMIT


class ApiError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class KeymanagerApi:
    """Route logic over the VC's moving parts."""

    def __init__(
        self,
        store,
        initialized,
        genesis_validators_root: bytes = b"\x00" * 32,
        graffiti_overrides: Optional[dict] = None,
        default_graffiti: Optional[str] = None,
        doppelganger_protection: bool = False,
        doppelganger_service=None,
    ):
        self.store = store
        self.initialized = initialized
        self.gvr = bytes(genesis_validators_root)
        # runtime (API-set) per-validator fee recipients + graffiti —
        # the reference persists these in the validator definitions
        self.fee_recipients: dict[bytes, str] = {}
        self.gas_limits: dict[bytes, int] = {}
        self.graffiti: dict[bytes, str] = graffiti_overrides or {}
        self.default_graffiti = default_graffiti
        # hot-imported keys must get the same doppelganger observation
        # window as startup-discovered ones
        self.doppelganger_protection = doppelganger_protection
        self.doppelganger_service = doppelganger_service

    # ------------------------------------------------------- keystores

    def list_keystores(self):
        data = []
        for d in self.initialized.definitions:
            if d.get("type", "local_keystore") != "local_keystore":
                continue
            data.append(
                {
                    "validating_pubkey": d["voting_public_key"],
                    "derivation_path": d.get("derivation_path", ""),
                    "readonly": not d.get("enabled", False),
                }
            )
        return 200, {"data": data}

    def import_keystores(self, body: bytes):
        req = json.loads(body)
        keystores = req.get("keystores", [])
        passwords = req.get("passwords", [])
        if len(keystores) != len(passwords):
            raise ApiError(400, "keystores/passwords length mismatch")
        if "slashing_protection" in req and req["slashing_protection"]:
            obj = req["slashing_protection"]
            if isinstance(obj, str):
                obj = json.loads(obj)
            self.store.slashing_db.import_interchange(obj)
        statuses = []
        known = {
            d["voting_public_key"].lower()
            for d in self.initialized.definitions
        }
        for raw, password in zip(keystores, passwords):
            try:
                ks = Keystore.from_json(raw if isinstance(raw, str) else json.dumps(raw))
                pk_hex = "0x" + ks.pubkey.hex()
                if pk_hex.lower() in known:
                    statuses.append({"status": "duplicate"})
                    continue
                sk = ks.decrypt(password)  # proves the password now
                self.initialized.definitions.append(
                    {
                        "enabled": True,
                        "voting_public_key": pk_hex,
                        "type": "local_keystore",
                        "voting_keystore_password": password,
                        "derivation_path": ks.path,
                        # imported inline: keystore JSON stored in the
                        # definition (no dir layout for API imports)
                        "voting_keystore_json": ks.to_json(),
                    }
                )
                known.add(pk_hex.lower())
                self.store.add_validator(
                    LocalKeystoreSigner(sk),
                    doppelganger_hold=self.doppelganger_protection,
                )
                if self.doppelganger_protection and self.doppelganger_service:
                    self.doppelganger_service.register(ks.pubkey)
                statuses.append({"status": "imported"})
            except (KeystoreError, ValueError) as e:
                statuses.append({"status": "error", "message": str(e)})
        self.initialized.save_definitions()
        return 200, {"data": statuses}

    def delete_keystores(self, body: bytes):
        req = json.loads(body)
        statuses = []
        for pk_hex in req.get("pubkeys", []):
            pk = bytes.fromhex(pk_hex[2:])
            # the key must stop signing BEFORE the response carries the
            # slashing export out (keymanager spec)
            removed_signer = self.store.remove_validator(pk)
            if self.doppelganger_service is not None:
                self.doppelganger_service.unregister(pk)
            if self.initialized.delete_definition(pk) or removed_signer:
                statuses.append({"status": "deleted"})
            else:
                statuses.append({"status": "not_found"})
        export = self.store.slashing_db.export_interchange(self.gvr)
        return 200, {
            "data": statuses,
            "slashing_protection": json.dumps(export),
        }

    # --------------------------------------------------- fee recipient

    def get_fee_recipient(self, pk_hex: str):
        pk = bytes.fromhex(pk_hex[2:])
        addr = self.fee_recipients.get(pk)
        if addr is None:
            raise ApiError(404, "no fee recipient set")
        return 200, {"data": {"pubkey": pk_hex, "ethaddress": addr}}

    def set_fee_recipient(self, pk_hex: str, body: bytes):
        req = json.loads(body)
        addr = req.get("ethaddress", "")
        if not re.fullmatch(r"0x[0-9a-fA-F]{40}", addr):
            raise ApiError(400, "bad ethaddress")
        self.fee_recipients[bytes.fromhex(pk_hex[2:])] = addr
        return 202, {}

    def delete_fee_recipient(self, pk_hex: str):
        self.fee_recipients.pop(bytes.fromhex(pk_hex[2:]), None)
        return 204, {}

    # -------------------------------------------------------- graffiti

    def get_graffiti(self, pk_hex: str):
        pk = bytes.fromhex(pk_hex[2:])
        g = self.graffiti.get(pk, self.default_graffiti)
        if g is None:
            raise ApiError(404, "no graffiti set")
        return 200, {"data": {"pubkey": pk_hex, "graffiti": g}}

    def set_graffiti(self, pk_hex: str, body: bytes):
        req = json.loads(body)
        self.graffiti[bytes.fromhex(pk_hex[2:])] = str(req.get("graffiti", ""))[:32]
        return 202, {}

    def delete_graffiti(self, pk_hex: str):
        self.graffiti.pop(bytes.fromhex(pk_hex[2:]), None)
        return 204, {}

    # ------------------------------------------------------ remotekeys
    # The keymanager remote-keys family (web3signer-backed validators;
    # validator_client/http_api's standard::remotekeys routes).

    def list_remotekeys(self):
        data = []
        for d in self.initialized.definitions:
            if d.get("type") != "web3signer":
                continue
            data.append(
                {
                    "pubkey": d["voting_public_key"],
                    "url": d.get("url", ""),
                    "readonly": not d.get("enabled", False),
                }
            )
        return 200, {"data": data}

    def import_remotekeys(self, body: bytes):
        from .signing_method import Web3SignerMethod

        req = json.loads(body)
        statuses = []
        known = {
            d["voting_public_key"].lower()
            for d in self.initialized.definitions
        }
        for entry in req.get("remote_keys", []):
            try:
                if not isinstance(entry, dict):
                    raise ValueError("entry must be an object")
                pk_hex = entry["pubkey"]
                url = entry.get("url", "")
                if not isinstance(pk_hex, str) or not re.fullmatch(
                    r"0x[0-9a-fA-F]{96}", pk_hex
                ):
                    raise ValueError("bad pubkey")
                if pk_hex.lower() in known:
                    statuses.append({"status": "duplicate"})
                    continue
                pk = bytes.fromhex(pk_hex[2:])
                self.initialized.definitions.append(
                    {
                        "enabled": True,
                        "voting_public_key": pk_hex,
                        "type": "web3signer",
                        "url": url,
                    }
                )
                known.add(pk_hex.lower())
                self.store.add_validator(
                    Web3SignerMethod(pk, url),
                    doppelganger_hold=self.doppelganger_protection,
                )
                if self.doppelganger_protection and self.doppelganger_service:
                    self.doppelganger_service.register(pk)
                statuses.append({"status": "imported"})
            except (KeyError, ValueError, TypeError) as e:
                statuses.append({"status": "error", "message": str(e)})
        self.initialized.save_definitions()
        return 200, {"data": statuses}

    def delete_remotekeys(self, body: bytes):
        req = json.loads(body)
        remote = {
            d["voting_public_key"].lower()
            for d in self.initialized.definitions
            if d.get("type") == "web3signer"
        }
        statuses = []
        for pk_hex in req.get("pubkeys", []):
            try:
                if not isinstance(pk_hex, str) or not re.fullmatch(
                    r"0x[0-9a-fA-F]{96}", pk_hex
                ):
                    raise ValueError("bad pubkey")
                # this route must only touch web3signer-backed keys —
                # local keystores are deleted via DELETE /keystores,
                # which also exports the slashing interchange
                if pk_hex.lower() not in remote:
                    statuses.append({"status": "not_found"})
                    continue
                pk = bytes.fromhex(pk_hex[2:])
                self.store.remove_validator(pk)
                if self.doppelganger_service is not None:
                    self.doppelganger_service.unregister(pk)
                self.initialized.delete_definition(pk)
                statuses.append({"status": "deleted"})
            except (KeyError, ValueError, TypeError) as e:
                statuses.append({"status": "error", "message": str(e)})
        self.initialized.save_definitions()
        return 200, {"data": statuses}

    # -------------------------------------------------------- gas limit

    def _known_pubkey(self, pk_hex: str) -> bool:
        low = pk_hex.lower()
        return any(
            d["voting_public_key"].lower() == low
            for d in self.initialized.definitions
        )

    def get_gas_limit(self, pk_hex: str):
        if not self._known_pubkey(pk_hex):
            raise ApiError(404, "unknown validator")
        pk = bytes.fromhex(pk_hex[2:])
        limit = self.gas_limits.get(pk, DEFAULT_GAS_LIMIT)
        return 200, {
            "data": {"pubkey": pk_hex, "gas_limit": str(limit)}
        }

    def set_gas_limit(self, pk_hex: str, body: bytes):
        if not self._known_pubkey(pk_hex):
            raise ApiError(404, "unknown validator")
        req = json.loads(body)
        try:
            limit = int(req["gas_limit"])
        except (KeyError, ValueError, TypeError):
            raise ApiError(400, "gas_limit required")
        if not 0 < limit < 2**64:
            raise ApiError(400, "gas_limit must be a positive u64")
        self.gas_limits[bytes.fromhex(pk_hex[2:])] = limit
        return 202, {}

    def delete_gas_limit(self, pk_hex: str):
        if not self._known_pubkey(pk_hex):
            raise ApiError(404, "unknown validator")
        self.gas_limits.pop(bytes.fromhex(pk_hex[2:]), None)
        return 204, {}

    def version(self):
        from ..node.http_api import VERSION

        return 200, {"data": {"version": VERSION}}


_ROUTES = [
    ("GET", re.compile(r"^/eth/v1/keystores$"), "list_keystores", False),
    ("POST", re.compile(r"^/eth/v1/keystores$"), "import_keystores", True),
    ("DELETE", re.compile(r"^/eth/v1/keystores$"), "delete_keystores", True),
    (
        "GET",
        re.compile(r"^/eth/v1/validator/(0x[0-9a-fA-F]{96})/feerecipient$"),
        "get_fee_recipient",
        False,
    ),
    (
        "POST",
        re.compile(r"^/eth/v1/validator/(0x[0-9a-fA-F]{96})/feerecipient$"),
        "set_fee_recipient",
        True,
    ),
    (
        "DELETE",
        re.compile(r"^/eth/v1/validator/(0x[0-9a-fA-F]{96})/feerecipient$"),
        "delete_fee_recipient",
        False,
    ),
    (
        "GET",
        re.compile(r"^/eth/v1/validator/(0x[0-9a-fA-F]{96})/graffiti$"),
        "get_graffiti",
        False,
    ),
    (
        "POST",
        re.compile(r"^/eth/v1/validator/(0x[0-9a-fA-F]{96})/graffiti$"),
        "set_graffiti",
        True,
    ),
    (
        "DELETE",
        re.compile(r"^/eth/v1/validator/(0x[0-9a-fA-F]{96})/graffiti$"),
        "delete_graffiti",
        False,
    ),
    ("GET", re.compile(r"^/eth/v1/remotekeys$"), "list_remotekeys", False),
    ("POST", re.compile(r"^/eth/v1/remotekeys$"), "import_remotekeys", True),
    (
        "DELETE",
        re.compile(r"^/eth/v1/remotekeys$"),
        "delete_remotekeys",
        True,
    ),
    (
        "GET",
        re.compile(r"^/eth/v1/validator/(0x[0-9a-fA-F]{96})/gas_limit$"),
        "get_gas_limit",
        False,
    ),
    (
        "POST",
        re.compile(r"^/eth/v1/validator/(0x[0-9a-fA-F]{96})/gas_limit$"),
        "set_gas_limit",
        True,
    ),
    (
        "DELETE",
        re.compile(r"^/eth/v1/validator/(0x[0-9a-fA-F]{96})/gas_limit$"),
        "delete_gas_limit",
        False,
    ),
    ("GET", re.compile(r"^/lighthouse/version$"), "version", False),
]


def make_handler(api: KeymanagerApi, token: str):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _send_json(self, code: int, obj) -> None:
            raw = b"" if code == 204 else json.dumps(obj).encode()
            self.send_response(code)
            if raw:
                self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            if raw:
                self.wfile.write(raw)

        def _authorized(self) -> bool:
            got = self.headers.get("Authorization", "")
            # constant-time compare: the bearer token gates keystore
            # import/delete; plain == leaks a timing side channel.
            # bytes, not str: compare_digest(str) raises on non-ASCII
            return hmac.compare_digest(
                got.encode("utf-8", "surrogateescape"),
                f"Bearer {token}".encode(),
            )

        def _dispatch(self, method: str, body: Optional[bytes]) -> None:
            path = self.path.split("?")[0]
            if method == "GET" and path == "/metrics":
                raw = metrics.gather().encode()
                self.send_response(200)
                # versioned content type (incl. charset): Prometheus
                # scrapers stop content-sniffing the exposition body
                self.send_header("Content-Type", metrics.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)
                return
            if not self._authorized():
                self._send_json(401, {"code": 401, "message": "invalid token"})
                return
            for m, pat, name, wants_body in _ROUTES:
                if m != method:
                    continue
                match = pat.match(path)
                if not match:
                    continue
                try:
                    args = list(match.groups())
                    if wants_body:
                        args.append(body)
                    code, obj = getattr(api, name)(*args)
                    self._send_json(code, obj)
                except ApiError as e:
                    self._send_json(e.code, {"code": e.code, "message": str(e)})
                except Exception as e:  # noqa: BLE001 — route boundary
                    self._send_json(400, {"code": 400, "message": str(e)})
                return
            self._send_json(404, {"code": 404, "message": "unknown route"})

        def do_GET(self):
            self._dispatch("GET", None)

        def do_POST(self):
            n = int(self.headers.get("Content-Length", "0"))
            self._dispatch("POST", self.rfile.read(n))

        def do_DELETE(self):
            n = int(self.headers.get("Content-Length", "0"))
            self._dispatch("DELETE", self.rfile.read(n) if n else None)

    return Handler


class ValidatorApiServer:
    """http_api::serve for the VC, with bearer-token auth."""

    def __init__(
        self,
        api: KeymanagerApi,
        datadir,
        host: str = "127.0.0.1",
        port: int = 0,
        token: Optional[str] = None,
    ):
        self.token = token or _secrets.token_hex(32)
        Path(datadir).mkdir(parents=True, exist_ok=True)
        token_path = Path(datadir) / API_TOKEN_FILE
        # owner-only: the token grants keystore import/delete
        # (api_secret.rs writes 0600)
        import os as _os

        fd = _os.open(
            token_path, _os.O_CREAT | _os.O_WRONLY | _os.O_TRUNC, 0o600
        )
        try:
            _os.write(fd, self.token.encode())
        finally:
            _os.close(fd)
        self.httpd = ThreadingHTTPServer((host, port), make_handler(api, self.token))
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="vc-http", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
