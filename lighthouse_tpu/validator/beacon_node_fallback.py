"""Multi-BN redundancy with health ranking
(common/beacon_node_fallback analog, SURVEY.md §2.4).

The reference wraps N BeaconNodeHttpClients in `CandidateBeaconNode`s,
periodically health-checks them (online → synced → optimistic), sorts by
health, and every VC request walks candidates in rank order until one
succeeds (`first_success`). Same shape over our `BeaconNodeApi` seam —
in-process nodes and HTTP-client-backed nodes rank identically.

Health ordering (beacon_node_fallback/src/lib.rs CandidateError +
health tiers): Synced < Syncing < Offline; ties break by user order.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence

from ..common import logging as clog
from ..common import metrics

log = clog.get_logger("fallback")

_FALLBACKS = metrics.counter(
    "vc_beacon_node_fallbacks_total",
    "Requests that fell back past the primary beacon node",
)

# health tiers, best (lowest) first
SYNCED = 0
SYNCING = 1
OFFLINE = 2

# re-probe an unhealthy candidate at most this often
HEALTH_CHECK_PERIOD = 12.0


class AllNodesFailed(Exception):
    def __init__(self, errors: list):
        super().__init__("; ".join(f"{n}: {e}" for n, e in errors))
        self.errors = errors


class CandidateBeaconNode:
    def __init__(self, api, name: str = "bn", sync_tolerance: int = 8):
        self.api = api
        self.name = name
        self.sync_tolerance = sync_tolerance
        self.health = SYNCED  # optimistic until first probe says otherwise
        self.last_probe = 0.0

    def probe(self) -> int:
        """One health observation. The BeaconNodeApi seam exposes
        `syncing_status() -> {is_syncing, sync_distance}` (HTTP:
        /eth/v1/node/syncing); in-process nodes are synced by
        construction if they answer at all."""
        try:
            status = getattr(self.api, "syncing_status", None)
            if status is None:
                self.api.head_root()  # answers → alive and local
                self.health = SYNCED
            else:
                s = status()
                syncing = s.get("is_syncing", False) and (
                    s.get("sync_distance", 0) > self.sync_tolerance
                )
                self.health = SYNCING if syncing else SYNCED
        except Exception as e:  # noqa: BLE001 — any failure = offline
            log.warning("beacon node offline", name=self.name, error=str(e))
            self.health = OFFLINE
        self.last_probe = time.monotonic()
        return self.health


class BeaconNodeFallback:
    """The ranked candidate list every VC request goes through."""

    def __init__(self, candidates: Sequence[CandidateBeaconNode]):
        if not candidates:
            raise ValueError("need at least one beacon node")
        self.candidates = list(candidates)
        self._lock = threading.Lock()

    @classmethod
    def from_apis(cls, apis: Sequence, sync_tolerance: int = 8):
        return cls(
            [
                CandidateBeaconNode(a, name=f"bn{i}", sync_tolerance=sync_tolerance)
                for i, a in enumerate(apis)
            ]
        )

    def update_all_candidates(self) -> None:
        """The periodic health-check task's body."""
        for c in self.candidates:
            c.probe()

    def _ranked(self) -> list:
        with self._lock:
            # stable sort: health tier, then user-given order
            return sorted(self.candidates, key=lambda c: c.health)

    def first_success(self, fn: Callable, *args, **kwargs):
        """Try `fn(api)` on each candidate in rank order; re-probe
        stale unhealthy candidates on the way. First success wins."""
        errors = []
        now = time.monotonic()
        for rank, cand in enumerate(self._ranked()):
            if cand.health != SYNCED and now - cand.last_probe > HEALTH_CHECK_PERIOD:
                cand.probe()
            try:
                result = fn(cand.api, *args, **kwargs)
                if rank > 0:
                    _FALLBACKS.inc()
                return result
            except Exception as e:  # noqa: BLE001 — candidate boundary
                errors.append((cand.name, e))
                # Only a TRANSPORT failure demotes the node. An HTTP
                # error response (status > 0, e.g. 404 for an unknown
                # validator) came from a live node answering correctly —
                # conflating it with health would mark every healthy
                # node offline on an application-level miss.
                if getattr(e, "status", 0) == 0:
                    cand.health = OFFLINE
                    cand.last_probe = time.monotonic()
        raise AllNodesFailed(errors)

    def num_available(self) -> int:
        return sum(1 for c in self.candidates if c.health != OFFLINE)
