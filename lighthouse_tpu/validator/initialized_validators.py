"""Keystore discovery / decryption / lifecycle
(common/initialized_validators analog, SURVEY.md §2.4).

The reference walks the validators dir, keeps a `validator_definitions.yml`
of definitions (enabled flag, voting keystore path, password source, or
web3signer URL), decrypts enabled keystores, and exposes the live set to
the ValidatorStore. Here the definitions file is JSON, and the output of
``initialize`` is SigningMethods pushed into a ValidatorStore.

Definition shapes (initialized_validators/src/lib.rs SigningDefinition):
  {"enabled": true, "voting_public_key": "0x..",
   "type": "local_keystore", "voting_keystore_path": "...",
   "voting_keystore_password_path": "..."}          # or inline password
  {"enabled": true, "voting_public_key": "0x..",
   "type": "web3signer", "url": "http://..."}
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Callable, Optional

from ..common import logging as clog
from ..common import validator_dir as vdir
from ..crypto.keystore.keystore import Keystore, KeystoreError
from .signing_method import LocalKeystoreSigner, SigningMethod, Web3SignerMethod

log = clog.get_logger("validator")

DEFINITIONS_FILE = "validator_definitions.json"


class InitializedValidators:
    """The live, decrypted validator set + its on-disk definitions."""

    def __init__(
        self,
        validators_dir,
        secrets_dir=None,
        web3signer_post: Optional[Callable] = None,
    ):
        self.validators_dir = Path(validators_dir)
        self.secrets_dir = Path(secrets_dir) if secrets_dir else None
        # None -> the SigningMethod's real HTTP transport
        self._web3signer_post = web3signer_post
        self.definitions: list[dict] = []
        self._methods: dict[bytes, SigningMethod] = {}
        self._load_definitions()

    # ------------------------------------------------------ definitions

    @property
    def _definitions_path(self) -> Path:
        return self.validators_dir / DEFINITIONS_FILE

    def _load_definitions(self) -> None:
        if self._definitions_path.exists():
            self.definitions = json.loads(self._definitions_path.read_text())
        else:
            self.definitions = []

    def save_definitions(self) -> None:
        import os

        self.validators_dir.mkdir(parents=True, exist_ok=True)
        # 0600: API-imported definitions carry inline keystore passwords
        fd = os.open(
            self._definitions_path,
            os.O_CREAT | os.O_WRONLY | os.O_TRUNC,
            0o600,
        )
        try:
            os.write(fd, json.dumps(self.definitions, indent=1).encode())
        finally:
            os.close(fd)

    def discover_local_keystores(self) -> int:
        """`discover_local_keystores`: scan the dir for validator
        subdirs not yet in the definitions; new ones are appended
        enabled, with the password expected in secrets_dir."""
        known = {d["voting_public_key"].lower() for d in self.definitions}
        added = 0
        for entry in vdir.list_validator_dirs(self.validators_dir):
            ks_path = entry / vdir.VOTING_KEYSTORE_FILE
            try:
                ks = Keystore.from_json(ks_path.read_text())
            except (KeystoreError, ValueError) as e:
                log.warning("skipping malformed keystore", path=str(ks_path), error=str(e))
                continue
            pk_hex = "0x" + ks.pubkey.hex()
            if pk_hex.lower() in known:
                continue
            d = {
                "enabled": True,
                "voting_public_key": pk_hex,
                "type": "local_keystore",
                "voting_keystore_path": str(ks_path),
            }
            if self.secrets_dir is not None:
                d["voting_keystore_password_path"] = str(self.secrets_dir / pk_hex)
            self.definitions.append(d)
            added += 1
        if added:
            self.save_definitions()
        return added

    # ------------------------------------------------------ lifecycle

    def initialize(self) -> dict:
        """Decrypt every enabled definition → {pubkey: SigningMethod}.
        A failed decrypt disables nothing but is logged and skipped
        (the reference surfaces it in the API as an error state)."""
        self._methods = {}
        for d in self.definitions:
            if not d.get("enabled", False):
                continue
            pk = bytes.fromhex(d["voting_public_key"][2:])
            try:
                self._methods[pk] = self._method_for(d)
            except (KeystoreError, OSError, ValueError) as e:
                log.warning(
                    "could not initialize validator",
                    pubkey=d["voting_public_key"], error=str(e),
                )
        return dict(self._methods)

    def _method_for(self, d: dict) -> SigningMethod:
        kind = d.get("type", "local_keystore")
        if kind == "web3signer":
            return Web3SignerMethod(
                bytes.fromhex(d["voting_public_key"][2:]),
                d["url"],
                self._web3signer_post,
            )
        if "voting_keystore_json" in d:  # API-imported inline keystore
            ks = Keystore.from_json(d["voting_keystore_json"])
        else:
            ks = Keystore.from_json(Path(d["voting_keystore_path"]).read_text())
        if "voting_keystore_password" in d:
            password = d["voting_keystore_password"]
        elif "voting_keystore_password_path" in d:
            password = Path(d["voting_keystore_password_path"]).read_text().strip()
        else:
            raise KeystoreError("no password source in definition")
        return LocalKeystoreSigner(ks.decrypt(password))

    def methods(self) -> dict:
        return dict(self._methods)

    def is_enabled(self, pubkey: bytes) -> Optional[bool]:
        pk_hex = ("0x" + bytes(pubkey).hex()).lower()
        for d in self.definitions:
            if d["voting_public_key"].lower() == pk_hex:
                return bool(d.get("enabled", False))
        return None

    def set_enabled(self, pubkey: bytes, enabled: bool) -> bool:
        """Keymanager enable/disable; returns True if the key is known."""
        pk_hex = ("0x" + bytes(pubkey).hex()).lower()
        for d in self.definitions:
            if d["voting_public_key"].lower() == pk_hex:
                d["enabled"] = enabled
                self.save_definitions()
                return True
        return False

    def delete_definition(self, pubkey: bytes) -> bool:
        pk_hex = ("0x" + bytes(pubkey).hex()).lower()
        before = len(self.definitions)
        self.definitions = [
            d for d in self.definitions
            if d["voting_public_key"].lower() != pk_hex
        ]
        if len(self.definitions) != before:
            self._methods.pop(bytes(pubkey), None)
            self.save_definitions()
            return True
        return False


