"""Duties service: who attests/proposes when, computed per epoch with
selection proofs precomputed at poll time
(validator_services/src/duties_service.rs:105-170,209).

The beacon-node boundary is a `duty_state_provider() -> state` callable
(direct chain access in-process; the typed HTTP client fills the same
seam across processes), so the service logic is transport-agnostic.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..consensus import state_transition as st
from ..consensus.spec import ChainSpec


@dataclass
class AttesterDuty:
    pubkey: bytes
    validator_index: int
    slot: int
    committee_index: int
    committee_position: int
    committee_length: int
    selection_proof: Optional[bytes] = None  # set if duty-holder aggregates
    is_aggregator: bool = False


@dataclass
class ProposerDuty:
    pubkey: bytes
    validator_index: int
    slot: int


class DutiesService:
    def __init__(self, spec: ChainSpec, store, duty_state_provider):
        self.spec = spec
        self.store = store  # ValidatorStore
        self._state_of = duty_state_provider
        # epoch -> {slot -> [AttesterDuty]} / {slot -> ProposerDuty}
        self._attesters: dict[int, dict] = {}
        self._proposers: dict[int, dict] = {}

    def poll_epoch(self, epoch: int, is_aggregator) -> None:
        """Compute every managed validator's duties for `epoch`;
        precompute selection proofs and the aggregator decision
        (duties_service.rs:128-158). `is_aggregator(committee_len,
        proof_bytes) -> bool` is the chain's modulo rule."""
        state = self._state_of()
        state = state.copy()
        target_slot = st.compute_start_slot_at_epoch(self.spec, epoch)
        if state.slot < target_slot:
            st.process_slots(self.spec, state, target_slot)
        managed_set = set(self.store.pubkeys())
        managed = {
            bytes(v.pubkey): i
            for i, v in enumerate(state.validators)
            if bytes(v.pubkey) in managed_set
        }
        att: dict[int, list] = {}
        prop: dict[int, object] = {}
        per_slot = st.get_committee_count_per_slot(self.spec, state, epoch)
        for slot in range(
            target_slot, target_slot + self.spec.preset.slots_per_epoch
        ):
            for cidx in range(per_slot):
                committee = st.get_beacon_committee(self.spec, state, slot, cidx)
                for pos, vidx in enumerate(committee):
                    pk = bytes(state.validators[vidx].pubkey)
                    if pk not in managed:
                        continue
                    duty = AttesterDuty(
                        pubkey=pk,
                        validator_index=vidx,
                        slot=slot,
                        committee_index=cidx,
                        committee_position=pos,
                        committee_length=len(committee),
                    )
                    duty.selection_proof = self.store.selection_proof(
                        pk, slot, state.fork
                    )
                    duty.is_aggregator = is_aggregator(
                        len(committee), duty.selection_proof
                    )
                    att.setdefault(slot, []).append(duty)
        # proposers: advance a copy through the epoch's slots
        walk = state
        for slot in range(
            target_slot, target_slot + self.spec.preset.slots_per_epoch
        ):
            if walk.slot < slot:
                st.process_slots(self.spec, walk, slot)
            vidx = st.get_beacon_proposer_index(self.spec, walk)
            pk = bytes(walk.validators[vidx].pubkey)
            if pk in managed:
                prop[slot] = ProposerDuty(
                    pubkey=pk, validator_index=vidx, slot=slot
                )
        self._attesters[epoch] = att
        self._proposers[epoch] = prop
        # retain a 2-epoch window
        for cache in (self._attesters, self._proposers):
            for e in [e for e in cache if e + 1 < epoch]:
                del cache[e]

    def attester_duties_at(self, slot: int) -> list:
        epoch = st.compute_epoch_at_slot(self.spec, slot)
        return self._attesters.get(epoch, {}).get(slot, [])

    def proposer_duty_at(self, slot: int) -> Optional[ProposerDuty]:
        epoch = st.compute_epoch_at_slot(self.spec, slot)
        return self._proposers.get(epoch, {}).get(slot)
