"""Signing methods (signing_method/src/lib.rs:79-90 analog).

`SigningMethod` is the seam between "what to sign" (a 32-byte signing
root, domain already mixed in) and "how": a local BLS key, or a remote
signer speaking the Web3Signer API. The store never touches raw secret
keys directly — doppelganger and slashing-protection gates live above
this seam, transport below it.
"""

from __future__ import annotations

from ..crypto.bls.keys import SecretKey, Signature


class SigningMethod:
    def sign(self, signing_root: bytes) -> Signature:
        raise NotImplementedError

    def public_key_bytes(self) -> bytes:
        raise NotImplementedError


class LocalKeystoreSigner(SigningMethod):
    """SigningMethod::LocalKeystore: in-process BLS sign."""

    def __init__(self, secret_key: SecretKey):
        self._sk = secret_key
        self._pk = secret_key.public_key().to_bytes()

    def sign(self, signing_root: bytes) -> Signature:
        return self._sk.sign(signing_root)

    def public_key_bytes(self) -> bytes:
        return self._pk


# the compressed point at infinity: decompresses to the identity in
# O(1) and aggregates as the identity, so signature bytes stay wire-
# valid without any curve math
_INFINITY_SIGNATURE = bytes([0xC0]) + b"\x00" * 95


class FakeSigner(SigningMethod):
    """The signing half of the fake-crypto backend (crypto/bls/src/
    impls/fake_crypto.rs AggregateSignature::infinity role): a real
    public key with infinity signatures. Only meaningful against chains
    running `bls_backend="fake"` — pure-Python G2 ladders dominate
    multi-node simulation wall clock otherwise, and the fake verifier
    never looks at the bytes anyway."""

    def __init__(self, secret_key: SecretKey):
        self._pk = secret_key.public_key().to_bytes()

    def sign(self, signing_root: bytes) -> Signature:
        return Signature.from_bytes(_INFINITY_SIGNATURE)

    def public_key_bytes(self) -> bytes:
        return self._pk


class Web3SignerMethod(SigningMethod):
    """SigningMethod::Web3Signer: remote HTTP signer. The transport is a
    callable (url, signing_root) -> signature bytes so the HTTP client
    (and its tests) slot in without this module importing one; pass
    `web3signer_http_post` for the real wire."""

    def __init__(self, public_key: bytes, url: str, post=None):
        self._pk = bytes(public_key)
        self.url = url
        self._post = post or web3signer_http_post

    def sign(self, signing_root: bytes) -> Signature:
        return Signature.from_bytes(self._post(self.url, signing_root))

    def public_key_bytes(self) -> bytes:
        return self._pk


class RemoteSignerError(Exception):
    """Typed transport/protocol failure from a remote signer — duty
    loops catch THIS, never raw urllib exceptions."""


def web3signer_http_post(
    url: str, signing_root: bytes, timeout: float = 3.0
) -> bytes:
    """The web3signer REST wire: POST /api/v1/eth2/sign/{identifier}
    with {"signing_root": "0x.."}; the response body is the 0x-hex
    signature (possibly JSON-wrapped). The default timeout stays well
    inside the slot/3 attestation window."""
    import json
    import urllib.error
    import urllib.request

    body = json.dumps({"signing_root": "0x" + bytes(signing_root).hex()})
    req = urllib.request.Request(
        url,
        data=body.encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read().decode().strip()
    except urllib.error.HTTPError as e:
        raise RemoteSignerError(
            f"signer HTTP {e.code}: {e.read().decode(errors='replace')[:200]}"
        ) from None
    except (urllib.error.URLError, OSError) as e:
        raise RemoteSignerError(f"signer unreachable: {e}") from None
    if raw.startswith("{"):
        obj = json.loads(raw)
        if "signature" not in obj:
            raise RemoteSignerError(
                f"signer response lacks 'signature': {raw[:200]}"
            )
        raw = obj["signature"]
    if raw.startswith('"'):
        raw = raw.strip('"')
    if raw.startswith("0x"):
        raw = raw[2:]
    try:
        out = bytes.fromhex(raw)
    except ValueError:
        raise RemoteSignerError(f"non-hex signer response: {raw[:64]}") from None
    if len(out) != 96:
        raise RemoteSignerError(f"signer returned {len(out)} bytes, want 96")
    return out
