"""ValidatorStore: every signature flows through here, gated by the
slashing-protection DB and the doppelganger state
(validator_store/src/lib.rs:575 sign_block, :671 sign_attestation).

The store holds SigningMethods keyed by pubkey; services ask it to sign
typed objects (block, attestation, randao, selection proof, sync
message) — never raw roots — so the watermarks are enforced at the only
place a signature can be born.
"""

from __future__ import annotations

from typing import Optional

from ..consensus import state_transition as st
from ..consensus import types as T
from ..consensus.domains import compute_signing_root, get_domain
from ..consensus.signature_sets import _EpochSSZ, _Bytes32SSZ
from ..consensus.spec import ChainSpec
from ..common import metrics
from .signing_method import SigningMethod
from .slashing_protection import SlashingProtectionDB, SlashingProtectionError

# validator_metrics crate role: per-process signing counters
SIGNED_BLOCKS = metrics.counter(
    "vc_signed_beacon_blocks_total", "Blocks signed by this VC"
)
SIGNED_ATTESTATIONS = metrics.counter(
    "vc_signed_attestations_total", "Attestations signed by this VC"
)
SIGNED_AGGREGATES = metrics.counter(
    "vc_signed_aggregates_total", "Aggregate-and-proofs signed by this VC"
)
SIGNED_SYNC_MESSAGES = metrics.counter(
    "vc_signed_sync_committee_messages_total",
    "Sync-committee messages signed by this VC",
)
SLASHING_VETOES = metrics.counter(
    "vc_slashing_protection_vetoes_total",
    "Signatures refused by the slashing-protection DB",
)


class DoppelgangerProtected(Exception):
    """Signing refused: the validator has not cleared doppelganger
    detection yet (doppelganger_service/src/lib.rs:1-16 role)."""


class ValidatorStore:
    def __init__(
        self,
        spec: ChainSpec,
        genesis_validators_root: bytes,
        slashing_db: SlashingProtectionDB = None,
    ):
        self.spec = spec
        self.genesis_validators_root = bytes(genesis_validators_root)
        self.slashing_db = slashing_db or SlashingProtectionDB()
        self._signers: dict[bytes, SigningMethod] = {}
        # pubkeys still under doppelganger observation (sign refused)
        self._doppelganger_hold: set[bytes] = set()

    # ------------------------------------------------------------ registry

    def add_validator(self, method: SigningMethod, doppelganger_hold: bool = False):
        pk = method.public_key_bytes()
        self._signers[pk] = method
        self.slashing_db.register_validator(pk)
        if doppelganger_hold:
            self._doppelganger_hold.add(pk)

    def remove_validator(self, pubkey: bytes) -> bool:
        """Forget a signer immediately (keymanager DELETE: the key must
        stop signing before the response returns)."""
        pk = bytes(pubkey)
        self._doppelganger_hold.discard(pk)
        return self._signers.pop(pk, None) is not None

    def clear_doppelganger(self, pubkey: bytes) -> None:
        self._doppelganger_hold.discard(bytes(pubkey))

    def pubkeys(self) -> list:
        return list(self._signers)

    def _signer(self, pubkey: bytes) -> SigningMethod:
        m = self._signers.get(bytes(pubkey))
        if m is None:
            raise KeyError("unknown validator")
        if bytes(pubkey) in self._doppelganger_hold:
            raise DoppelgangerProtected(bytes(pubkey).hex())
        return m

    # ------------------------------------------------------------ signing

    def sign_block(self, pubkey: bytes, block, fork) -> T.SignedBeaconBlock:
        """Slashing-gated block proposal signature (sign_block)."""
        epoch = st.compute_epoch_at_slot(self.spec, block.slot)
        domain = get_domain(
            self.spec,
            self.spec.domain_beacon_proposer,
            epoch,
            fork,
            self.genesis_validators_root,
        )
        root = compute_signing_root(block, domain)
        m = self._signer(pubkey)
        try:
            self.slashing_db.check_and_insert_block_proposal(
                bytes(pubkey), int(block.slot), root
            )
        except SlashingProtectionError:
            SLASHING_VETOES.inc()
            raise
        wrapper = (
            T.SignedBlindedBeaconBlock
            if hasattr(block.body, "execution_payload_header")
            else T.SignedBeaconBlock
        )
        signed = wrapper.make(message=block, signature=m.sign(root).to_bytes())
        SIGNED_BLOCKS.inc()
        return signed

    def sign_application(self, pubkey: bytes, signing_root: bytes):
        """Non-consensus application signature (builder registration,
        DOMAIN_APPLICATION_BUILDER): no slashing protection applies, and
        the doppelganger hold does not block it (the reference signs
        registrations during the doppelganger window too)."""
        m = self._signers.get(bytes(pubkey))
        if m is None:
            raise KeyError("unknown validator")
        return m.sign(signing_root)

    def sign_attestation(self, pubkey: bytes, data, fork) -> bytes:
        """Slashing-gated attestation signature (sign_attestation);
        returns the signature bytes for the service to wrap in bits."""
        domain = get_domain(
            self.spec,
            self.spec.domain_beacon_attester,
            data.target.epoch,
            fork,
            self.genesis_validators_root,
        )
        root = compute_signing_root(data, domain)
        m = self._signer(pubkey)
        try:
            self.slashing_db.check_and_insert_attestation(
                bytes(pubkey),
                int(data.source.epoch),
                int(data.target.epoch),
                root,
            )
        except SlashingProtectionError:
            SLASHING_VETOES.inc()
            raise
        sig = m.sign(root).to_bytes()
        SIGNED_ATTESTATIONS.inc()
        return sig

    def sign_randao(self, pubkey: bytes, epoch: int, fork) -> bytes:
        domain = get_domain(
            self.spec,
            self.spec.domain_randao,
            epoch,
            fork,
            self.genesis_validators_root,
        )
        return (
            self._signer(pubkey)
            .sign(compute_signing_root(_EpochSSZ(epoch), domain))
            .to_bytes()
        )

    def selection_proof(self, pubkey: bytes, slot: int, fork) -> bytes:
        """Aggregation selection proof (precomputed by the duties
        service, duties_service.rs:128-158)."""
        epoch = st.compute_epoch_at_slot(self.spec, slot)
        domain = get_domain(
            self.spec,
            self.spec.domain_selection_proof,
            epoch,
            fork,
            self.genesis_validators_root,
        )
        return (
            self._signer(pubkey)
            .sign(compute_signing_root(_EpochSSZ(slot), domain))
            .to_bytes()
        )

    def sign_aggregate_and_proof(self, pubkey: bytes, msg, fork) -> bytes:
        epoch = st.compute_epoch_at_slot(self.spec, msg.aggregate.data.slot)
        domain = get_domain(
            self.spec,
            self.spec.domain_aggregate_and_proof,
            epoch,
            fork,
            self.genesis_validators_root,
        )
        sig = self._signer(pubkey).sign(compute_signing_root(msg, domain)).to_bytes()
        SIGNED_AGGREGATES.inc()
        return sig

    def sync_selection_proof(
        self, pubkey: bytes, slot: int, subcommittee_index: int, fork
    ) -> bytes:
        """Sync-aggregator selection proof over
        SyncAggregatorSelectionData."""
        epoch = st.compute_epoch_at_slot(self.spec, slot)
        domain = get_domain(
            self.spec,
            self.spec.domain_sync_committee_selection_proof,
            epoch,
            fork,
            self.genesis_validators_root,
        )
        data = T.SyncAggregatorSelectionData.make(
            slot=slot, subcommittee_index=subcommittee_index
        )
        return (
            self._signer(pubkey)
            .sign(compute_signing_root(data, domain))
            .to_bytes()
        )

    def sign_contribution_and_proof(self, pubkey: bytes, msg, fork) -> bytes:
        epoch = st.compute_epoch_at_slot(self.spec, msg.contribution.slot)
        domain = get_domain(
            self.spec,
            self.spec.domain_contribution_and_proof,
            epoch,
            fork,
            self.genesis_validators_root,
        )
        return (
            self._signer(pubkey)
            .sign(compute_signing_root(msg, domain))
            .to_bytes()
        )

    def sign_sync_committee_message(
        self, pubkey: bytes, slot: int, beacon_block_root: bytes, fork
    ) -> bytes:
        epoch = st.compute_epoch_at_slot(self.spec, slot)
        domain = get_domain(
            self.spec,
            self.spec.domain_sync_committee,
            epoch,
            fork,
            self.genesis_validators_root,
        )
        sig = (
            self._signer(pubkey)
            .sign(
                compute_signing_root(_Bytes32SSZ(beacon_block_root), domain)
            )
            .to_bytes()
        )
        SIGNED_SYNC_MESSAGES.inc()
        return sig
