"""Per-validator graffiti (common/graffiti_file analog).

File format (graffiti_file/src/lib.rs):

    default: lighthouse-tpu
    0x<pubkey>: my validator one
    0x<pubkey>: my validator two

`graffiti_for` resolves pubkey → 32-byte graffiti with the default as
fallback; the file is re-read on `load` so operators can edit live.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

GRAFFITI_BYTES = 32


class GraffitiFileError(Exception):
    pass


def pad_graffiti(text: str) -> bytes:
    raw = text.encode()[:GRAFFITI_BYTES]
    return raw + b"\x00" * (GRAFFITI_BYTES - len(raw))


class GraffitiFile:
    def __init__(self, path):
        self.path = Path(path)
        self.default: Optional[bytes] = None
        self.graffitis: dict[bytes, bytes] = {}
        self.load()

    def load(self) -> None:
        self.graffitis = {}
        self.default = None
        if not self.path.exists():
            return
        for lineno, line in enumerate(self.path.read_text().splitlines(), 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            key, sep, value = line.partition(":")
            if not sep:
                raise GraffitiFileError(f"line {lineno}: no ':' separator")
            key = key.strip()
            value = value.strip()
            if key == "default":
                self.default = pad_graffiti(value)
            else:
                if not key.startswith("0x") or len(key) != 98:
                    raise GraffitiFileError(
                        f"line {lineno}: bad pubkey {key!r}"
                    )
                self.graffitis[bytes.fromhex(key[2:])] = pad_graffiti(value)

    def graffiti_for(self, pubkey: bytes) -> Optional[bytes]:
        return self.graffitis.get(bytes(pubkey), self.default)
