"""lighthouse_tpu — a TPU-native Ethereum consensus framework.

A ground-up rebuild of the capabilities of ParaState/lighthouse (Rust) with a
JAX/XLA/Pallas execution backend for the cryptographic hot paths (batched
BLS12-381 signature verification, KZG blob-commitment checks) and host-side
C++/Python for the runtime around them (scheduler, store, networking, APIs).

Layer map (mirrors reference SURVEY.md §1):
  crypto/    — L0: BLS12-381 + KZG primitives, three backends (cpu/tpu/fake)
               like the reference's blst/fake_crypto seam
               (reference: crypto/bls/src/lib.rs:87-142)
  consensus/ — L1-L2: types, state transition, fork choice, proto-array
  node/      — L3-L6: BeaconChain core, beacon_processor scheduler, store
  validator/ — L-VC: validator-client components (slashing protection, ...)
  ops/       — JAX/Pallas kernels (big-int limb arithmetic, curve ops, pairing)
  parallel/  — device-mesh sharding of crypto batches over ICI (shard_map)
  common/    — cross-cutting commons (metrics registry, slot clock)
  tools/     — offline derivation utilities (G2 isogeny constants)
"""

__version__ = "0.1.0"


def enable_compilation_cache(path: str = None) -> None:
    """Point JAX's persistent compilation cache at a repo-local dir.

    The fused verification kernel is a large XLA program; caching makes
    every process after the first (tests, bench, driver compile-checks)
    load it instead of recompiling. Call before the first jit execution.
    """
    import os

    import jax

    if path is None:
        path = os.path.join(os.path.dirname(os.path.dirname(__file__)), ".jax_cache")
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    try:
        jax.config.update("jax_persistent_cache_enable_xla_caches", "all")
    except Exception:  # flag renamed across jax versions; cache still works
        pass
