"""Scalar reference epoch transition (ISSUE 6 differential oracle).

A spec-literal, per-validator-Python-loop implementation of
`process_epoch`, retained so the columnar/fused path in
state_transition.py + ops/epoch.py can be differentially tested against
an implementation with no numpy in the per-validator math
(tests/test_epoch_columnar.py asserts bit-identical post-states and
hash_tree_root on randomized states).

Deliberately NOT performance-relevant: it exists to be obviously
correct. Shared stages with no per-validator loop (justification,
resets, participation rotation, sync-committee updates, electra
pending-deposit/consolidation queues — all already scalar) are reused
from state_transition/electra so the diff isolates exactly the stages
the columnar path rewrote."""

from __future__ import annotations

from .spec import FAR_FUTURE_EPOCH, GENESIS_EPOCH, ChainSpec
from .ssz import seq_get_mut
from . import state_transition as st
from . import electra as el


def _eligible_indices(spec: ChainSpec, state) -> list:
    prev = st.get_previous_epoch(spec, state)
    out = []
    for i, v in enumerate(state.validators):
        if st.is_active_validator(v, prev) or (
            v.slashed and prev + 1 < v.withdrawable_epoch
        ):
            out.append(i)
    return out


def process_inactivity_updates(spec: ChainSpec, state) -> None:
    if st.get_current_epoch(spec, state) == GENESIS_EPOCH:
        return
    prev = st.get_previous_epoch(spec, state)
    leak = st.is_in_inactivity_leak(spec, state)
    # per-element writeback through __setitem__ (the whitelisted CoW
    # form, graft-lint R1): a whole-list rebuild would replace the
    # ChunkedSeq spine and drop every clean chunk's shared root cache
    for i in _eligible_indices(spec, state):
        v = state.validators[i]
        orig = state.inactivity_scores[i]
        score = orig
        participated_target = (
            st.is_active_validator(v, prev)
            and not v.slashed
            and (
                state.previous_epoch_participation[i]
                & (1 << st.TIMELY_TARGET_FLAG_INDEX)
            )
        )
        if participated_target:
            score -= min(1, score)
        else:
            score += st.INACTIVITY_SCORE_BIAS
        if not leak:
            score -= min(st.INACTIVITY_SCORE_RECOVERY_RATE, score)
        if score != orig:
            state.inactivity_scores[i] = score


def process_rewards_and_penalties(
    spec: ChainSpec, state, flag_balances_prev, total_active: int
) -> None:
    if st.get_current_epoch(spec, state) == GENESIS_EPOCH:
        return
    prev = st.get_previous_epoch(spec, state)
    inc = spec.effective_balance_increment
    base_reward_per_inc = (
        inc * spec.base_reward_factor // st._integer_sqrt(total_active)
    )
    total_active_increments = total_active // inc
    leak = st.is_in_inactivity_leak(spec, state)
    deltas = [0] * len(state.validators)
    for i in _eligible_indices(spec, state):
        v = state.validators[i]
        base_reward = (v.effective_balance // inc) * base_reward_per_inc
        unslashed_prev = st.is_active_validator(v, prev) and not v.slashed
        part = state.previous_epoch_participation[i]
        for flag_index, weight in enumerate(st.PARTICIPATION_FLAG_WEIGHTS):
            has_flag = unslashed_prev and (part & (1 << flag_index))
            if has_flag:
                if not leak:
                    unslashed_increments = flag_balances_prev[flag_index] // inc
                    deltas[i] += (
                        base_reward * weight * unslashed_increments
                        // (total_active_increments * st.WEIGHT_DENOMINATOR)
                    )
            elif flag_index != st.TIMELY_HEAD_FLAG_INDEX:
                deltas[i] -= base_reward * weight // st.WEIGHT_DENOMINATOR
        has_target = unslashed_prev and (
            part & (1 << st.TIMELY_TARGET_FLAG_INDEX)
        )
        if not has_target:
            deltas[i] -= (
                v.effective_balance
                * state.inactivity_scores[i]
                // (st.INACTIVITY_SCORE_BIAS * st.INACTIVITY_PENALTY_QUOTIENT)
            )
    for i, d in enumerate(deltas):
        if d:
            state.balances[i] = max(0, state.balances[i] + d)


def _initiate_validator_exit_scalar(spec: ChainSpec, state, index: int) -> None:
    """Phase0 initiate_validator_exit with the literal O(n) rescan."""
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    exit_epochs = [
        w.exit_epoch
        for w in state.validators
        if w.exit_epoch != FAR_FUTURE_EPOCH
    ]
    activation_exit = (
        st.get_current_epoch(spec, state) + 1 + spec.max_seed_lookahead
    )
    exit_queue_epoch = max(exit_epochs + [activation_exit])
    churn = len(
        [w for w in state.validators if w.exit_epoch == exit_queue_epoch]
    )
    if churn >= st.get_validator_churn_limit(spec, state):
        exit_queue_epoch += 1
    v = seq_get_mut(state.validators, index)
    v.exit_epoch = exit_queue_epoch
    v.withdrawable_epoch = (
        exit_queue_epoch + spec.min_validator_withdrawability_delay
    )


def process_registry_updates(spec: ChainSpec, state) -> None:
    cur = st.get_current_epoch(spec, state)
    for i, v in enumerate(state.validators):
        if (
            v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
            and v.effective_balance == spec.max_effective_balance
        ):
            seq_get_mut(state.validators, i).activation_eligibility_epoch = (
                cur + 1
            )
        if (
            st.is_active_validator(v, cur)
            and v.effective_balance <= spec.ejection_balance
        ):
            _initiate_validator_exit_scalar(spec, state, i)
    queue = sorted(
        (
            i
            for i, v in enumerate(state.validators)
            if v.activation_eligibility_epoch
            <= state.finalized_checkpoint.epoch
            and v.activation_epoch == FAR_FUTURE_EPOCH
        ),
        key=lambda i: (
            state.validators[i].activation_eligibility_epoch,
            i,
        ),
    )
    for i in queue[: st.get_validator_churn_limit(spec, state)]:
        seq_get_mut(state.validators, i).activation_epoch = (
            cur + 1 + spec.max_seed_lookahead
        )


def process_registry_updates_electra(spec: ChainSpec, state) -> None:
    cur = st.get_current_epoch(spec, state)
    for i, v in enumerate(state.validators):
        if (
            v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
            and v.effective_balance >= spec.min_activation_balance
        ):
            seq_get_mut(state.validators, i).activation_eligibility_epoch = (
                cur + 1
            )
        if (
            st.is_active_validator(v, cur)
            and v.effective_balance <= spec.ejection_balance
        ):
            el.initiate_validator_exit(spec, state, i)
        if (
            v.activation_epoch == FAR_FUTURE_EPOCH
            and v.activation_eligibility_epoch
            <= state.finalized_checkpoint.epoch
        ):
            seq_get_mut(state.validators, i).activation_epoch = (
                cur + 1 + spec.max_seed_lookahead
            )


def process_slashings(spec: ChainSpec, state, total_active: int) -> None:
    epoch = st.get_current_epoch(spec, state)
    total_slashings = sum(state.slashings)
    adjusted = min(
        total_slashings * st.PROPORTIONAL_SLASHING_MULTIPLIER, total_active
    )
    for i, v in enumerate(state.validators):
        if (
            v.slashed
            and epoch + spec.preset.epochs_per_slashings_vector // 2
            == v.withdrawable_epoch
        ):
            increment = spec.effective_balance_increment
            penalty_numerator = v.effective_balance // increment * adjusted
            penalty = penalty_numerator // total_active * increment
            st.decrease_balance(state, i, penalty)


def process_effective_balance_updates(
    spec: ChainSpec, state, electra: bool
) -> None:
    hysteresis_increment = spec.effective_balance_increment // 4
    downward = hysteresis_increment
    upward = hysteresis_increment * 2
    for i, v in enumerate(state.validators):
        balance = state.balances[i]
        cap = (
            el.get_max_effective_balance(spec, v)
            if electra
            else spec.max_effective_balance
        )
        if (
            balance + downward < v.effective_balance
            or v.effective_balance + upward < balance
        ):
            seq_get_mut(state.validators, i).effective_balance = min(
                balance - balance % spec.effective_balance_increment, cap
            )


def process_epoch_scalar(spec: ChainSpec, state) -> None:
    """The full boundary, spec order, all-scalar hot stages."""
    cur = st.get_current_epoch(spec, state)
    prev = st.get_previous_epoch(spec, state)
    total_active = 0
    for v in state.validators:
        if st.is_active_validator(v, cur):
            total_active += v.effective_balance
    total_active = max(total_active, spec.effective_balance_increment)
    flag_balances_prev = [0, 0, 0]
    target_balance_cur = 0
    for i, v in enumerate(state.validators):
        if v.slashed:
            continue
        if st.is_active_validator(v, prev):
            part = state.previous_epoch_participation[i]
            for f in range(3):
                if part & (1 << f):
                    flag_balances_prev[f] += v.effective_balance
        if st.is_active_validator(v, cur):
            if state.current_epoch_participation[i] & (
                1 << st.TIMELY_TARGET_FLAG_INDEX
            ):
                target_balance_cur += v.effective_balance

    st.process_justification_and_finalization(
        spec,
        state,
        total_active,
        flag_balances_prev[st.TIMELY_TARGET_FLAG_INDEX],
        target_balance_cur,
    )
    process_inactivity_updates(spec, state)
    process_rewards_and_penalties(spec, state, flag_balances_prev, total_active)
    electra_active = spec.electra_enabled(cur)
    if electra_active:
        process_registry_updates_electra(spec, state)
    else:
        process_registry_updates(spec, state)
    process_slashings(spec, state, total_active)
    st.process_eth1_data_reset(spec, state)
    if electra_active:
        el.process_pending_deposits(spec, state)
        el.process_pending_consolidations(spec, state)
    process_effective_balance_updates(spec, state, electra_active)
    st.process_slashings_reset(spec, state)
    st.process_randao_mixes_reset(spec, state)
    st.process_historical_roots_update(spec, state)
    st.process_participation_flag_updates(state)
    st.process_sync_committee_updates(spec, state)
