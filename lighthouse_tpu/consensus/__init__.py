"""Consensus layer: SSZ types, state transition, fork choice.

The host-side control plane of the framework (SURVEY.md §2.2) — the
analog of the reference's consensus/{types,state_processing,fork_choice,
proto_array} crates. Control-flow-heavy and hash-heavy, so it stays on
CPU; everything signature-shaped funnels into crypto.bls SignatureSets
that the TPU backend batch-verifies (signature_sets.py ==
consensus/state_processing/src/per_block_processing/signature_sets.rs).
"""
