"""Per-fork SSZ-EXACT container variants (superstruct role,
consensus/types/src/beacon_block.rs + beacon_state.rs).

The framework's internal representation stays the union family in
`types.py` (one Deneb-shaped set + an electra sub-container — chosen so
the state tree keeps 32 leaves and device-facing code handles ONE
layout). What the union family cannot do is speak to the outside world:
decode a real phase0..electra SSZ object, re-produce its
hash_tree_root, or serve spec-exact SSZ over REST (VERDICT r3 missing
item #2). This module provides that boundary layer: for each fork a
container set whose field ORDER, SHAPES and LIMITS are exactly the
spec's, plus converters from the union representation.

Fork coverage: phase0, altair, bellatrix, capella, deneb, electra.
External pins: the mainnet/sepolia genesis.ssz fixtures decode through
the phase0 BeaconState here and reproduce the publicly-known
genesis_validators_root values (tests/test_forked_types.py).
"""

from __future__ import annotations

from .spec import MAINNET_PRESET as _P
from .ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Bytes4,
    Bytes20,
    Bytes32,
    Bytes48,
    Bytes96,
    Container,
    List,
    Vector,
    boolean,
    uint8,
    uint64,
    uint256,
)
from . import types as U

FORKS = ("phase0", "altair", "bellatrix", "capella", "deneb", "electra")
_FORK_IDX = {f: i for i, f in enumerate(FORKS)}


def _at_least(fork: str, floor: str) -> bool:
    return _FORK_IDX[fork] >= _FORK_IDX[floor]


# ------------------------------------------------------- invariant parts
# These containers are identical in every fork; reuse the union family's
# (their SSZ is already spec-exact).
Fork = U.Fork
Checkpoint = U.Checkpoint
Validator = U.Validator
Eth1Data = U.Eth1Data
AttestationData = U.AttestationData
BeaconBlockHeader = U.BeaconBlockHeader
SignedBeaconBlockHeader = U.SignedBeaconBlockHeader
ProposerSlashing = U.ProposerSlashing
Deposit = U.Deposit
SignedVoluntaryExit = U.SignedVoluntaryExit
SignedBLSToExecutionChange = U.SignedBLSToExecutionChange
SyncAggregate = U.SyncAggregate
SyncCommittee = U.SyncCommittee
Withdrawal = U.Withdrawal
HistoricalSummary = U.HistoricalSummary
ExecutionRequests = U.ExecutionRequests
Transaction = U.Transaction

# spec electra limits (EIP-7549 widens attestations to span committees)
MAX_ATTESTATIONS_ELECTRA = 8
MAX_ATTESTER_SLASHINGS_ELECTRA = 1
_AGG_BITS_ELECTRA = _P.max_validators_per_committee * _P.max_committees_per_slot

# --------------------------------------------------- per-fork attestations

Attestation = Container(
    "AttestationPhase0",
    [
        ("aggregation_bits", Bitlist(_P.max_validators_per_committee)),
        ("data", AttestationData),
        ("signature", Bytes96),
    ],
)

IndexedAttestation = Container(
    "IndexedAttestationPhase0",
    [
        ("attesting_indices", List(uint64, _P.max_validators_per_committee)),
        ("data", AttestationData),
        ("signature", Bytes96),
    ],
)

AttesterSlashing = Container(
    "AttesterSlashingPhase0",
    [
        ("attestation_1", IndexedAttestation),
        ("attestation_2", IndexedAttestation),
    ],
)

AttestationElectra = Container(
    "AttestationElectra",
    [
        ("aggregation_bits", Bitlist(_AGG_BITS_ELECTRA)),
        ("data", AttestationData),
        ("signature", Bytes96),
        ("committee_bits", Bitvector(_P.max_committees_per_slot)),
    ],
)

IndexedAttestationElectra = Container(
    "IndexedAttestationElectra",
    [
        ("attesting_indices", List(uint64, _AGG_BITS_ELECTRA)),
        ("data", AttestationData),
        ("signature", Bytes96),
    ],
)

AttesterSlashingElectra = Container(
    "AttesterSlashingElectra",
    [
        ("attestation_1", IndexedAttestationElectra),
        ("attestation_2", IndexedAttestationElectra),
    ],
)

PendingAttestation = Container(
    "PendingAttestation",
    [
        ("aggregation_bits", Bitlist(_P.max_validators_per_committee)),
        ("data", AttestationData),
        ("inclusion_delay", uint64),
        ("proposer_index", uint64),
    ],
)


def attestation_t(fork: str):
    return AttestationElectra if _at_least(fork, "electra") else Attestation


def attester_slashing_t(fork: str):
    return (
        AttesterSlashingElectra
        if _at_least(fork, "electra")
        else AttesterSlashing
    )


# ------------------------------------------------- per-fork exec payloads

_PAYLOAD_PREFIX = [
    ("parent_hash", Bytes32),
    ("fee_recipient", Bytes20),
    ("state_root", Bytes32),
    ("receipts_root", Bytes32),
    ("logs_bloom", ByteVector(_P.bytes_per_logs_bloom)),
    ("prev_randao", Bytes32),
    ("block_number", uint64),
    ("gas_limit", uint64),
    ("gas_used", uint64),
    ("timestamp", uint64),
    ("extra_data", ByteList(_P.max_extra_data_bytes)),
    ("base_fee_per_gas", uint256),
    ("block_hash", Bytes32),
]


def _payload_fields(fork: str, header: bool) -> list:
    fields = list(_PAYLOAD_PREFIX)
    if header:
        fields.append(("transactions_root", Bytes32))
    else:
        fields.append(
            ("transactions", List(Transaction, _P.max_transactions_per_payload))
        )
    if _at_least(fork, "capella"):
        if header:
            fields.append(("withdrawals_root", Bytes32))
        else:
            fields.append(
                ("withdrawals", List(Withdrawal, _P.max_withdrawals_per_payload))
            )
    if _at_least(fork, "deneb"):
        fields.append(("blob_gas_used", uint64))
        fields.append(("excess_blob_gas", uint64))
    return fields


_PAYLOADS = {
    f: Container(f"ExecutionPayload_{f}", _payload_fields(f, header=False))
    for f in ("bellatrix", "capella", "deneb", "electra")
}
_HEADERS = {
    f: Container(f"ExecutionPayloadHeader_{f}", _payload_fields(f, header=True))
    for f in ("bellatrix", "capella", "deneb", "electra")
}


def execution_payload_t(fork: str):
    return _PAYLOADS[fork]


def execution_payload_header_t(fork: str):
    return _HEADERS[fork]


# ------------------------------------------------------ per-fork bodies


def _body_fields(fork: str) -> list:
    att_t = attestation_t(fork)
    slash_t = attester_slashing_t(fork)
    max_atts = (
        MAX_ATTESTATIONS_ELECTRA
        if _at_least(fork, "electra")
        else _P.max_attestations
    )
    max_slash = (
        MAX_ATTESTER_SLASHINGS_ELECTRA
        if _at_least(fork, "electra")
        else _P.max_attester_slashings
    )
    fields = [
        ("randao_reveal", Bytes96),
        ("eth1_data", Eth1Data),
        ("graffiti", Bytes32),
        ("proposer_slashings", List(ProposerSlashing, _P.max_proposer_slashings)),
        ("attester_slashings", List(slash_t, max_slash)),
        ("attestations", List(att_t, max_atts)),
        ("deposits", List(Deposit, _P.max_deposits)),
        ("voluntary_exits", List(SignedVoluntaryExit, _P.max_voluntary_exits)),
    ]
    if _at_least(fork, "altair"):
        fields.append(("sync_aggregate", SyncAggregate))
    if _at_least(fork, "bellatrix"):
        fields.append(("execution_payload", execution_payload_t(fork)))
    if _at_least(fork, "capella"):
        fields.append(
            (
                "bls_to_execution_changes",
                List(SignedBLSToExecutionChange, _P.max_bls_to_execution_changes),
            )
        )
    if _at_least(fork, "deneb"):
        fields.append(
            (
                "blob_kzg_commitments",
                List(Bytes48, _P.max_blob_commitments_per_block),
            )
        )
    if _at_least(fork, "electra"):
        fields.append(("execution_requests", ExecutionRequests))
    return fields


_BODIES = {f: Container(f"BeaconBlockBody_{f}", _body_fields(f)) for f in FORKS}
_BLOCKS = {
    f: Container(
        f"BeaconBlock_{f}",
        [
            ("slot", uint64),
            ("proposer_index", uint64),
            ("parent_root", Bytes32),
            ("state_root", Bytes32),
            ("body", _BODIES[f]),
        ],
    )
    for f in FORKS
}
_SIGNED_BLOCKS = {
    f: Container(
        f"SignedBeaconBlock_{f}",
        [("message", _BLOCKS[f]), ("signature", Bytes96)],
    )
    for f in FORKS
}


def beacon_block_body_t(fork: str):
    return _BODIES[fork]


def beacon_block_t(fork: str):
    return _BLOCKS[fork]


def signed_beacon_block_t(fork: str):
    return _SIGNED_BLOCKS[fork]


# ------------------------------------------------------ per-fork states


def _state_fields(fork: str) -> list:
    fields = [
        ("genesis_time", uint64),
        ("genesis_validators_root", Bytes32),
        ("slot", uint64),
        ("fork", Fork),
        ("latest_block_header", BeaconBlockHeader),
        ("block_roots", Vector(Bytes32, _P.slots_per_historical_root)),
        ("state_roots", Vector(Bytes32, _P.slots_per_historical_root)),
        ("historical_roots", List(Bytes32, _P.historical_roots_limit)),
        ("eth1_data", Eth1Data),
        (
            "eth1_data_votes",
            List(
                Eth1Data,
                _P.epochs_per_eth1_voting_period * _P.slots_per_epoch,
            ),
        ),
        ("eth1_deposit_index", uint64),
        ("validators", List(Validator, _P.validator_registry_limit)),
        ("balances", List(uint64, _P.validator_registry_limit)),
        ("randao_mixes", Vector(Bytes32, _P.epochs_per_historical_vector)),
        ("slashings", Vector(uint64, _P.epochs_per_slashings_vector)),
    ]
    if fork == "phase0":
        pend = List(
            PendingAttestation, _P.max_attestations * _P.slots_per_epoch
        )
        fields += [
            ("previous_epoch_attestations", pend),
            ("current_epoch_attestations", pend),
        ]
    else:
        fields += [
            (
                "previous_epoch_participation",
                List(uint8, _P.validator_registry_limit),
            ),
            (
                "current_epoch_participation",
                List(uint8, _P.validator_registry_limit),
            ),
        ]
    fields += [
        ("justification_bits", Bitvector(4)),
        ("previous_justified_checkpoint", Checkpoint),
        ("current_justified_checkpoint", Checkpoint),
        ("finalized_checkpoint", Checkpoint),
    ]
    if _at_least(fork, "altair"):
        fields += [
            ("inactivity_scores", List(uint64, _P.validator_registry_limit)),
            ("current_sync_committee", SyncCommittee),
            ("next_sync_committee", SyncCommittee),
        ]
    if _at_least(fork, "bellatrix"):
        fields.append(
            ("latest_execution_payload_header", execution_payload_header_t(fork))
        )
    if _at_least(fork, "capella"):
        fields += [
            ("next_withdrawal_index", uint64),
            ("next_withdrawal_validator_index", uint64),
            (
                "historical_summaries",
                List(HistoricalSummary, _P.historical_roots_limit),
            ),
        ]
    if _at_least(fork, "electra"):
        # the spec appends these FLAT (the union family nests them in
        # one sub-container; this is exactly the deviation this module
        # exists to bridge)
        fields += [
            ("deposit_requests_start_index", uint64),
            ("deposit_balance_to_consume", uint64),
            ("exit_balance_to_consume", uint64),
            ("earliest_exit_epoch", uint64),
            ("consolidation_balance_to_consume", uint64),
            ("earliest_consolidation_epoch", uint64),
            ("pending_deposits", List(U.PendingDeposit, 2**27)),
            (
                "pending_partial_withdrawals",
                List(U.PendingPartialWithdrawal, 2**27),
            ),
            ("pending_consolidations", List(U.PendingConsolidation, 2**18)),
        ]
    return fields


_STATES = {f: Container(f"BeaconState_{f}", _state_fields(f)) for f in FORKS}


def beacon_state_t(fork: str):
    return _STATES[fork]


# ----------------------------------------------------------- converters


def _spec_attestation(att, fork: str):
    t = attestation_t(fork)
    if _at_least(fork, "electra"):
        return t.make(
            aggregation_bits=list(att.aggregation_bits),
            data=att.data,
            signature=bytes(att.signature),
            committee_bits=list(att.committee_bits),
        )
    return t.make(
        aggregation_bits=list(att.aggregation_bits),
        data=att.data,
        signature=bytes(att.signature),
    )


def _spec_payload(p, fork: str):
    t = execution_payload_t(fork)
    vals = {}
    for name, _ in t.fields:
        vals[name] = getattr(p, name)
    return t.make(**vals)


def spec_block_from_union(signed_block, fork: str):
    """Union-family SignedBeaconBlock -> the fork's spec-exact value
    (REST SSZ responses; drops the pre-electra committee_bits carry)."""
    msg = signed_block.message
    body = msg.body
    body_t = beacon_block_body_t(fork)
    vals = {}
    for name, _ in body_t.fields:
        if name == "attestations":
            vals[name] = [
                _spec_attestation(a, fork) for a in body.attestations
            ]
        elif name == "attester_slashings":
            st = attester_slashing_t(fork)
            it = (
                IndexedAttestationElectra
                if _at_least(fork, "electra")
                else IndexedAttestation
            )
            vals[name] = [
                st.make(
                    attestation_1=it.make(
                        attesting_indices=list(s.attestation_1.attesting_indices),
                        data=s.attestation_1.data,
                        signature=bytes(s.attestation_1.signature),
                    ),
                    attestation_2=it.make(
                        attesting_indices=list(s.attestation_2.attesting_indices),
                        data=s.attestation_2.data,
                        signature=bytes(s.attestation_2.signature),
                    ),
                )
                for s in body.attester_slashings
            ]
        elif name == "execution_payload":
            vals[name] = _spec_payload(body.execution_payload, fork)
        else:
            vals[name] = getattr(body, name)
    block_t = beacon_block_t(fork)
    return signed_beacon_block_t(fork).make(
        message=block_t.make(
            slot=msg.slot,
            proposer_index=msg.proposer_index,
            parent_root=bytes(msg.parent_root),
            state_root=bytes(msg.state_root),
            body=body_t.make(**vals),
        ),
        signature=bytes(signed_block.signature),
    )


class UnsupportedBlockContent(ValueError):
    """Spec-valid content the union family cannot represent (today:
    EIP-7549 multi-committee aggregates — splitting one needs the
    slot's committee sizes, i.e. state, not available at decode time).
    Callers must treat this as OUR limitation, never penalize the
    serving peer for it."""


def _union_attestation_from_spec(att, fork: str):
    """Spec attestation -> union shape. Pre-electra: committee_bits
    stays all-zero (the committee rides data.index, types.py comment).
    Electra: the union family keeps ONE committee per attestation
    (aggregation_bits committee-scoped), so multi-committee aggregates
    cannot be represented and are rejected."""
    committee_bits = [0] * _P.max_committees_per_slot
    agg_bits = list(att.aggregation_bits)
    if _at_least(fork, "electra"):
        set_bits = [
            i for i, b in enumerate(att.committee_bits) if b
        ]
        if len(set_bits) > 1:
            raise UnsupportedBlockContent(
                "multi-committee electra attestation cannot ingest into "
                "the single-committee union shape"
            )
        for i in set_bits:
            committee_bits[i] = 1
    return U.Attestation.make(
        aggregation_bits=agg_bits,
        data=att.data,
        signature=bytes(att.signature),
        committee_bits=committee_bits,
    )


def _union_payload_from_spec(p, fork: str):
    """Spec payload -> the union's deneb-shaped payload; fields the
    fork predates default to zero-values."""
    vals = {
        name: getattr(p, name)
        for name, _ in execution_payload_t(fork).fields
    }
    out = U.ExecutionPayload.default()
    for name, v in vals.items():
        setattr(out, name, v)
    return out


def union_block_from_spec(spec_signed, fork: str):
    """Spec-exact SignedBeaconBlock -> union family (the INGEST
    direction, beacon_block.rs superstruct decode role): externally
    produced phase0..electra blocks become processable by
    `process_block`/fork choice. Fields the fork predates default."""
    msg = spec_signed.message
    sbody = msg.body
    body = U.BeaconBlockBody.default()
    if not _at_least(fork, "altair"):
        # a defaulted (absent) sync aggregate must still carry a VALID
        # G2 encoding: the compressed point at infinity, as the
        # internal block producer emits pre-altair
        body.sync_aggregate.sync_committee_signature = (
            b"\xc0" + b"\x00" * 95
        )
    for name, _ in beacon_block_body_t(fork).fields:
        if name == "attestations":
            body.attestations = [
                _union_attestation_from_spec(a, fork)
                for a in sbody.attestations
            ]
        elif name == "attester_slashings":
            body.attester_slashings = [
                U.AttesterSlashing.make(
                    attestation_1=U.IndexedAttestation.make(
                        attesting_indices=list(
                            s.attestation_1.attesting_indices
                        ),
                        data=s.attestation_1.data,
                        signature=bytes(s.attestation_1.signature),
                    ),
                    attestation_2=U.IndexedAttestation.make(
                        attesting_indices=list(
                            s.attestation_2.attesting_indices
                        ),
                        data=s.attestation_2.data,
                        signature=bytes(s.attestation_2.signature),
                    ),
                )
                for s in sbody.attester_slashings
            ]
        elif name == "execution_payload":
            body.execution_payload = _union_payload_from_spec(
                sbody.execution_payload, fork
            )
        else:
            setattr(body, name, getattr(sbody, name))
    return U.SignedBeaconBlock.make(
        message=U.BeaconBlock.make(
            slot=msg.slot,
            proposer_index=msg.proposer_index,
            parent_root=bytes(msg.parent_root),
            state_root=bytes(msg.state_root),
            body=body,
        ),
        signature=bytes(spec_signed.signature),
    )


def union_state_from_spec(spec_state, fork: str):
    """Spec-exact BeaconState -> union family (altair+ only: phase0's
    pending-attestation lists cannot become participation flags without
    an epoch replay — the reference performs that as the
    upgrade_to_altair fork transition, not a decode)."""
    if fork == "phase0":
        raise ValueError(
            "phase0 state ingest needs the altair upgrade replay; "
            "decode with beacon_state_t('phase0') instead"
        )
    out = U.BeaconState.default()
    electra_flat = {
        name for name, _ in U.ElectraStateExtras.fields
    }
    for name, _ in beacon_state_t(fork).fields:
        if name == "latest_execution_payload_header":
            h = spec_state.latest_execution_payload_header
            uh = U.ExecutionPayloadHeader.default()
            for n, _t in execution_payload_header_t(fork).fields:
                setattr(uh, n, getattr(h, n))
            out.latest_execution_payload_header = uh
        elif name in electra_flat:
            setattr(out.electra, name, getattr(spec_state, name))
        else:
            setattr(out, name, getattr(spec_state, name))
    return out


def slot_of_signed_block_ssz(raw: bytes) -> int:
    """Peek the slot of a serialized SignedBeaconBlock without a full
    decode: fixed part is [message offset u32][signature 96B]; the
    message begins with its u64 slot (the reference's
    from_ssz_bytes fork-dispatch trick, beacon_block.rs)."""
    if len(raw) < 108:
        raise ValueError("SignedBeaconBlock SSZ shorter than fixed part")
    off = int.from_bytes(raw[:4], "little")
    if off + 8 > len(raw):
        raise ValueError("bad message offset")
    return int.from_bytes(raw[off : off + 8], "little")


def decode_signed_block(spec, raw: bytes):
    """Fork-dispatched SignedBeaconBlock decode: peek the slot, pick
    the slot's fork per the spec schedule, decode the spec-exact
    container, convert to the union family. THE entry point for
    externally-encoded blocks (REST POST bodies, RPC BlocksByRange)."""
    slot = slot_of_signed_block_ssz(raw)
    fork = spec.fork_name_at_epoch(slot // spec.preset.slots_per_epoch)
    spec_signed = signed_beacon_block_t(fork).deserialize(raw)
    return union_block_from_spec(spec_signed, fork)


def spec_state_from_union(state, fork: str):
    """Union-family BeaconState -> the fork's spec-exact value
    (flattens the electra sub-container; narrows the payload header)."""
    t = beacon_state_t(fork)
    vals = {}
    for name, _ in t.fields:
        if name == "latest_execution_payload_header":
            h = state.latest_execution_payload_header
            ht = execution_payload_header_t(fork)
            vals[name] = ht.make(
                **{n: getattr(h, n) for n, _ in ht.fields}
            )
        elif name in (
            "deposit_requests_start_index",
            "deposit_balance_to_consume",
            "exit_balance_to_consume",
            "earliest_exit_epoch",
            "consolidation_balance_to_consume",
            "earliest_consolidation_epoch",
            "pending_deposits",
            "pending_partial_withdrawals",
            "pending_consolidations",
        ):
            vals[name] = getattr(state.electra, name)
        else:
            vals[name] = getattr(state, name)
    return t.make(**vals)
