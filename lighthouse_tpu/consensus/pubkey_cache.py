"""Decompressed validator pubkey cache.

The verify hot path must never pay point decompression per message —
the reference keeps every validator's pubkey decompressed in memory and
persists the cache (beacon_node/beacon_chain/src/validator_pubkey_cache.rs:1-24,138).
Same role here: bytes -> PublicKey (affine point, subgroup-checked once
at insert), indexed by validator index, append-only.
"""

from __future__ import annotations

from typing import Optional

from ..crypto.bls.keys import PublicKey


class ValidatorPubkeyCache:
    def __init__(self):
        self._keys: list[PublicKey] = []
        self._by_bytes: dict[bytes, int] = {}

    def __len__(self) -> int:
        return len(self._keys)

    def import_new_pubkeys(self, pubkey_bytes_list) -> None:
        """Append validators in registry order (decompression +
        subgroup check happen here, once per validator ever)."""
        for pb in pubkey_bytes_list:
            pb = bytes(pb)
            # Decompress/validate BEFORE recording the index mapping, so
            # a rejected key can't leave a stale bytes->index entry that
            # would later resolve to a different validator.
            key = PublicKey.from_bytes(pb)
            self._by_bytes[pb] = len(self._keys)
            self._keys.append(key)

    def get(self, validator_index: int) -> Optional[PublicKey]:
        if 0 <= validator_index < len(self._keys):
            return self._keys[validator_index]
        return None

    def get_index(self, pubkey_bytes: bytes) -> Optional[int]:
        return self._by_bytes.get(bytes(pubkey_bytes))

    def getter(self):
        """get_pubkey callable for the signature-set constructors."""

        def get_pubkey(index: int) -> PublicKey:
            pk = self.get(index)
            if pk is None:
                raise KeyError(f"unknown validator index {index}")
            return pk

        return get_pubkey
