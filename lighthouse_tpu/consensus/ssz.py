"""SSZ (SimpleSerialize) encode/decode + Merkle hash-tree-root.

Clean-room implementation of the Ethereum consensus SSZ spec (the
reference consumes it via the `ethereum_ssz`/`tree_hash` crates across
consensus/types). Covers the full type algebra the beacon types need:
uintN, boolean, Bytes{N}, Vector, List, Bitvector, Bitlist, Container,
and Union is omitted (unused by the types we model).

Types are *descriptors* (instances of SSZType subclasses); values are
plain Python (ints, bytes, lists, dataclass-like Containers). This keeps
the host layer simple and keeps hashing vectorizable later (hash-tree-
root of big state objects is a flagged TPU-offload candidate,
SURVEY.md §7 P4 note).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Any, Sequence

BYTES_PER_CHUNK = 32
OFFSET_SIZE = 4


def _hash(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


_ZERO_CHUNKS = [b"\x00" * 32]
for _ in range(64):
    _ZERO_CHUNKS.append(_hash(_ZERO_CHUNKS[-1], _ZERO_CHUNKS[-1]))


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def merkleize(chunks: Sequence[bytes], limit: int = None) -> bytes:
    """Binary Merkle tree over 32-byte chunks, padded with zero-subtrees
    to `limit` (or to the chunk count) leaves."""
    count = len(chunks)
    width = _next_pow2(limit if limit is not None else count)
    if limit is not None and count > limit:
        raise ValueError("chunk count exceeds limit")
    depth = width.bit_length() - 1
    if count == 0:
        return _ZERO_CHUNKS[depth]
    layer = list(chunks)
    for d in range(depth):
        if len(layer) % 2:
            layer.append(_ZERO_CHUNKS[d])
        layer = [_hash(layer[i], layer[i + 1]) for i in range(0, len(layer), 2)]
    return layer[0]


def mix_in_length(root: bytes, length: int) -> bytes:
    return _hash(root, length.to_bytes(32, "little"))


def _pack_bytes(data: bytes) -> list:
    if len(data) % BYTES_PER_CHUNK:
        data = data + b"\x00" * (BYTES_PER_CHUNK - len(data) % BYTES_PER_CHUNK)
    return [data[i : i + 32] for i in range(0, len(data), 32)] or [b"\x00" * 32]


# ---------------------------------------------------------------- descriptors


class SSZType:
    def is_fixed_size(self) -> bool:
        raise NotImplementedError

    def fixed_size(self) -> int:
        raise NotImplementedError

    def serialize(self, value) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes):
        raise NotImplementedError

    def hash_tree_root(self, value) -> bytes:
        raise NotImplementedError

    def default(self):
        raise NotImplementedError


class Uint(SSZType):
    def __init__(self, bits: int):
        self.bits = bits

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return self.bits // 8

    def serialize(self, value) -> bytes:
        return int(value).to_bytes(self.bits // 8, "little")

    def deserialize(self, data: bytes):
        if len(data) != self.bits // 8:
            raise ValueError("bad uint size")
        return int.from_bytes(data, "little")

    def hash_tree_root(self, value) -> bytes:
        return merkleize(_pack_bytes(self.serialize(value)))

    def default(self):
        return 0


class Boolean(SSZType):
    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return 1

    def serialize(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def deserialize(self, data: bytes):
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise ValueError("bad boolean")

    def hash_tree_root(self, value) -> bytes:
        return merkleize(_pack_bytes(self.serialize(value)))

    def default(self):
        return False


class ByteVector(SSZType):
    def __init__(self, length: int):
        self.length = length

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return self.length

    def serialize(self, value) -> bytes:
        if len(value) != self.length:
            raise ValueError("bad bytes length")
        return bytes(value)

    def deserialize(self, data: bytes):
        if len(data) != self.length:
            raise ValueError("bad bytes length")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        return merkleize(_pack_bytes(self.serialize(value)))

    def default(self):
        return b"\x00" * self.length


class ByteList(SSZType):
    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        if len(value) > self.limit:
            raise ValueError("byte list too long")
        return bytes(value)

    def deserialize(self, data: bytes):
        if len(data) > self.limit:
            raise ValueError("byte list too long")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        chunks = _pack_bytes(bytes(value)) if value else []
        return mix_in_length(
            merkleize(chunks, (self.limit + 31) // 32), len(value)
        )

    def default(self):
        return b""


class Vector(SSZType):
    def __init__(self, elem: SSZType, length: int):
        self.elem = elem
        self.length = length

    def is_fixed_size(self):
        return self.elem.is_fixed_size()

    def fixed_size(self):
        return self.elem.fixed_size() * self.length

    def serialize(self, value) -> bytes:
        if len(value) != self.length:
            raise ValueError("bad vector length")
        return _serialize_seq(self.elem, value)

    def deserialize(self, data: bytes):
        out = _deserialize_seq(self.elem, data)
        if len(out) != self.length:
            raise ValueError("bad vector length")
        return out

    def hash_tree_root(self, value) -> bytes:
        return _seq_root(self.elem, value, None)

    def default(self):
        return [self.elem.default() for _ in range(self.length)]


class List(SSZType):
    def __init__(self, elem: SSZType, limit: int):
        self.elem = elem
        self.limit = limit

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        if len(value) > self.limit:
            raise ValueError("list too long")
        return _serialize_seq(self.elem, value)

    def deserialize(self, data: bytes):
        out = _deserialize_seq(self.elem, data)
        if len(out) > self.limit:
            raise ValueError("list too long")
        return out

    def hash_tree_root(self, value) -> bytes:
        if isinstance(self.elem, (Uint, Boolean)):
            limit_chunks = (self.limit * self.elem.fixed_size() + 31) // 32
        else:
            limit_chunks = self.limit
        return mix_in_length(
            _seq_root(self.elem, value, limit_chunks), len(value)
        )

    def default(self):
        return []


class Bitvector(SSZType):
    def __init__(self, length: int):
        self.length = length

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return (self.length + 7) // 8

    def serialize(self, value) -> bytes:
        if len(value) != self.length:
            raise ValueError("bad bitvector length")
        out = bytearray((self.length + 7) // 8)
        for i, bit in enumerate(value):
            if bit:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)

    def deserialize(self, data: bytes):
        if len(data) != self.fixed_size():
            raise ValueError("bad bitvector size")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(self.length)]
        # excess bits must be zero
        for i in range(self.length, len(data) * 8):
            if (data[i // 8] >> (i % 8)) & 1:
                raise ValueError("nonzero padding bit")
        return bits

    def hash_tree_root(self, value) -> bytes:
        return merkleize(
            _pack_bytes(self.serialize(value)), (self.length + 255) // 256
        )

    def default(self):
        return [False] * self.length


class Bitlist(SSZType):
    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        if len(value) > self.limit:
            raise ValueError("bitlist too long")
        out = bytearray(len(value) // 8 + 1)
        for i, bit in enumerate(value):
            if bit:
                out[i // 8] |= 1 << (i % 8)
        out[len(value) // 8] |= 1 << (len(value) % 8)  # delimiter
        return bytes(out)

    def deserialize(self, data: bytes):
        if not data or data[-1] == 0:
            raise ValueError("missing bitlist delimiter")
        total = (len(data) - 1) * 8 + data[-1].bit_length() - 1
        if total > self.limit:
            raise ValueError("bitlist too long")
        return [bool((data[i // 8] >> (i % 8)) & 1) for i in range(total)]

    def hash_tree_root(self, value) -> bytes:
        out = bytearray(((len(value) + 7) // 8) or 0)
        for i, bit in enumerate(value):
            if bit:
                out[i // 8] |= 1 << (i % 8)
        chunks = _pack_bytes(bytes(out)) if value else []
        return mix_in_length(
            merkleize(chunks, (self.limit + 255) // 256), len(value)
        )

    def default(self):
        return []


def _serialize_seq(elem: SSZType, values) -> bytes:
    if elem.is_fixed_size():
        return b"".join(elem.serialize(v) for v in values)
    parts = [elem.serialize(v) for v in values]
    offset = OFFSET_SIZE * len(parts)
    out = bytearray()
    for p in parts:
        out += offset.to_bytes(OFFSET_SIZE, "little")
        offset += len(p)
    for p in parts:
        out += p
    return bytes(out)


def _deserialize_seq(elem: SSZType, data: bytes):
    if elem.is_fixed_size():
        size = elem.fixed_size()
        if size == 0 or len(data) % size:
            raise ValueError("bad sequence size")
        return [
            elem.deserialize(data[i : i + size]) for i in range(0, len(data), size)
        ]
    if not data:
        return []
    first = int.from_bytes(data[:OFFSET_SIZE], "little")
    if first % OFFSET_SIZE or first > len(data) or first == 0:
        raise ValueError("bad first offset")
    n = first // OFFSET_SIZE
    offsets = [
        int.from_bytes(data[i * 4 : i * 4 + 4], "little") for i in range(n)
    ] + [len(data)]
    out = []
    for i in range(n):
        if offsets[i + 1] < offsets[i] or offsets[i] > len(data):
            raise ValueError("offsets not monotonic / out of bounds")
        out.append(elem.deserialize(data[offsets[i] : offsets[i + 1]]))
    return out


# Content-keyed root cache for big sequences: beacon-state vectors
# (randao mixes, block/state roots) are re-rooted every slot but change
# in at most one entry; one C-speed sha256 over the joined leaves is
# ~100x cheaper than the 2N python-level hash calls it skips. Bounded
# FIFO (dict preserves insertion order).
_ROOT_CACHE: dict = {}
_ROOT_CACHE_MAX = 4096
_CACHE_MIN_CHUNKS = 256


def _cached_merkleize(chunks: list, limit_chunks) -> bytes:
    if len(chunks) < _CACHE_MIN_CHUNKS:
        return merkleize(chunks, limit_chunks)
    key = (hashlib.sha256(b"".join(chunks)).digest(), len(chunks), limit_chunks)
    root = _ROOT_CACHE.get(key)
    if root is None:
        root = merkleize(chunks, limit_chunks)
        if len(_ROOT_CACHE) >= _ROOT_CACHE_MAX:
            _ROOT_CACHE.pop(next(iter(_ROOT_CACHE)))
        _ROOT_CACHE[key] = root
    return root


def _seq_root(elem: SSZType, values, limit_chunks) -> bytes:
    if isinstance(elem, (Uint, Boolean)):
        data = b"".join(elem.serialize(v) for v in values)
        chunks = _pack_bytes(data) if data else []
        return _cached_merkleize(chunks, limit_chunks)
    if isinstance(elem, ByteVector) and elem.length == 32:
        # a 32-byte leaf IS its own chunk root — skip per-element calls
        roots = [bytes(v) for v in values]
    else:
        roots = [elem.hash_tree_root(v) for v in values]
    return _cached_merkleize(roots, limit_chunks)


# ---------------------------------------------------------------- containers


class Container(SSZType):
    """A named, ordered set of typed fields. Subclass-free: built from a
    field spec, producing lightweight value objects (SSZValue)."""

    def __init__(self, name: str, fields: Sequence[tuple]):
        self.name = name
        self.fields = list(fields)  # [(name, SSZType), ...]

    def is_fixed_size(self):
        return all(t.is_fixed_size() for _, t in self.fields)

    def fixed_size(self):
        return sum(t.fixed_size() for _, t in self.fields)

    def serialize(self, value) -> bytes:
        fixed_parts = []
        var_parts = []
        for fname, ftype in self.fields:
            v = getattr(value, fname)
            if ftype.is_fixed_size():
                fixed_parts.append(ftype.serialize(v))
                var_parts.append(None)
            else:
                fixed_parts.append(None)
                var_parts.append(ftype.serialize(v))
        fixed_len = sum(
            len(p) if p is not None else OFFSET_SIZE for p in fixed_parts
        )
        out = bytearray()
        offset = fixed_len
        for p, v in zip(fixed_parts, var_parts):
            if p is not None:
                out += p
            else:
                out += offset.to_bytes(OFFSET_SIZE, "little")
                offset += len(v)
        for v in var_parts:
            if v is not None:
                out += v
        return bytes(out)

    def deserialize(self, data: bytes):
        pos = 0
        offsets = []
        fixed_vals = {}
        for fname, ftype in self.fields:
            if ftype.is_fixed_size():
                size = ftype.fixed_size()
                if pos + size > len(data):
                    raise ValueError("container truncated")
                fixed_vals[fname] = ftype.deserialize(data[pos : pos + size])
                pos += size
            else:
                offsets.append(
                    (fname, int.from_bytes(data[pos : pos + 4], "little"))
                )
                pos += OFFSET_SIZE
        if offsets:
            # the first variable offset must land exactly at the end of
            # the fixed part — anything else is a non-canonical encoding
            if offsets[0][1] != pos:
                raise ValueError("first offset != fixed-part length")
        elif pos != len(data):
            raise ValueError("trailing bytes after fixed container")
        offsets.append((None, len(data)))
        for i in range(len(offsets) - 1):
            fname, start = offsets[i]
            _, end = offsets[i + 1]
            ftype = dict(self.fields)[fname]
            if end < start or start > len(data):
                raise ValueError("offsets not monotonic / out of bounds")
            fixed_vals[fname] = ftype.deserialize(data[start:end])
        return SSZValue(self, fixed_vals)

    def hash_tree_root(self, value) -> bytes:
        roots = [
            ftype.hash_tree_root(getattr(value, fname))
            for fname, ftype in self.fields
        ]
        return merkleize(roots)

    def default(self):
        return SSZValue(
            self, {fname: ftype.default() for fname, ftype in self.fields}
        )

    def make(self, **kwargs):
        vals = {}
        for fname, ftype in self.fields:
            vals[fname] = kwargs.pop(fname) if fname in kwargs else ftype.default()
        if kwargs:
            raise TypeError(f"unknown fields {list(kwargs)} for {self.name}")
        return SSZValue(self, vals)


class SSZValue:
    """A container instance: attribute access + copy-on-write updates."""

    __slots__ = ("_type", "_vals")

    def __init__(self, ctype: Container, vals: dict):
        object.__setattr__(self, "_type", ctype)
        object.__setattr__(self, "_vals", vals)

    def __getattr__(self, name):
        try:
            return object.__getattribute__(self, "_vals")[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name, value):
        vals = object.__getattribute__(self, "_vals")
        if name not in vals:
            raise AttributeError(f"no field {name}")
        vals[name] = value

    def copy(self) -> "SSZValue":
        """Type-driven fast copy: leaf values (ints, bytes, bools) are
        immutable and SHARED; containers and element lists are rebuilt.
        Semantically a deep copy (every mutation path in this codebase
        goes through __setattr__ / list __setitem__ on the rebuilt
        spine) at a fraction of generic deepcopy's dispatch cost —
        state.copy() is the per-block hot path the reference serves
        with milhouse structural sharing."""
        return _fast_copy_container(self._type, self)

    def __deepcopy__(self, memo) -> "SSZValue":
        return self.copy()

    def serialize(self) -> bytes:
        return self._type.serialize(self)

    def hash_tree_root(self) -> bytes:
        return self._type.hash_tree_root(self)

    def __eq__(self, other):
        return (
            isinstance(other, SSZValue)
            and self._type is other._type
            and self.serialize() == other.serialize()
        )

    def __repr__(self):
        return f"<{self._type.name} {self._vals}>"


def _fast_copy_value(ftype: SSZType, value):
    """Copy `value` of SSZ type `ftype`: immutable leaves shared,
    mutable spines (lists, containers) rebuilt."""
    if isinstance(ftype, Container):
        return _fast_copy_container(ftype, value)
    if isinstance(ftype, (List, Vector)):
        elem = ftype.elem
        if isinstance(elem, (Container, List, Vector, Bitlist, Bitvector)):
            return [_fast_copy_value(elem, v) for v in value]
        return list(value)  # scalar/bytes elements are immutable
    if isinstance(ftype, (Bitlist, Bitvector)):
        return list(value)
    return value  # int / bytes / bool


def _fast_copy_container(ctype: Container, value) -> "SSZValue":
    return SSZValue(
        ctype,
        {
            fname: _fast_copy_value(ftype, getattr(value, fname))
            for fname, ftype in ctype.fields
        },
    )


# common aliases
uint8 = Uint(8)
uint16 = Uint(16)
uint32 = Uint(32)
uint64 = Uint(64)
uint256 = Uint(256)
boolean = Boolean()
Bytes4 = ByteVector(4)
Bytes20 = ByteVector(20)
Bytes32 = ByteVector(32)
Bytes48 = ByteVector(48)
Bytes96 = ByteVector(96)
