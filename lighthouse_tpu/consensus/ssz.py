"""SSZ (SimpleSerialize) encode/decode + Merkle hash-tree-root.

Clean-room implementation of the Ethereum consensus SSZ spec (the
reference consumes it via the `ethereum_ssz`/`tree_hash` crates across
consensus/types). Covers the full type algebra the beacon types need:
uintN, boolean, Bytes{N}, Vector, List, Bitvector, Bitlist, Container,
and Union is omitted (unused by the types we model).

Types are *descriptors* (instances of SSZType subclasses); values are
plain Python (ints, bytes, lists, dataclass-like Containers) — except
big List/Vector values, which live on a chunked copy-on-write spine
(ChunkedSeq, the milhouse-persistent-list analog) so `state.copy()` is
O(spine) and hash-tree-root is O(dirty chunks). This keeps the host
layer simple and keeps hashing vectorizable later (hash-tree-root of
big state objects is a flagged TPU-offload candidate, SURVEY.md §7 P4
note).
"""

from __future__ import annotations

import hashlib
import itertools
from dataclasses import dataclass
from typing import Any, Sequence

import numpy as np

BYTES_PER_CHUNK = 32
OFFSET_SIZE = 4

# ChunkedSeq spine geometry: elements per chunk (power of two). A plain
# list longer than _WRAP_THRESHOLD that lands in a List/Vector container
# field is converted to a ChunkedSeq so state.copy() is O(spine).
CHUNK_ELEMS = 1024
_WRAP_THRESHOLD = 2048


# Merkleization census hook (ISSUE 11): ops/hash_costs.py installs a
# recorder here and every seam below consults it per call — the
# fp.CENSUS pattern. None (the default) costs one global read on the
# hot path; a recorder attributes every SHA-256 compression during a
# hash_tree_root to (top-level field, cause) where cause is one of
# dirty_chunk / subtree / cache_key / small_container, plus per-field
# dirty-chunk counts and chunk/root cache hit rates.
CENSUS = None

# Runtime sanitizer hook (ISSUE 12): common/sanitize.py installs a
# Sanitizer here (LH_SANITIZE=1 or tests), and the ChunkedSeq/SSZValue
# seams below consult it per call — the CENSUS pattern. None (the
# default) costs one global read on each seam. Install ONLY through
# common/sanitize.install() (graft-lint R5 flags direct assignment).
SANITIZER = None


def _hash(a: bytes, b: bytes) -> bytes:
    # both operands are 32-byte chunks at every call site: 64 bytes +
    # SHA-256 padding = exactly 2 compression-function invocations
    if CENSUS is not None:
        CENSUS.on_hash(2)
    return hashlib.sha256(a + b).digest()


_ZERO_CHUNKS = [b"\x00" * 32]
for _ in range(64):
    _ZERO_CHUNKS.append(_hash(_ZERO_CHUNKS[-1], _ZERO_CHUNKS[-1]))


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def merkleize(chunks: Sequence[bytes], limit: int = None) -> bytes:
    """Binary Merkle tree over 32-byte chunks, padded with zero-subtrees
    to `limit` (or to the chunk count) leaves."""
    count = len(chunks)
    width = _next_pow2(limit if limit is not None else count)
    if limit is not None and count > limit:
        raise ValueError("chunk count exceeds limit")
    depth = width.bit_length() - 1
    if count == 0:
        return _ZERO_CHUNKS[depth]
    layer = list(chunks)
    for d in range(depth):
        if len(layer) % 2:
            layer.append(_ZERO_CHUNKS[d])
        layer = [_hash(layer[i], layer[i + 1]) for i in range(0, len(layer), 2)]
    return layer[0]


def mix_in_length(root: bytes, length: int) -> bytes:
    return _hash(root, length.to_bytes(32, "little"))


def _pack_bytes(data: bytes) -> list:
    if len(data) % BYTES_PER_CHUNK:
        data = data + b"\x00" * (BYTES_PER_CHUNK - len(data) % BYTES_PER_CHUNK)
    return [data[i : i + 32] for i in range(0, len(data), 32)] or [b"\x00" * 32]


# ---------------------------------------------------------------- descriptors


class SSZType:
    def is_fixed_size(self) -> bool:
        raise NotImplementedError

    def fixed_size(self) -> int:
        raise NotImplementedError

    def serialize(self, value) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes):
        raise NotImplementedError

    def hash_tree_root(self, value) -> bytes:
        raise NotImplementedError

    def default(self):
        raise NotImplementedError


class Uint(SSZType):
    def __init__(self, bits: int):
        self.bits = bits

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return self.bits // 8

    def serialize(self, value) -> bytes:
        return int(value).to_bytes(self.bits // 8, "little")

    def deserialize(self, data: bytes):
        if len(data) != self.bits // 8:
            raise ValueError("bad uint size")
        return int.from_bytes(data, "little")

    def hash_tree_root(self, value) -> bytes:
        return merkleize(_pack_bytes(self.serialize(value)))

    def default(self):
        return 0


class Boolean(SSZType):
    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return 1

    def serialize(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def deserialize(self, data: bytes):
        if data == b"\x00":
            return False
        if data == b"\x01":
            return True
        raise ValueError("bad boolean")

    def hash_tree_root(self, value) -> bytes:
        return merkleize(_pack_bytes(self.serialize(value)))

    def default(self):
        return False


class ByteVector(SSZType):
    def __init__(self, length: int):
        self.length = length

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return self.length

    def serialize(self, value) -> bytes:
        if len(value) != self.length:
            raise ValueError("bad bytes length")
        return bytes(value)

    def deserialize(self, data: bytes):
        if len(data) != self.length:
            raise ValueError("bad bytes length")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        return merkleize(_pack_bytes(self.serialize(value)))

    def default(self):
        return b"\x00" * self.length


class ByteList(SSZType):
    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        if len(value) > self.limit:
            raise ValueError("byte list too long")
        return bytes(value)

    def deserialize(self, data: bytes):
        if len(data) > self.limit:
            raise ValueError("byte list too long")
        return bytes(data)

    def hash_tree_root(self, value) -> bytes:
        chunks = _pack_bytes(bytes(value)) if value else []
        return mix_in_length(
            merkleize(chunks, (self.limit + 31) // 32), len(value)
        )

    def default(self):
        return b""


class Vector(SSZType):
    def __init__(self, elem: SSZType, length: int):
        self.elem = elem
        self.length = length

    def is_fixed_size(self):
        return self.elem.is_fixed_size()

    def fixed_size(self):
        return self.elem.fixed_size() * self.length

    def serialize(self, value) -> bytes:
        if len(value) != self.length:
            raise ValueError("bad vector length")
        return _serialize_seq(self.elem, value)

    def deserialize(self, data: bytes):
        out = _deserialize_seq(self.elem, data)
        if len(out) != self.length:
            raise ValueError("bad vector length")
        return out

    def hash_tree_root(self, value) -> bytes:
        return _seq_root(self.elem, value, None)

    def default(self):
        return [self.elem.default() for _ in range(self.length)]


class List(SSZType):
    def __init__(self, elem: SSZType, limit: int):
        self.elem = elem
        self.limit = limit

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        if len(value) > self.limit:
            raise ValueError("list too long")
        return _serialize_seq(self.elem, value)

    def deserialize(self, data: bytes):
        out = _deserialize_seq(self.elem, data)
        if len(out) > self.limit:
            raise ValueError("list too long")
        return out

    def hash_tree_root(self, value) -> bytes:
        if isinstance(self.elem, (Uint, Boolean)):
            limit_chunks = (self.limit * self.elem.fixed_size() + 31) // 32
        else:
            limit_chunks = self.limit
        return mix_in_length(
            _seq_root(self.elem, value, limit_chunks), len(value)
        )

    def default(self):
        return []


class Bitvector(SSZType):
    def __init__(self, length: int):
        self.length = length

    def is_fixed_size(self):
        return True

    def fixed_size(self):
        return (self.length + 7) // 8

    def serialize(self, value) -> bytes:
        if len(value) != self.length:
            raise ValueError("bad bitvector length")
        out = bytearray((self.length + 7) // 8)
        for i, bit in enumerate(value):
            if bit:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)

    def deserialize(self, data: bytes):
        if len(data) != self.fixed_size():
            raise ValueError("bad bitvector size")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(self.length)]
        # excess bits must be zero
        for i in range(self.length, len(data) * 8):
            if (data[i // 8] >> (i % 8)) & 1:
                raise ValueError("nonzero padding bit")
        return bits

    def hash_tree_root(self, value) -> bytes:
        return merkleize(
            _pack_bytes(self.serialize(value)), (self.length + 255) // 256
        )

    def default(self):
        return [False] * self.length


class Bitlist(SSZType):
    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed_size(self):
        return False

    def serialize(self, value) -> bytes:
        if len(value) > self.limit:
            raise ValueError("bitlist too long")
        out = bytearray(len(value) // 8 + 1)
        for i, bit in enumerate(value):
            if bit:
                out[i // 8] |= 1 << (i % 8)
        out[len(value) // 8] |= 1 << (len(value) % 8)  # delimiter
        return bytes(out)

    def deserialize(self, data: bytes):
        if not data or data[-1] == 0:
            raise ValueError("missing bitlist delimiter")
        total = (len(data) - 1) * 8 + data[-1].bit_length() - 1
        if total > self.limit:
            raise ValueError("bitlist too long")
        return [bool((data[i // 8] >> (i % 8)) & 1) for i in range(total)]

    def hash_tree_root(self, value) -> bytes:
        out = bytearray(((len(value) + 7) // 8) or 0)
        for i, bit in enumerate(value):
            if bit:
                out[i // 8] |= 1 << (i % 8)
        chunks = _pack_bytes(bytes(out)) if value else []
        return mix_in_length(
            merkleize(chunks, (self.limit + 255) // 256), len(value)
        )

    def default(self):
        return []


def _serialize_seq(elem: SSZType, values) -> bytes:
    if elem.is_fixed_size():
        return b"".join(elem.serialize(v) for v in values)
    parts = [elem.serialize(v) for v in values]
    offset = OFFSET_SIZE * len(parts)
    out = bytearray()
    for p in parts:
        out += offset.to_bytes(OFFSET_SIZE, "little")
        offset += len(p)
    for p in parts:
        out += p
    return bytes(out)


def _deserialize_seq(elem: SSZType, data: bytes):
    if elem.is_fixed_size():
        size = elem.fixed_size()
        if size == 0 or len(data) % size:
            raise ValueError("bad sequence size")
        return [
            elem.deserialize(data[i : i + size]) for i in range(0, len(data), size)
        ]
    if not data:
        return []
    first = int.from_bytes(data[:OFFSET_SIZE], "little")
    if first % OFFSET_SIZE or first > len(data) or first == 0:
        raise ValueError("bad first offset")
    n = first // OFFSET_SIZE
    offsets = [
        int.from_bytes(data[i * 4 : i * 4 + 4], "little") for i in range(n)
    ] + [len(data)]
    out = []
    for i in range(n):
        if offsets[i + 1] < offsets[i] or offsets[i] > len(data):
            raise ValueError("offsets not monotonic / out of bounds")
        out.append(elem.deserialize(data[offsets[i] : offsets[i + 1]]))
    return out


# ------------------------------------------------------- chunked CoW spine
#
# The persistent-list layer (milhouse analog): a big List/Vector value is
# a spine of fixed-size chunks. `copy()` shares every chunk and is
# O(spine); the first mutation of a chunk through __setitem__ / append /
# get_mut copies just that chunk (and, for container elements, just that
# element). Each chunk also caches its merkle SUBTREE root, so
# hash_tree_root after k mutated chunks re-hashes O(k + spine) instead
# of O(n) — the structural sharing the reference gets from milhouse
# (consensus/types/src/beacon_state.rs) for the 9 state.copy() sites in
# the per-slot hot path.
#
# Sharing contract (CHANGES.md "CoW spine contract"):
#   - copy() FREEZES both sides: every chunk becomes shared, and all
#     element-privacy marks are dropped. Either side re-owns a chunk by
#     mutating it.
#   - __setitem__ / append invalidate exactly the touched chunk's cached
#     subtree root and bump the content token.
#   - container elements fetched for IN-PLACE mutation must come from
#     get_mut(i) (seq_get_mut for plain-list compatibility): it CoWs the
#     chunk AND the element, so the sibling copy never observes the
#     write. Reading via [i] / iteration returns the shared object.
#   - the content token (seq_token) is equal across copies until one
#     side mutates: equal tokens imply identical content, which keys the
#     state_transition active-set / committee caches safely.
#
# Column-cache contract (ISSUE 6, the ChunkedSeq→columnar bridge):
#   - columns(name, builder) materializes numpy columns of the
#     sequence ONCE and refreshes only chunks whose per-chunk version
#     changed since the cached build — mutation cost O(dirty chunks),
#     not O(n). Returned arrays are READ-ONLY (writeable=False); callers
#     copy (e.g. .astype) before doing math in place.
#   - copy() shares the column cache both ways (arrays are immutable);
#     each side refreshes independently against its own chunk versions.
#   - in-place element mutation must FINISH before the next column
#     read: get_mut bumps the chunk version at fetch time, so a write
#     applied after a later column refresh would go unseen.
#   - assign_array(arr) is the bulk writeback: it diffs `arr` against
#     the current content per chunk, CoWs + rewrites only the chunks
#     that actually changed (token and merkle root caches invalidate
#     for exactly those), and re-seeds the identity column cache with
#     `arr` itself — ownership of `arr` transfers to the sequence.

_TOKEN_COUNTER = itertools.count(1)


class ChunkedSeq:
    """Chunked persistent sequence backing big SSZ List/Vector values."""

    __slots__ = (
        "_chunks",
        "_len",
        "_owned",
        "_owned_elems",
        "_roots",
        "_root_elem",
        "_elem",
        "_token",
        "_versions",
        "_cols",
        "_san",
    )

    def __init__(self, values=(), elem: SSZType = None):
        vals = values if isinstance(values, list) else list(values)
        self._chunks = [
            vals[i : i + CHUNK_ELEMS] for i in range(0, len(vals), CHUNK_ELEMS)
        ]
        self._len = len(vals)
        # freshly sliced chunk lists are private; the ELEMENTS inside
        # came from the caller and may be aliased — not private
        self._owned = set(range(len(self._chunks)))
        self._owned_elems = {}
        self._roots = [None] * len(self._chunks)
        self._root_elem = None
        self._elem = elem
        self._token = next(_TOKEN_COUNTER)
        # per-chunk mutation counters keying the column cache
        self._versions = [0] * len(self._chunks)
        # name -> (tuple of np arrays, versions snapshot, length)
        self._cols = {}
        # sanitizer-mode per-chunk checksums ({ci: hash}, see
        # common/sanitize.py); None whenever the sanitizer is off
        self._san = None

    # ------------------------------------------------------------ sharing

    def copy(self) -> "ChunkedSeq":
        """O(spine) structural-sharing copy; freezes both sides."""
        self._owned.clear()
        self._owned_elems.clear()
        new = ChunkedSeq.__new__(ChunkedSeq)
        new._chunks = list(self._chunks)
        new._len = self._len
        new._owned = set()
        new._owned_elems = {}
        new._roots = list(self._roots)
        new._root_elem = self._root_elem
        new._elem = self._elem
        new._token = self._token
        new._versions = list(self._versions)
        new._cols = dict(self._cols)  # arrays are read-only: share both ways
        new._san = None
        if SANITIZER is not None:
            SANITIZER.on_copy(self, new)
        return new

    @property
    def token(self) -> int:
        return self._token

    # ------------------------------------------------- dirty-set surface
    #
    # ISSUE 11: the per-chunk version counters keying the column cache
    # already know exactly which chunks mutated; surface them so the
    # merkleization observatory (ops/hash_costs.py) and its soundness
    # tests can compare "what the spine thinks is dirty" against "what
    # actually re-hashed" without reaching into slots.

    def versions(self) -> tuple:
        """Snapshot of the per-chunk mutation counters (pair with
        dirty_chunks_since)."""
        return tuple(self._versions)

    def dirty_chunks_since(self, snapshot: tuple) -> list:
        """Chunk indices whose content may differ from when `snapshot`
        (a versions() result) was taken: bumped counters plus chunks
        appended since. Exactly the set hash_tree_root will re-hash,
        provided the snapshot was taken with root caches warm."""
        n = min(len(snapshot), len(self._versions))
        out = [ci for ci in range(n) if self._versions[ci] != snapshot[ci]]
        out.extend(range(len(snapshot), len(self._chunks)))
        return out

    def _own_chunk(self, ci: int) -> list:
        """Make chunk `ci` privately mutable; invalidate its root."""
        if SANITIZER is not None and self._san:
            SANITIZER.on_own_chunk(self, ci)
        if ci not in self._owned:
            self._chunks[ci] = list(self._chunks[ci])
            self._owned.add(ci)
            self._owned_elems[ci] = set()
        self._roots[ci] = None
        self._token = next(_TOKEN_COUNTER)
        self._versions[ci] += 1
        return self._chunks[ci]

    def get_mut(self, i: int):
        """Fetch element `i` for in-place mutation: CoWs the chunk and
        the element so no sibling copy observes the write."""
        ci, off = self._locate(i)
        chunk = self._own_chunk(ci)
        priv = self._owned_elems.setdefault(ci, set())
        if off not in priv:
            e = chunk[off]
            if self._elem is not None:
                e = _fast_copy_value(self._elem, e)
            elif isinstance(e, SSZValue):
                e = SSZValue(e._type, dict(e._vals))
            elif isinstance(e, list):
                e = list(e)
            chunk[off] = e
            priv.add(off)
        return chunk[off]

    # ----------------------------------------------------------- sequence

    def _locate(self, i):
        i = int(i)
        if i < 0:
            i += self._len
        if not 0 <= i < self._len:
            raise IndexError("ChunkedSeq index out of range")
        return i // CHUNK_ELEMS, i % CHUNK_ELEMS

    def __len__(self) -> int:
        return self._len

    def __iter__(self):
        san = SANITIZER
        if san is None:
            for ci in range(len(self._chunks)):
                yield from self._chunks[ci]
            return
        for ci, chunk in enumerate(self._chunks):
            for off, v in enumerate(chunk):
                san.on_element_read(self, ci, off, v)
                yield v

    def __getitem__(self, i):
        if isinstance(i, slice):
            start, stop, step = i.indices(self._len)
            return [self[j] for j in range(start, stop, step)]
        ci, off = self._locate(i)
        v = self._chunks[ci][off]
        if SANITIZER is not None:
            SANITIZER.on_element_read(self, ci, off, v)
        return v

    def __setitem__(self, i, value) -> None:
        ci, off = self._locate(i)
        chunk = self._own_chunk(ci)
        chunk[off] = value
        # caller-provided object: treat as private to this instance
        self._owned_elems.setdefault(ci, set()).add(off)

    def append(self, value) -> None:
        if self._chunks and len(self._chunks[-1]) < CHUNK_ELEMS:
            ci = len(self._chunks) - 1
            chunk = self._own_chunk(ci)
            self._owned_elems.setdefault(ci, set()).add(len(chunk))
            chunk.append(value)
        else:
            ci = len(self._chunks)
            self._chunks.append([value])
            self._roots.append(None)
            self._owned.add(ci)
            self._owned_elems[ci] = {0}
            self._token = next(_TOKEN_COUNTER)
            self._versions.append(0)
        self._len += 1

    def __eq__(self, other):
        if other is self:
            return True
        try:
            if len(other) != self._len:
                return False
        except TypeError:
            return NotImplemented
        return all(a == b for a, b in zip(self, other))

    def __repr__(self):
        return (
            f"<ChunkedSeq len={self._len} chunks={len(self._chunks)} "
            f"token={self._token}>"
        )

    # ------------------------------------------------------ column caching

    def columns(self, name: str, builder) -> tuple:
        """Materialize numpy columns of this sequence, cached under
        `name` and refreshed per dirty chunk.

        `builder(values) -> tuple of arrays (one row per element)` is
        called per chunk on refresh (and with the full value list by
        the plain-list fallback in `seq_columns`); it must handle an
        empty list, and its arity fixes the column count. Returned
        arrays are read-only."""
        cur = tuple(self._versions)
        hit = self._cols.get(name)
        old = vers = None
        length = 0
        if hit is not None:
            old, vers, length = hit
            if length == self._len and vers == cur:
                return old
        if not self._chunks:
            arrs = builder([])
            for a in arrs:
                a.flags.writeable = False
            self._cols[name] = (arrs, cur, 0)
            return arrs
        outs = None
        for ci, chunk in enumerate(self._chunks):
            lo = ci * CHUNK_ELEMS
            hi = lo + len(chunk)
            clean = (
                old is not None
                and ci < len(vers)
                and vers[ci] == cur[ci]
                and hi <= length
            )
            if clean:
                if outs is not None:
                    for k, out in enumerate(outs):
                        out[lo:hi] = old[k][lo:hi]
                continue
            part = builder(chunk)
            if outs is None:
                outs = tuple(
                    np.empty(self._len, dtype=p.dtype) for p in part
                )
                if lo:  # backfill the clean prefix we skipped
                    for k, out in enumerate(outs):
                        out[:lo] = old[k][:lo]
            for k, out in enumerate(outs):
                out[lo:hi] = part[k]
        if outs is None:  # all chunks clean yet cache key missed
            outs = tuple(a[: self._len].copy() for a in old)
        for a in outs:
            a.flags.writeable = False
        self._cols[name] = (outs, cur, self._len)
        return outs

    def assign_array(self, arr: "np.ndarray") -> int:
        """Bulk scalar writeback: make this sequence's content equal to
        `arr`, copying-on-write only the chunks that differ. Ownership
        of `arr` transfers to the sequence (it becomes the cached
        identity column and is frozen read-only). Returns the number of
        chunks rewritten — 0 leaves token and root caches untouched."""
        if len(arr) != self._len:
            raise ValueError(
                f"assign_array length {len(arr)} != seq length {self._len}"
            )
        name = f"id:{arr.dtype.name}"
        hit = self._cols.get(name)
        cur = tuple(self._versions)
        prev = None
        if hit is not None and hit[2] == self._len and hit[1] == cur:
            prev = hit[0][0]
        dirty = 0
        for ci, chunk in enumerate(self._chunks):
            lo = ci * CHUNK_ELEMS
            hi = lo + len(chunk)
            seg = arr[lo:hi]
            ref = (
                prev[lo:hi]
                if prev is not None
                else np.asarray(chunk, dtype=arr.dtype)
            )
            if np.array_equal(seg, ref):
                continue
            self._own_chunk(ci)
            self._chunks[ci][:] = seg.tolist()
            dirty += 1
        arr.flags.writeable = False
        self._cols[name] = ((arr,), tuple(self._versions), self._len)
        return dirty

    # -------------------------------------------------------- root caching

    def _cached_chunk_root(self, ci: int, elem: SSZType) -> bytes:
        if self._root_elem is not elem:
            # roots were computed under a different descriptor: drop them
            self._roots = [None] * len(self._chunks)
            self._root_elem = elem
        if SANITIZER is not None and self._san:
            SANITIZER.on_chunk_root(self, ci)
        r = self._roots[ci]
        c = CENSUS
        if r is None:
            if c is not None:
                # everything hashed until the chunk root lands — packing,
                # per-element container roots, the subtree combine — is a
                # dirty-chunk recompute; the recorder also charges one
                # dirty chunk to the current field and a chunk-cache miss
                c.begin_dirty_chunk()
                try:
                    r = _chunk_subtree_root(
                        elem, self._chunks[ci], _chunk_depth(elem)
                    )
                finally:
                    c.end_dirty_chunk()
            else:
                r = _chunk_subtree_root(
                    elem, self._chunks[ci], _chunk_depth(elem)
                )
            self._roots[ci] = r
        elif c is not None:
            c.cache_event("chunk", True)
        return r


def seq_token(seq):
    """Content token for cache keys: equal tokens imply identical
    content. None for plain lists (no cheap identity)."""
    return seq._token if isinstance(seq, ChunkedSeq) else None


def seq_get_mut(seq, i: int):
    """Element `i` of `seq`, safe to mutate in place. For a ChunkedSeq
    this CoWs the chunk+element; a plain list was deep-rebuilt by
    copy(), so the element itself is returned."""
    if isinstance(seq, ChunkedSeq):
        return seq.get_mut(i)
    return seq[i]


def seq_column(seq, dtype) -> "np.ndarray":
    """Read-only numpy identity column of a scalar sequence. Cached per
    dirty chunk on a ChunkedSeq; rebuilt per call on a plain list."""
    dt = np.dtype(dtype)
    if isinstance(seq, ChunkedSeq):

        def build(vals, _dt=dt):
            return (np.asarray(vals, dtype=_dt),)

        return seq.columns(f"id:{dt.name}", build)[0]
    vals = seq if isinstance(seq, list) else list(seq)
    return np.asarray(vals, dtype=dt)


def seq_columns(seq, name: str, builder) -> tuple:
    """Derived numpy columns of a sequence (e.g. several validator
    fields in one pass). Cached per dirty chunk on a ChunkedSeq;
    rebuilt per call on a plain list."""
    if isinstance(seq, ChunkedSeq):
        return seq.columns(name, builder)
    vals = seq if isinstance(seq, list) else list(seq)
    return builder(vals)


def seq_assign_array(seq, arr, dtype=None) -> int:
    """Bulk scalar writeback of a numpy column into `seq` — the API
    that replaces `state.field = [int(x) for x in arr]` scalarization.
    ChunkedSeq: CoW + token/root invalidation only for changed chunks
    (ownership of `arr` transfers, see ChunkedSeq.assign_array). Plain
    list: slice-assigned in place. Returns changed-chunk count (plain
    lists report 1)."""
    arr = np.ascontiguousarray(arr, dtype=None if dtype is None else np.dtype(dtype))
    if isinstance(seq, ChunkedSeq):
        return seq.assign_array(arr)
    if len(arr) != len(seq):
        raise ValueError(
            f"assign_array length {len(arr)} != seq length {len(seq)}"
        )
    seq[:] = arr.tolist()
    return 1


def _chunk_depth(elem: SSZType) -> int:
    """Depth of one chunk's merkle subtree: leaf chunks per spine chunk
    as a power of two (basic elements pack; composite elements
    contribute one 32-byte root each)."""
    if isinstance(elem, (Uint, Boolean)):
        leaf_chunks = elem.fixed_size() * CHUNK_ELEMS // BYTES_PER_CHUNK
    else:
        leaf_chunks = CHUNK_ELEMS
    return leaf_chunks.bit_length() - 1


def _chunk_subtree_root(elem: SSZType, chunk: list, depth: int) -> bytes:
    if isinstance(elem, (Uint, Boolean)):
        leaves = _pack_bytes(b"".join(elem.serialize(v) for v in chunk))
    elif isinstance(elem, ByteVector) and elem.length == 32:
        leaves = [bytes(v) for v in chunk]
    else:
        leaves = [elem.hash_tree_root(v) for v in chunk]
    return merkleize(leaves, 1 << depth)


def _chunked_seq_root(elem: SSZType, cs: ChunkedSeq, limit_chunks) -> bytes:
    """Merkle root of a ChunkedSeq from cached per-chunk subtree roots:
    O(dirty chunks + spine) instead of O(n)."""
    if isinstance(elem, (Uint, Boolean)):
        actual_leaves = (len(cs) * elem.fixed_size() + 31) // BYTES_PER_CHUNK
    else:
        actual_leaves = len(cs)
    total_leaves = limit_chunks if limit_chunks is not None else actual_leaves
    if actual_leaves > total_leaves:
        raise ValueError("chunk count exceeds limit")
    width = _next_pow2(total_leaves)
    depth = width.bit_length() - 1
    k = _chunk_depth(elem)
    if depth < k or not cs._chunks:
        return _seq_root_plain(elem, list(cs), limit_chunks)
    layer = [cs._cached_chunk_root(ci, elem) for ci in range(len(cs._chunks))]
    c = CENSUS
    if c is not None:
        c.push_cause("subtree")
    try:
        for d in range(k, depth):
            if len(layer) % 2:
                layer.append(_ZERO_CHUNKS[d])
            layer = [
                _hash(layer[i], layer[i + 1])
                for i in range(0, len(layer), 2)
            ]
    finally:
        if c is not None:
            c.pop_cause()
    return layer[0]


# Content-keyed root cache for big plain sequences: beacon-state
# vectors that stay on plain lists are re-rooted every slot but rarely
# change. ChunkedSeq-backed fields never land here with a cacheable
# chunk count (their per-chunk subtree caches already make re-rooting
# O(dirty chunks)); this cache serves the plain-list leftovers.
# Bounded FIFO (dict preserves insertion order).
#
# Key construction (ISSUE 11 satellite): the key used to be a SHA-256
# over the joined chunks — in compression count that is HALF of the
# merkleization a hit avoids, so every "hit" still paid ~33% of the
# hashing. The chunk tuple itself is the key now: bytes hashes are
# C-speed siphash (cached per object, and `bytes(v)` of an unchanged
# Bytes32 entry returns the same object), equality on a hit is
# content equality — zero SHA-256 compressions, and the census
# `cache_key` column proves it stays at zero. A tuple key retains its
# chunk objects, so the FIFO bound is sized for ~64 KB/entry worst
# case (~16 MB total), not the 4096 entries the 32-byte digest keys
# allowed — the observed working set is single-digit entries.
_ROOT_CACHE: dict = {}
_ROOT_CACHE_MAX = 256
_CACHE_MIN_CHUNKS = 256


def _cached_merkleize(chunks: list, limit_chunks) -> bytes:
    if len(chunks) < _CACHE_MIN_CHUNKS:
        return merkleize(chunks, limit_chunks)
    full_key = (tuple(chunks), limit_chunks)
    root = _ROOT_CACHE.get(full_key)
    c = CENSUS
    if root is None:
        if c is not None:
            c.cache_event("root", False)
        root = merkleize(chunks, limit_chunks)
        if len(_ROOT_CACHE) >= _ROOT_CACHE_MAX:
            _ROOT_CACHE.pop(next(iter(_ROOT_CACHE)))
        _ROOT_CACHE[full_key] = root
    elif c is not None:
        c.cache_event("root", True)
    return root


def _seq_root(elem: SSZType, values, limit_chunks) -> bytes:
    if isinstance(values, ChunkedSeq):
        return _chunked_seq_root(elem, values, limit_chunks)
    return _seq_root_plain(elem, values, limit_chunks)


def _seq_root_plain(elem: SSZType, values, limit_chunks) -> bytes:
    if isinstance(elem, (Uint, Boolean)):
        data = b"".join(elem.serialize(v) for v in values)
        chunks = _pack_bytes(data) if data else []
        return _cached_merkleize(chunks, limit_chunks)
    if isinstance(elem, ByteVector) and elem.length == 32:
        # a 32-byte leaf IS its own chunk root — skip per-element calls
        roots = [bytes(v) for v in values]
    else:
        roots = [elem.hash_tree_root(v) for v in values]
    return _cached_merkleize(roots, limit_chunks)


# ---------------------------------------------------------------- containers

# Content-keyed container root cache (ISSUE 15 satellite): serves
# repeat roots of opted-in containers (Container(cache_root=True)) at
# zero compressions. Keys retain their field values (a SyncCommittee
# key holds its 512 pubkey bytes, ~25 KB), so the FIFO bound is small;
# the live working set is a handful of committees.
_CONTAINER_ROOT_CACHE: dict = {}
_CONTAINER_ROOT_CACHE_MAX = 32


class Container(SSZType):
    """A named, ordered set of typed fields. Subclass-free: built from a
    field spec, producing lightweight value objects (SSZValue).

    `cache_root=True` opts the container into the content-keyed root
    cache below (ISSUE 15 satellite): hash_tree_root builds a content
    tuple from the field values (immutable leaves / tuples of leaves /
    ChunkedSeq tokens) and serves repeats from the cache — ZERO SHA-256
    compressions for an unchanged value. Content keys make this safe
    under any mutation pattern (a changed value is a different key, the
    _cached_merkleize posture); values whose content cannot be cheaply
    keyed fall through to the normal walk. Used by SyncCommittee: the
    two 512-pubkey lists cost 1,028 compressions per root otherwise —
    the largest steady-slot line in the PR 11 census."""

    def __init__(self, name: str, fields: Sequence[tuple],
                 cache_root: bool = False):
        self.name = name
        self.fields = list(fields)  # [(name, SSZType), ...]
        self.fmap = dict(self.fields)
        self._cache_root = cache_root
        # field names whose values auto-wrap into a ChunkedSeq when a
        # big plain list is stored (List/Vector container fields)
        self._seq_fields = {
            fname: ftype
            for fname, ftype in self.fields
            if type(ftype) in (List, Vector)
        }

    def is_fixed_size(self):
        return all(t.is_fixed_size() for _, t in self.fields)

    def fixed_size(self):
        return sum(t.fixed_size() for _, t in self.fields)

    def serialize(self, value) -> bytes:
        fixed_parts = []
        var_parts = []
        for fname, ftype in self.fields:
            v = getattr(value, fname)
            if ftype.is_fixed_size():
                fixed_parts.append(ftype.serialize(v))
                var_parts.append(None)
            else:
                fixed_parts.append(None)
                var_parts.append(ftype.serialize(v))
        fixed_len = sum(
            len(p) if p is not None else OFFSET_SIZE for p in fixed_parts
        )
        out = bytearray()
        offset = fixed_len
        for p, v in zip(fixed_parts, var_parts):
            if p is not None:
                out += p
            else:
                out += offset.to_bytes(OFFSET_SIZE, "little")
                offset += len(v)
        for v in var_parts:
            if v is not None:
                out += v
        return bytes(out)

    def deserialize(self, data: bytes):
        pos = 0
        offsets = []
        fixed_vals = {}
        for fname, ftype in self.fields:
            if ftype.is_fixed_size():
                size = ftype.fixed_size()
                if pos + size > len(data):
                    raise ValueError("container truncated")
                fixed_vals[fname] = ftype.deserialize(data[pos : pos + size])
                pos += size
            else:
                offsets.append(
                    (fname, int.from_bytes(data[pos : pos + 4], "little"))
                )
                pos += OFFSET_SIZE
        if offsets:
            # the first variable offset must land exactly at the end of
            # the fixed part — anything else is a non-canonical encoding
            if offsets[0][1] != pos:
                raise ValueError("first offset != fixed-part length")
        elif pos != len(data):
            raise ValueError("trailing bytes after fixed container")
        offsets.append((None, len(data)))
        for i in range(len(offsets) - 1):
            fname, start = offsets[i]
            _, end = offsets[i + 1]
            ftype = self.fmap[fname]
            if end < start or start > len(data):
                raise ValueError("offsets not monotonic / out of bounds")
            fixed_vals[fname] = ftype.deserialize(data[start:end])
        return SSZValue(self, fixed_vals)

    def _content_key(self, value):
        """Hashable content tuple for the root cache, or None when a
        field value is not cheaply keyable. Building the key costs
        C-speed tuple/bytes hashing — zero SHA-256 compressions (the
        census cache_key column pins that)."""
        parts = [self.name]
        for fname, _ftype in self.fields:
            v = object.__getattribute__(value, "_vals")[fname]
            if isinstance(v, (bytes, int, bool)):
                parts.append(v)
            elif isinstance(v, ChunkedSeq):
                # equal tokens imply identical content (CoW contract)
                parts.append(("cs", v._token))
            elif type(v) is list:
                # EVERY element must be an immutable leaf — one
                # identity-hashed mutable element anywhere would make
                # the key blind to its in-place mutation
                if not all(isinstance(x, (bytes, int, bool)) for x in v):
                    return None
                parts.append(tuple(v))
            else:
                return None
        return tuple(parts)

    def hash_tree_root(self, value) -> bytes:
        if self._cache_root:
            key = self._content_key(value)
            if key is not None:
                c = CENSUS
                root = _CONTAINER_ROOT_CACHE.get(key)
                if root is not None:
                    if c is not None:
                        c.cache_event("container", True)
                    return root
                if c is not None:
                    c.cache_event("container", False)
                root = self._hash_tree_root(value)
                if len(_CONTAINER_ROOT_CACHE) >= _CONTAINER_ROOT_CACHE_MAX:
                    _CONTAINER_ROOT_CACHE.pop(
                        next(iter(_CONTAINER_ROOT_CACHE))
                    )
                _CONTAINER_ROOT_CACHE[key] = root
                return root
        return self._hash_tree_root(value)

    def _hash_tree_root(self, value) -> bytes:
        c = CENSUS
        if c is None or not c.wants_fields():
            # nested containers keep the enclosing top-level field label:
            # only the OUTERMOST container of a measured root pays the
            # per-field bookkeeping (the 250k validator containers don't)
            roots = [
                ftype.hash_tree_root(getattr(value, fname))
                for fname, ftype in self.fields
            ]
            return merkleize(roots)
        roots = []
        for fname, ftype in self.fields:
            c.begin_field(fname)
            try:
                roots.append(ftype.hash_tree_root(getattr(value, fname)))
            finally:
                c.end_field()
        return merkleize(roots)

    def default(self):
        return SSZValue(
            self, {fname: ftype.default() for fname, ftype in self.fields}
        )

    def make(self, **kwargs):
        vals = {}
        for fname, ftype in self.fields:
            vals[fname] = kwargs.pop(fname) if fname in kwargs else ftype.default()
        if kwargs:
            raise TypeError(f"unknown fields {list(kwargs)} for {self.name}")
        return SSZValue(self, vals)


class SSZValue:
    """A container instance: attribute access + copy-on-write updates."""

    __slots__ = ("_type", "_vals")

    def __init__(self, ctype: Container, vals: dict):
        for fname, ftype in ctype._seq_fields.items():
            v = vals.get(fname)
            if type(v) is list and len(v) > _WRAP_THRESHOLD:
                vals[fname] = ChunkedSeq(v, elem=ftype.elem)
        object.__setattr__(self, "_type", ctype)
        object.__setattr__(self, "_vals", vals)

    def __getattr__(self, name):
        try:
            return object.__getattribute__(self, "_vals")[name]
        except KeyError:
            raise AttributeError(name) from None

    def __setattr__(self, name, value):
        vals = object.__getattribute__(self, "_vals")
        if name not in vals:
            # a typo'd field must stay an AttributeError even on a
            # frozen element — check BEFORE the sanitizer guard
            raise AttributeError(f"no field {name}")
        if SANITIZER is not None:
            SANITIZER.on_container_write(self, name)
        if type(value) is list and len(value) > _WRAP_THRESHOLD:
            ftype = object.__getattribute__(self, "_type")._seq_fields.get(name)
            if ftype is not None:
                value = ChunkedSeq(value, elem=ftype.elem)
        vals[name] = value

    def copy(self) -> "SSZValue":
        """Type-driven fast copy: leaf values (ints, bytes, bools) are
        immutable and SHARED, nested containers are rebuilt (bounded
        count), and big List/Vector values are ChunkedSeq spines shared
        copy-on-write — O(spine), not O(n), the structural sharing the
        reference gets from milhouse. Semantically a deep copy: scalar
        writes go through __setitem__ (chunk CoW) and in-place container
        element mutation through seq_get_mut (chunk + element CoW), so
        no write on either side is ever visible to the other."""
        return _fast_copy_container(self._type, self)

    def __deepcopy__(self, memo) -> "SSZValue":
        return self.copy()

    def serialize(self) -> bytes:
        return self._type.serialize(self)

    def hash_tree_root(self) -> bytes:
        return self._type.hash_tree_root(self)

    def __eq__(self, other):
        return (
            isinstance(other, SSZValue)
            and self._type is other._type
            and self.serialize() == other.serialize()
        )

    def __repr__(self):
        return f"<{self._type.name} {self._vals}>"


def _fast_copy_value(ftype: SSZType, value):
    """Copy `value` of SSZ type `ftype`: immutable leaves shared,
    ChunkedSeq spines shared copy-on-write, plain lists rebuilt."""
    if isinstance(ftype, Container):
        return _fast_copy_container(ftype, value)
    if isinstance(ftype, (List, Vector)):
        if isinstance(value, ChunkedSeq):
            return value.copy()  # O(spine) structural sharing
        elem = ftype.elem
        if isinstance(elem, (Container, List, Vector, Bitlist, Bitvector)):
            copied = [_fast_copy_value(elem, v) for v in value]
        else:
            copied = list(value)  # scalar/bytes elements are immutable
        if len(copied) > _WRAP_THRESHOLD:
            # promote: the NEXT copy of this value is O(spine)
            return ChunkedSeq(copied, elem=elem)
        return copied
    if isinstance(ftype, (Bitlist, Bitvector)):
        return list(value)
    return value  # int / bytes / bool


def _fast_copy_container(ctype: Container, value) -> "SSZValue":
    return SSZValue(
        ctype,
        {
            fname: _fast_copy_value(ftype, getattr(value, fname))
            for fname, ftype in ctype.fields
        },
    )


# common aliases
uint8 = Uint(8)
uint16 = Uint(16)
uint32 = Uint(32)
uint64 = Uint(64)
uint256 = Uint(256)
boolean = Boolean()
Bytes4 = ByteVector(4)
Bytes20 = ByteVector(20)
Bytes32 = ByteVector(32)
Bytes48 = ByteVector(48)
Bytes96 = ByteVector(96)


def _auto_install_sanitizer() -> None:
    # LH_SANITIZE=1 turns the runtime contract checks on process-wide
    # (tier-1 re-runs test_ssz/test_epoch_columnar under it). Deferred
    # import: common/sanitize touches this module only inside install().
    import os as _os

    if _os.environ.get("LH_SANITIZE", "") == "1":
        from ..common import sanitize as _sanitize

        _sanitize.install_from_env()


_auto_install_sanitizer()
