"""Electra state-transition extensions (EIP-7251 MaxEB, EIP-7002
execution-layer withdrawals, EIP-6110 EL deposits, EIP-7549 committee
bits) — the reference's per_block_processing/per_epoch_processing
electra variants (consensus/state_processing single_pass.rs electra
arms, process_operations.rs:703 request handling).

State surface lives in `state.electra` (ElectraStateExtras); every
function here is gated by `spec.electra_enabled(epoch)` at the call
sites in state_transition.py.
"""

from __future__ import annotations

import numpy as np

from .spec import FAR_FUTURE_EPOCH, ChainSpec
from . import types as T
from .ssz import seq_get_mut

COMPOUNDING_WITHDRAWAL_PREFIX = b"\x02"
UNSET_DEPOSIT_REQUESTS_START_INDEX = 2**64 - 1
ETH1_WITHDRAWAL_PREFIX = b"\x01"
FULL_EXIT_REQUEST_AMOUNT = 0


# ---------------------------------------------------------------- creds


def has_compounding_withdrawal_credential(v) -> bool:
    return bytes(v.withdrawal_credentials)[:1] == COMPOUNDING_WITHDRAWAL_PREFIX


def has_execution_withdrawal_credential(v) -> bool:
    prefix = bytes(v.withdrawal_credentials)[:1]
    return prefix in (ETH1_WITHDRAWAL_PREFIX, COMPOUNDING_WITHDRAWAL_PREFIX)


def get_max_effective_balance(spec: ChainSpec, v) -> int:
    """Per-validator cap: 2048 ETH for compounding creds, 32 otherwise."""
    if has_compounding_withdrawal_credential(v):
        return spec.max_effective_balance_electra
    return spec.min_activation_balance


# ---------------------------------------------------------------- churn


def get_balance_churn_limit(
    spec: ChainSpec, state, total_active: int = None
) -> int:
    """`total_active` short-circuits the registry scan when the caller
    (the columnar epoch pass) already holds the current-epoch active
    balance — token-keyed caches miss on every registry mutation, so
    per-ejection rescans would be O(n) each."""
    from . import state_transition as st

    if total_active is None:
        total_active = st.get_total_active_balance(spec, state)
    limit = max(
        spec.min_per_epoch_churn_limit_electra,
        total_active // spec.churn_limit_quotient,
    )
    return limit - limit % spec.effective_balance_increment


def get_activation_exit_churn_limit(
    spec: ChainSpec, state, total_active: int = None
) -> int:
    return min(
        spec.max_per_epoch_activation_exit_churn_limit,
        get_balance_churn_limit(spec, state, total_active=total_active),
    )


def get_consolidation_churn_limit(spec: ChainSpec, state) -> int:
    return get_balance_churn_limit(spec, state) - get_activation_exit_churn_limit(
        spec, state
    )


def compute_exit_epoch_and_update_churn(
    spec: ChainSpec, state, exit_balance: int, per_epoch_churn: int = None
) -> int:
    """Balance-denominated exit queue (EIP-7251 replaces the per-
    validator churn with gwei churn)."""
    from . import state_transition as st

    ex = state.electra
    earliest = max(
        ex.earliest_exit_epoch,
        st.get_current_epoch(spec, state) + 1 + spec.max_seed_lookahead,
    )
    if per_epoch_churn is None:
        per_epoch_churn = get_activation_exit_churn_limit(spec, state)
    if ex.earliest_exit_epoch < earliest:
        balance_to_consume = per_epoch_churn
    else:
        balance_to_consume = ex.exit_balance_to_consume
    if exit_balance > balance_to_consume:
        additional = exit_balance - balance_to_consume
        epochs = (additional + per_epoch_churn - 1) // per_epoch_churn
        earliest += epochs
        balance_to_consume += epochs * per_epoch_churn
    ex.exit_balance_to_consume = balance_to_consume - exit_balance
    ex.earliest_exit_epoch = earliest
    return earliest


def compute_consolidation_epoch_and_update_churn(
    spec: ChainSpec, state, consolidation_balance: int
) -> int:
    from . import state_transition as st

    ex = state.electra
    earliest = max(
        ex.earliest_consolidation_epoch,
        st.get_current_epoch(spec, state) + 1 + spec.max_seed_lookahead,
    )
    # floor of one increment: on a network whose balance churn sits at
    # the electra minimum the spec formula yields 0 (all churn goes to
    # activations/exits) and consolidations would divide by zero; one
    # increment per epoch keeps them merely slow
    per_epoch = max(
        get_consolidation_churn_limit(spec, state),
        spec.effective_balance_increment,
    )
    if ex.earliest_consolidation_epoch < earliest:
        balance_to_consume = per_epoch
    else:
        balance_to_consume = ex.consolidation_balance_to_consume
    if consolidation_balance > balance_to_consume:
        additional = consolidation_balance - balance_to_consume
        epochs = (additional + per_epoch - 1) // per_epoch
        earliest += epochs
        balance_to_consume += epochs * per_epoch
    ex.consolidation_balance_to_consume = (
        balance_to_consume - consolidation_balance
    )
    ex.earliest_consolidation_epoch = earliest
    return earliest


def initiate_validator_exit(
    spec: ChainSpec, state, index: int, per_epoch_churn: int = None
) -> None:
    """Electra initiate_validator_exit: balance-churned queue."""
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    exit_epoch = compute_exit_epoch_and_update_churn(
        spec, state, v.effective_balance, per_epoch_churn=per_epoch_churn
    )
    v = seq_get_mut(state.validators, index)  # CoW: never leak to copies
    v.exit_epoch = exit_epoch
    v.withdrawable_epoch = (
        exit_epoch + spec.min_validator_withdrawability_delay
    )


def get_pending_balance_to_withdraw(state, index: int) -> int:
    return sum(
        int(w.amount)
        for w in state.electra.pending_partial_withdrawals
        if int(w.validator_index) == index
    )


def switch_to_compounding_validator(spec: ChainSpec, state, index: int) -> None:
    v = seq_get_mut(state.validators, index)
    v.withdrawal_credentials = (
        COMPOUNDING_WITHDRAWAL_PREFIX + bytes(v.withdrawal_credentials)[1:]
    )
    queue_excess_active_balance(spec, state, index)


def queue_excess_active_balance(spec: ChainSpec, state, index: int) -> None:
    from . import state_transition as st

    balance = state.balances[index]
    if balance > spec.min_activation_balance:
        excess = balance - spec.min_activation_balance
        state.balances[index] = spec.min_activation_balance
        v = state.validators[index]
        state.electra.pending_deposits.append(
            T.PendingDeposit.make(
                pubkey=bytes(v.pubkey),
                withdrawal_credentials=bytes(v.withdrawal_credentials),
                amount=excess,
                signature=b"\x00" * 96,  # G2 infinity marker (skip sig)
                # GENESIS_SLOT, like the spec's queue_excess_active_balance:
                # internally-queued balance is exempt from the finalization
                # and eth1-bridge-ordering guards in process_pending_deposits
                slot=0,
            )
        )


# --------------------------------------------------------- block requests


def process_deposit_request(spec: ChainSpec, state, request) -> None:
    """EIP-6110: EL deposit receipts enter the pending queue."""
    ex = state.electra
    if ex.deposit_requests_start_index == UNSET_DEPOSIT_REQUESTS_START_INDEX:
        ex.deposit_requests_start_index = int(request.index)
    ex.pending_deposits.append(
        T.PendingDeposit.make(
            pubkey=bytes(request.pubkey),
            withdrawal_credentials=bytes(request.withdrawal_credentials),
            amount=int(request.amount),
            signature=bytes(request.signature),
            slot=int(state.slot),
        )
    )


def process_withdrawal_request(spec: ChainSpec, state, request, ctx) -> None:
    """EIP-7002: EL-triggered exits / partial withdrawals. Invalid
    requests are no-ops (the EL cannot be rolled back)."""
    from . import state_transition as st

    amount = int(request.amount)
    index = ctx.pubkey_index(bytes(request.validator_pubkey))
    if index is None:
        return
    v = state.validators[index]
    if not has_execution_withdrawal_credential(v):
        return
    # request must come from the credentialed address
    if bytes(v.withdrawal_credentials)[12:] != bytes(request.source_address):
        return
    cur = st.get_current_epoch(spec, state)
    if not st.is_active_validator(v, cur) or v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    if cur < v.activation_epoch + spec.shard_committee_period:
        return
    pending = get_pending_balance_to_withdraw(state, index)
    if amount == FULL_EXIT_REQUEST_AMOUNT:
        if pending == 0:
            initiate_validator_exit(spec, state, index)
        return
    # partial: compounding validators with excess over 32 ETH only
    has_sufficient = (
        v.effective_balance >= spec.min_activation_balance
        and state.balances[index] > spec.min_activation_balance + pending
    )
    if not (has_compounding_withdrawal_credential(v) and has_sufficient):
        return
    to_withdraw = min(
        state.balances[index] - spec.min_activation_balance - pending,
        amount,
    )
    withdrawable = compute_exit_epoch_and_update_churn(spec, state, to_withdraw)
    state.electra.pending_partial_withdrawals.append(
        T.PendingPartialWithdrawal.make(
            validator_index=index,
            amount=to_withdraw,
            withdrawable_epoch=withdrawable
            + spec.min_validator_withdrawability_delay,
        )
    )


def process_consolidation_request(spec: ChainSpec, state, request, ctx) -> None:
    from . import state_transition as st

    src_pk = bytes(request.source_pubkey)
    tgt_pk = bytes(request.target_pubkey)
    source_index = ctx.pubkey_index(src_pk)
    if source_index is None:
        return
    # self-consolidation = switch to compounding credentials
    if src_pk == tgt_pk:
        v = state.validators[source_index]
        cur = st.get_current_epoch(spec, state)
        if (
            bytes(v.withdrawal_credentials)[:1] == ETH1_WITHDRAWAL_PREFIX
            and bytes(v.withdrawal_credentials)[12:]
            == bytes(request.source_address)
            # spec is_valid_switch_to_compounding_request: active, no
            # exit initiated — an exiting validator flipping to 0x02
            # would strand its excess balance
            and st.is_active_validator(v, cur)
            and v.exit_epoch == FAR_FUTURE_EPOCH
        ):
            switch_to_compounding_validator(spec, state, source_index)
        return
    target_index = ctx.pubkey_index(tgt_pk)
    if target_index is None:
        return
    source = state.validators[source_index]
    target = state.validators[target_index]
    cur = st.get_current_epoch(spec, state)
    if not (
        st.is_active_validator(source, cur)
        and st.is_active_validator(target, cur)
    ):
        return
    if (
        source.exit_epoch != FAR_FUTURE_EPOCH
        or target.exit_epoch != FAR_FUTURE_EPOCH
    ):
        return
    if bytes(source.withdrawal_credentials)[12:] != bytes(
        request.source_address
    ):
        return
    if not has_execution_withdrawal_credential(source):
        return
    if not has_compounding_withdrawal_credential(target):
        return
    if cur < source.activation_epoch + spec.shard_committee_period:
        return
    if get_pending_balance_to_withdraw(state, source_index) > 0:
        return
    exit_epoch = compute_consolidation_epoch_and_update_churn(
        spec, state, source.effective_balance
    )
    source = seq_get_mut(state.validators, source_index)
    source.exit_epoch = exit_epoch
    source.withdrawable_epoch = (
        exit_epoch + spec.min_validator_withdrawability_delay
    )
    state.electra.pending_consolidations.append(
        T.PendingConsolidation.make(
            source_index=source_index, target_index=target_index
        )
    )


def process_execution_requests(spec: ChainSpec, state, requests, ctx) -> None:
    """The per-block entry: deposits, then withdrawals, then
    consolidations (process_operations electra tail)."""
    for r in requests.deposits:
        process_deposit_request(spec, state, r)
    for r in requests.withdrawals:
        process_withdrawal_request(spec, state, r, ctx)
    for r in requests.consolidations:
        process_consolidation_request(spec, state, r, ctx)


# ------------------------------------------------------------ epoch passes


def process_pending_deposits(
    spec: ChainSpec, state, ctx=None, total_active: int = None
) -> None:
    """Apply queued deposits under the gwei activation churn — spec-exact
    electra branches (single_pass.rs electra pending-deposit arm):

    - eth1-bridge ordering guard: post-genesis deposit requests wait
      until every legacy eth1 deposit has been applied;
    - only finalized deposits apply (slot <= finalized start slot);
    - deposits to a WITHDRAWN validator credit immediately without
      consuming churn (the balance can never activate);
    - deposits to an EXITING validator are postponed past its
      withdrawable epoch (re-queued at the tail);
    - otherwise churn-limited, banking unused churn only when churn was
      the stopper."""
    from . import state_transition as st

    ex = state.electra
    next_epoch = st.get_current_epoch(spec, state) + 1
    available = (
        get_activation_exit_churn_limit(spec, state, total_active=total_active)
        + ex.deposit_balance_to_consume
    )
    finalized_slot = st.compute_start_slot_at_epoch(
        spec, int(state.finalized_checkpoint.epoch)
    )
    ctx = ctx or st.BlockContext(spec, state)
    processed_amount = 0
    next_index = 0
    churn_limited = False
    postponed = []
    remaining = list(ex.pending_deposits)
    for dep in remaining:
        # deposit requests wait for the legacy eth1 bridge to drain
        if (
            int(dep.slot) > 0
            and int(state.eth1_deposit_index) < ex.deposit_requests_start_index
        ):
            break
        # only deposits the chain has finalized past are applyable
        if int(dep.slot) > finalized_slot:
            break
        if next_index >= spec.max_pending_deposits_per_epoch:
            break

        index = ctx.pubkey_index(bytes(dep.pubkey))
        is_exited = False
        is_withdrawn = False
        if index is not None:
            v = state.validators[index]
            is_exited = v.exit_epoch < FAR_FUTURE_EPOCH
            is_withdrawn = v.withdrawable_epoch < next_epoch

        if is_withdrawn:
            # balance can never activate: credit without consuming churn
            _apply_pending_deposit(spec, state, dep, ctx)
        elif is_exited:
            postponed.append(dep)
        else:
            if processed_amount + int(dep.amount) > available:
                churn_limited = True
                break
            processed_amount += int(dep.amount)
            _apply_pending_deposit(spec, state, dep, ctx)
        next_index += 1
    ex.pending_deposits = remaining[next_index:] + postponed
    # unused churn banks ONLY when churn was the stopper — a deposit
    # waiting on finalization must not accumulate multi-epoch credit
    # that later applies a burst above the per-epoch limit
    if churn_limited:
        ex.deposit_balance_to_consume = available - processed_amount
    else:
        ex.deposit_balance_to_consume = 0


def _apply_pending_deposit(spec: ChainSpec, state, dep, ctx=None) -> None:
    from . import state_transition as st

    ctx = ctx or st.BlockContext(spec, state)
    index = ctx.pubkey_index(bytes(dep.pubkey))
    if index is not None:
        st.increase_balance(state, index, int(dep.amount))
        return
    # zero signature marks an internally-queued balance (excess from
    # compounding switch) — never a NEW validator
    if bytes(dep.signature) == b"\x00" * 96:
        return
    st.apply_deposit(
        spec,
        state,
        bytes(dep.pubkey),
        bytes(dep.withdrawal_credentials),
        int(dep.amount),
        bytes(dep.signature),
        ctx=ctx,
    )


def process_pending_consolidations(spec: ChainSpec, state) -> None:
    from . import state_transition as st

    ex = state.electra
    cur = st.get_current_epoch(spec, state)
    done = 0
    for pc in ex.pending_consolidations:
        source = state.validators[int(pc.source_index)]
        if source.slashed:
            done += 1
            continue
        if source.withdrawable_epoch > cur:
            break
        # move the source's remaining MIN_ACTIVATION-capped balance
        balance = min(
            state.balances[int(pc.source_index)],
            spec.min_activation_balance,
        )
        st.decrease_balance(state, int(pc.source_index), balance)
        st.increase_balance(state, int(pc.target_index), balance)
        done += 1
    if done:
        ex.pending_consolidations = list(ex.pending_consolidations)[done:]


def process_effective_balance_updates(spec: ChainSpec, state, cols=None) -> None:
    """Electra variant: per-validator cap (compounding -> 2048 ETH);
    the masked hysteresis decision + writeback are shared with the
    phase0 arm."""
    from . import state_transition as st

    cols = cols or st.EpochColumns(state)
    cap = np.where(
        cols.compounding,
        np.int64(spec.max_effective_balance_electra),
        np.int64(spec.min_activation_balance),
    )
    st.apply_effective_balance_hysteresis(spec, state, cols, cap)


def process_registry_updates(
    spec: ChainSpec, state, cols=None, total_active: int = None
) -> None:
    """Electra variant: eligibility at MIN_ACTIVATION_BALANCE; the
    activation queue is churn-free (the gwei churn already ran at the
    pending-deposit stage). Mask scans over the epoch columns; the
    balance-churned exit queue runs per ejected index with the churn
    limit resolved once."""
    from . import state_transition as st

    cols = cols or st.EpochColumns(state)
    cur = st.get_current_epoch(spec, state)
    clamp = st._EPOCH_CLAMP
    elig_idx = np.nonzero(
        (cols.eligibility == clamp)
        & (cols.eff >= spec.min_activation_balance)
    )[0]
    for i in elig_idx:
        seq_get_mut(state.validators, int(i)).activation_eligibility_epoch = (
            cur + 1
        )
    active_cur = (cols.activation <= cur) & (cur < cols.exit_epoch)
    eject_idx = np.nonzero(
        active_cur
        & (cols.eff <= spec.ejection_balance)
        & (cols.exit_epoch == clamp)
    )[0]
    if len(eject_idx):
        per_epoch_churn = get_activation_exit_churn_limit(
            spec, state, total_active=total_active
        )
        for i in eject_idx:
            initiate_validator_exit(
                spec, state, int(i), per_epoch_churn=per_epoch_churn
            )
    # re-read eligibility after the eligibility writes above (dirty
    # chunks only): the one-pass spec loop sees its own eligibility
    # updates when checking activation. Ejections never touch
    # eligibility, so they don't force a rebuild.
    elig = (
        st.EpochColumns(state).eligibility
        if len(elig_idx)
        else cols.eligibility
    )
    act_idx = np.nonzero(
        (cols.activation == clamp)
        & (elig <= int(state.finalized_checkpoint.epoch))
    )[0]
    for i in act_idx:
        seq_get_mut(state.validators, int(i)).activation_epoch = (
            cur + 1 + spec.max_seed_lookahead
        )


# ------------------------------------------------------------ withdrawals


def get_expected_withdrawals(spec: ChainSpec, state) -> tuple:
    """Electra variant: pending partials drain first (bounded per
    sweep), then the regular sweep with per-validator caps. Returns
    (withdrawals, partials_consumed)."""
    from . import state_transition as st

    epoch = st.get_current_epoch(spec, state)
    withdrawal_index = state.next_withdrawal_index
    withdrawals = []
    consumed = 0
    for w in state.electra.pending_partial_withdrawals:
        if (
            int(w.withdrawable_epoch) > epoch
            or len(withdrawals)
            == spec.max_pending_partials_per_withdrawals_sweep
        ):
            break
        idx = int(w.validator_index)
        v = state.validators[idx]
        ok = (
            v.exit_epoch == FAR_FUTURE_EPOCH
            and v.effective_balance >= spec.min_activation_balance
            and state.balances[idx] > spec.min_activation_balance
        )
        if ok:
            amount = min(
                state.balances[idx] - spec.min_activation_balance,
                int(w.amount),
            )
            withdrawals.append(
                T.Withdrawal.make(
                    index=withdrawal_index,
                    validator_index=idx,
                    address=bytes(v.withdrawal_credentials)[12:],
                    amount=amount,
                )
            )
            withdrawal_index += 1
        consumed += 1
    # regular sweep on top
    bound = min(
        len(state.validators), spec.preset.max_validators_per_withdrawals_sweep
    )
    vi = state.next_withdrawal_validator_index
    for _ in range(bound):
        if len(withdrawals) >= spec.preset.max_withdrawals_per_payload:
            break
        v = state.validators[vi]
        balance = state.balances[vi]
        # account for partials already in this set
        already = sum(
            int(w.amount) for w in withdrawals if int(w.validator_index) == vi
        )
        balance -= min(balance, already)
        cap = get_max_effective_balance(spec, v)
        fully = (
            has_execution_withdrawal_credential(v)  # 0x01 OR 0x02
            and v.withdrawable_epoch <= epoch
            and balance > 0
        )
        if fully:
            withdrawals.append(
                T.Withdrawal.make(
                    index=withdrawal_index,
                    validator_index=vi,
                    address=bytes(v.withdrawal_credentials)[12:],
                    amount=balance,
                )
            )
            withdrawal_index += 1
        elif (
            has_execution_withdrawal_credential(v)
            and v.effective_balance == cap
            and balance > cap
        ):
            withdrawals.append(
                T.Withdrawal.make(
                    index=withdrawal_index,
                    validator_index=vi,
                    address=bytes(v.withdrawal_credentials)[12:],
                    amount=balance - cap,
                )
            )
            withdrawal_index += 1
        vi = (vi + 1) % len(state.validators)
    return withdrawals, consumed


# ------------------------------------------------------------- fork upgrade


def upgrade_state(spec: ChainSpec, state) -> None:
    """upgrade_to_electra: seed the electra sub-state at the fork
    boundary (or electra genesis) — the balance churn must inherit the
    pre-fork exit queue, not jump it."""
    from . import state_transition as st

    ex = state.electra
    ex.deposit_requests_start_index = UNSET_DEPOSIT_REQUESTS_START_INDEX
    exit_epochs = [
        int(v.exit_epoch)
        for v in state.validators
        if v.exit_epoch != FAR_FUTURE_EPOCH
    ]
    ex.earliest_exit_epoch = max(
        exit_epochs + [st.get_current_epoch(spec, state)]
    ) + 1
    ex.earliest_consolidation_epoch = (
        st.get_current_epoch(spec, state) + 1 + spec.max_seed_lookahead
    )
    ex.exit_balance_to_consume = get_activation_exit_churn_limit(spec, state)
    ex.consolidation_balance_to_consume = max(
        get_consolidation_churn_limit(spec, state),
        spec.effective_balance_increment,
    )
