"""PeerDAS data columns: the DataColumnSidecar type, column
construction from blobs (kzg_utils blob->column role), custody
assignment, and gossip verification
(reference consensus/types data_column_sidecar.rs,
beacon_chain/src/data_column_verification.rs, kzg_utils.rs,
network custody assignment in sync/network_context/custody.rs).

The blob matrix view: row b = blob b's CELLS_PER_EXT_BLOB cells;
COLUMN j = cell j of every blob. A node custodies a deterministic
pseudo-random set of columns derived from its node id and serves/
verifies only those; sampling queries SAMPLES_PER_SLOT random columns
per slot to probabilistically confirm availability.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

from ..crypto.kzg.peerdas import CELLS_PER_EXT_BLOB
from .merkle_proof import merkle_branch, verify_merkle_branch, _next_pow2
from . import types as T
from .ssz import ByteList, Bytes32, Bytes48, Container, List, Vector, uint64

NUMBER_OF_COLUMNS = CELLS_PER_EXT_BLOB  # 128
DATA_COLUMN_SIDECAR_SUBNET_COUNT = 128
CUSTODY_REQUIREMENT = 4
SAMPLES_PER_SLOT = 8
MAX_CELL_BYTES = 64 * 32  # FIELD_ELEMENTS_PER_CELL * 32

# commitments-LIST inclusion proof: the body has 12 fields -> 16
# leaves, depth 4 (data_column_sidecar.rs
# KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH)
_BODY_FIELDS = [name for name, _ in T.BeaconBlockBody.fields]
_COMMITMENTS_FIELD = _BODY_FIELDS.index("blob_kzg_commitments")
_BODY_WIDTH = _next_pow2(len(_BODY_FIELDS))
KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH = _BODY_WIDTH.bit_length() - 1

# Cell as ByteList so shrunk test geometries (smaller cells) round-trip
# through the same container; mainnet cells are exactly MAX_CELL_BYTES.
Cell = ByteList(MAX_CELL_BYTES)

DataColumnSidecar = Container(
    "DataColumnSidecar",
    [
        ("index", uint64),
        # limits = max_blob_commitments_per_block (spec preset)
        ("column", List(Cell, 4096)),
        ("kzg_commitments", List(Bytes48, 4096)),
        ("kzg_proofs", List(Bytes48, 4096)),
        ("signed_block_header", T.SignedBeaconBlockHeader),
        (
            "kzg_commitments_inclusion_proof",
            Vector(Bytes32, KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH),
        ),
    ],
)

DataColumnIdentifier = Container(
    "DataColumnIdentifier", [("block_root", Bytes32), ("index", uint64)]
)

DataColumnsByRangeRequest = Container(
    "DataColumnsByRangeRequest",
    [
        ("start_slot", uint64),
        ("count", uint64),
        ("columns", List(uint64, NUMBER_OF_COLUMNS)),
    ],
)


class DataColumnError(Exception):
    pass


# ------------------------------------------------------- construction


def compute_commitments_inclusion_proof(body) -> list:
    """Branch proving the blob_kzg_commitments LIST against body root."""
    roots = [
        ftype.hash_tree_root(getattr(body, fname))
        for fname, ftype in T.BeaconBlockBody.fields
    ]
    return merkle_branch(roots, _BODY_WIDTH, _COMMITMENTS_FIELD)


def verify_commitments_inclusion_proof(sidecar) -> bool:
    commitments_type = dict(T.BeaconBlockBody.fields)["blob_kzg_commitments"]
    leaf = commitments_type.hash_tree_root(list(sidecar.kzg_commitments))
    return verify_merkle_branch(
        leaf,
        [bytes(b) for b in sidecar.kzg_commitments_inclusion_proof],
        KZG_COMMITMENTS_INCLUSION_PROOF_DEPTH,
        _COMMITMENTS_FIELD,
        bytes(sidecar.signed_block_header.message.body_root),
    )


def build_sidecars(
    signed_block,
    cell_matrix: Sequence[Sequence[bytes]],
    proof_matrix: Sequence[Sequence[bytes]],
    n_columns: int = NUMBER_OF_COLUMNS,
) -> list:
    """kzg_utils blob->column sidecar construction: `cell_matrix[b][j]`
    is blob b's cell j as bytes; column j gathers that cell from every
    blob, with the full commitment list + inclusion proof repeated per
    sidecar (data_column_sidecar.rs build path)."""
    block = signed_block.message
    commitments = [bytes(c) for c in block.body.blob_kzg_commitments]
    if len(cell_matrix) != len(commitments):
        raise DataColumnError("one cell row per commitment required")
    header = T.SignedBeaconBlockHeader.make(
        message=T.BeaconBlockHeader.make(
            slot=block.slot,
            proposer_index=block.proposer_index,
            parent_root=bytes(block.parent_root),
            state_root=bytes(block.state_root),
            body_root=block.body.hash_tree_root(),
        ),
        signature=bytes(signed_block.signature),
    )
    inclusion = compute_commitments_inclusion_proof(block.body)
    out = []
    for j in range(n_columns):
        out.append(
            DataColumnSidecar.make(
                index=j,
                column=[bytes(row[j]) for row in cell_matrix],
                kzg_commitments=commitments,
                kzg_proofs=[bytes(row[j]) for row in proof_matrix],
                signed_block_header=header,
                kzg_commitments_inclusion_proof=inclusion,
            )
        )
    return out


# ------------------------------------------------------------ custody


def pseudo_random_selection(seed: bytes, k: int, space: int) -> list:
    """k distinct hash-derived values in [0, space) — the shared
    derivation for custody subnets AND per-block sample columns."""
    out, i = [], 0
    while len(out) < k:
        h = hashlib.sha256(bytes(seed) + i.to_bytes(8, "little")).digest()
        v = int.from_bytes(h[:8], "little") % space
        if v not in out:
            out.append(v)
        i += 1
    return out


def get_custody_columns(node_id: bytes, custody_subnet_count: int = CUSTODY_REQUIREMENT) -> list:
    """Deterministic pseudo-random custody assignment from the node id
    (the spec's get_custody_columns shape: hash-derived subnet ids,
    columns striped across subnets)."""
    if custody_subnet_count > DATA_COLUMN_SIDECAR_SUBNET_COUNT:
        raise DataColumnError("custody count exceeds subnet count")
    subnets = pseudo_random_selection(
        node_id, custody_subnet_count, DATA_COLUMN_SIDECAR_SUBNET_COUNT
    )
    per = NUMBER_OF_COLUMNS // DATA_COLUMN_SIDECAR_SUBNET_COUNT
    cols = []
    for sid in subnets:
        cols.extend(
            DATA_COLUMN_SIDECAR_SUBNET_COUNT * k + sid for k in range(per)
        )
    return sorted(cols)


def compute_subnet_for_column(index: int) -> int:
    return index % DATA_COLUMN_SIDECAR_SUBNET_COUNT


# ------------------------------------------------------- verification


class DataColumnVerifier:
    """Gossip-path verification (data_column_verification.rs):
    structural checks + inclusion proof + ONE batched cell-proof check
    per sidecar; header-signature verification rides the chain's
    block-header path, supplied as a callable."""

    def __init__(self, cell_context, verify_header_signature=None):
        self.ctx = cell_context
        self._verify_header = verify_header_signature or (lambda h: True)

    def verify_sidecar(self, sidecar) -> None:
        idx = int(sidecar.index)
        if idx >= NUMBER_OF_COLUMNS:
            raise DataColumnError("column index out of range")
        n = len(sidecar.column)
        if not (
            n == len(sidecar.kzg_commitments) == len(sidecar.kzg_proofs)
        ):
            raise DataColumnError("column/commitment/proof length mismatch")
        if n == 0:
            raise DataColumnError("empty column")
        if not verify_commitments_inclusion_proof(sidecar):
            raise DataColumnError("bad commitments inclusion proof")
        if not self._verify_header(sidecar.signed_block_header):
            raise DataColumnError("bad header signature")
        from ..crypto.bls import curve as C

        # everything below parses REMOTE bytes — any malformation must
        # surface as DataColumnError so callers' failover paths fire
        try:
            commitments = [
                C.g1_decompress(bytes(cm)) for cm in sidecar.kzg_commitments
            ]
            proofs = [C.g1_decompress(bytes(p)) for p in sidecar.kzg_proofs]
            cells = [
                self.ctx.cell_from_bytes(bytes(cell))
                for cell in sidecar.column
            ]
            ok = self.ctx.verify_cell_proof_batch(
                commitments, [idx] * n, cells, proofs
            )
        except DataColumnError:
            raise
        except Exception as e:  # noqa: BLE001 — remote-bytes boundary
            raise DataColumnError(f"malformed sidecar: {e}") from None
        if not ok:
            raise DataColumnError("cell proof batch failed")
