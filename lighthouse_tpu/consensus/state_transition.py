"""The spec state-transition function (consensus/state_processing analog).

Covers the reference's `per_slot_processing` (per_slot_processing.rs:28),
`per_block_processing` (per_block_processing.rs:100 + process_operations.rs),
and `per_epoch_processing` for the Altair+ participation-flag family —
the canonical fork shape of `consensus.types` (one Deneb-shaped container
set, SURVEY.md §2.2).

TPU-first design choice: epoch processing is the validator-set-sized
"big dimension" (SURVEY.md §5.7), so it runs as ONE vectorized pass over
numpy arrays mirroring the reference's fused
`per_epoch_processing/single_pass.rs` — flag tallies, justification,
inactivity, rewards/penalties, effective-balance hysteresis and slashing
penalties are all array expressions (batch-offloadable later), never
per-validator Python loops.

Signature policy mirrors the reference: the transition itself can run
with signature verification OFF (`verify_signatures=False`) while
`BlockSignatureVerifier` (consensus/signature_sets.py) collects every
set of the block for one TPU batch — block_signature_verifier.rs:127-138.
Randao reveal, deposit signatures and operation signatures each have an
individual check path for `verify_signatures=True`.
"""

from __future__ import annotations

import hashlib
import operator
import threading
import time
from contextlib import contextmanager
from typing import Iterable, Optional

import numpy as np

from ..common import metrics as _metrics
from ..common import tracing as _tracing
from ..ops import epoch as _epoch_ops
from ..ops import hash_costs as _hash_costs
from ..ops.lane import merkle as _merkle
from ..crypto import bls
from ..crypto.bls.keys import PublicKey, Signature, SignatureSet
from . import types as T
from .ssz import (
    seq_assign_array,
    seq_column,
    seq_columns,
    seq_get_mut,
    seq_token,
)
from .domains import compute_domain, compute_signing_root, get_domain
from .shuffling import compute_committee, compute_shuffled_index
from .spec import ChainSpec, FAR_FUTURE_EPOCH, GENESIS_EPOCH, GENESIS_SLOT

# --------------------------------------------------------- reward meter
# Thread-local accumulator for the PROPOSER-ROLE reward components of
# one block replay (the beacon-API /rewards/blocks decomposition,
# beacon_chain/src/beacon_block_reward.rs role). A raw balance delta
# conflates roles: a proposer who is also a non-participating sync
# member nets negative even though their proposer reward is positive.
_REWARD_METER = threading.local()


class BlockRewardMeter:
    """Collects proposer rewards while `metered()` is active."""

    def __init__(self):
        self.attestations = 0
        self.sync_aggregate = 0
        self.proposer_slashings = 0
        self.attester_slashings = 0

    def __enter__(self):
        _REWARD_METER.meter = self
        return self

    def __exit__(self, *exc):
        _REWARD_METER.meter = None

    @property
    def total(self) -> int:
        return (
            self.attestations
            + self.sync_aggregate
            + self.proposer_slashings
            + self.attester_slashings
        )


def _meter_add(component: str, amount: int) -> None:
    m = getattr(_REWARD_METER, "meter", None)
    if m is not None:
        setattr(m, component, getattr(m, component) + int(amount))

# Altair participation flags (participation_flags.rs analog)
TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2
PARTICIPATION_FLAG_WEIGHTS = [14, 26, 14]  # source, target, head
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64

INACTIVITY_SCORE_BIAS = 4
INACTIVITY_SCORE_RECOVERY_RATE = 16
# Bellatrix+ values (the canonical container set models the merged chain)
INACTIVITY_PENALTY_QUOTIENT = 2**24
MIN_SLASHING_PENALTY_QUOTIENT = 32
PROPORTIONAL_SLASHING_MULTIPLIER = 3

DEPOSIT_CONTRACT_TREE_DEPTH = 32


class BlockProcessingError(Exception):
    pass


def _hash(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


# ---------------------------------------------------------------- accessors


def compute_epoch_at_slot(spec: ChainSpec, slot: int) -> int:
    return slot // spec.preset.slots_per_epoch


def compute_start_slot_at_epoch(spec: ChainSpec, epoch: int) -> int:
    return epoch * spec.preset.slots_per_epoch


def get_current_epoch(spec: ChainSpec, state) -> int:
    return compute_epoch_at_slot(spec, state.slot)


def get_previous_epoch(spec: ChainSpec, state) -> int:
    cur = get_current_epoch(spec, state)
    return cur - 1 if cur > GENESIS_EPOCH else GENESIS_EPOCH


def is_active_validator(v, epoch: int) -> bool:
    return v.activation_epoch <= epoch < v.exit_epoch


# (validators content token, epoch) -> active index list. The active
# set at epoch E is fixed once the state is inside E (exits/activations
# only schedule E+1+lookahead), and the ChunkedSeq token is shared
# across state copies until a registry mutation — so one O(n) scan
# serves every committee/proposer/balance lookup of the epoch across
# all fork states with identical registries. Returned lists are
# READ-ONLY by contract.
_ACTIVE_CACHE: dict = {}
_ACTIVE_CACHE_MAX = 8
# (validators content token, epoch) -> total active balance (gwei)
_TAB_CACHE: dict = {}

# the CoW-spine caches' hit/miss series (PR 2 built the caches; the
# observability layer exports them — a miss on the active-set cache is
# an O(n) registry scan on the hot path). Children pre-resolved once:
# the cache-HIT fast path stays a dict get + one uncontended inc.
_M_EPOCH_CACHE = _metrics.counter(
    "state_epoch_cache_total",
    "Token-keyed epoch cache lookups by cache and result",
    labelnames=("cache", "result"),
)
_M_ACTIVE_HIT = _M_EPOCH_CACHE.labels(cache="active_set", result="hit")
_M_ACTIVE_MISS = _M_EPOCH_CACHE.labels(cache="active_set", result="miss")
_M_TAB_HIT = _M_EPOCH_CACHE.labels(
    cache="total_active_balance", result="hit"
)
_M_TAB_MISS = _M_EPOCH_CACHE.labels(
    cache="total_active_balance", result="miss"
)


def get_active_validator_indices(state, epoch: int) -> list:
    tok = seq_token(state.validators)
    if tok is not None:
        hit = _ACTIVE_CACHE.get((tok, epoch))
        if hit is not None:
            _M_ACTIVE_HIT.inc()
            return hit
    _M_ACTIVE_MISS.inc()
    # inlined is_active_validator: this O(n) scan is the cold-path cost
    # of the first committee lookup of an epoch at mainnet scale
    out = [
        i
        for i, v in enumerate(state.validators)
        if v.activation_epoch <= epoch < v.exit_epoch
    ]
    if tok is not None:
        try:  # FIFO eviction; benign under concurrent readers
            while len(_ACTIVE_CACHE) >= _ACTIVE_CACHE_MAX:
                _ACTIVE_CACHE.pop(next(iter(_ACTIVE_CACHE)))
        except (KeyError, StopIteration, RuntimeError):
            pass
        _ACTIVE_CACHE[(tok, epoch)] = out
    return out


def get_randao_mix(spec: ChainSpec, state, epoch: int) -> bytes:
    return state.randao_mixes[epoch % spec.preset.epochs_per_historical_vector]


def get_seed(spec: ChainSpec, state, epoch: int, domain_type: bytes) -> bytes:
    mix = get_randao_mix(
        spec,
        state,
        epoch
        + spec.preset.epochs_per_historical_vector
        - spec.min_seed_lookahead
        - 1,
    )
    return _hash(domain_type + epoch.to_bytes(8, "little") + mix)


def get_total_balance(spec: ChainSpec, state, indices: Iterable[int]) -> int:
    total = sum(state.validators[i].effective_balance for i in indices)
    return max(spec.effective_balance_increment, total)


def get_total_active_balance(spec: ChainSpec, state) -> int:
    epoch = get_current_epoch(spec, state)
    tok = seq_token(state.validators)
    if tok is not None:
        hit = _TAB_CACHE.get((tok, epoch))
        if hit is not None:
            _M_TAB_HIT.inc()
            return hit
    _M_TAB_MISS.inc()
    total = get_total_balance(
        spec, state, get_active_validator_indices(state, epoch)
    )
    if tok is not None:
        try:
            while len(_TAB_CACHE) >= _ACTIVE_CACHE_MAX:
                _TAB_CACHE.pop(next(iter(_TAB_CACHE)))
        except (KeyError, StopIteration, RuntimeError):
            pass
        _TAB_CACHE[(tok, epoch)] = total
    return total


def get_validator_churn_limit(spec: ChainSpec, state) -> int:
    active = len(get_active_validator_indices(state, get_current_epoch(spec, state)))
    return max(
        spec.min_per_epoch_churn_limit, active // spec.churn_limit_quotient
    )


def increase_balance(state, index: int, delta: int) -> None:
    state.balances[index] += delta


def decrease_balance(state, index: int, delta: int) -> None:
    state.balances[index] = max(0, state.balances[index] - delta)


# ---------------------------------------------------------------- committees


def get_committee_count_per_slot(spec: ChainSpec, state, epoch: int) -> int:
    active = len(get_active_validator_indices(state, epoch))
    p = spec.preset
    return max(
        1,
        min(
            p.max_committees_per_slot,
            active // p.slots_per_epoch // p.target_committee_size,
        ),
    )


def get_beacon_committee(spec: ChainSpec, state, slot: int, index: int) -> list:
    epoch = compute_epoch_at_slot(spec, slot)
    per_slot = get_committee_count_per_slot(spec, state, epoch)
    indices = get_active_validator_indices(state, epoch)
    seed = get_seed(spec, state, epoch, spec.domain_beacon_attester)
    return compute_committee(
        indices,
        seed,
        (slot % spec.preset.slots_per_epoch) * per_slot + index,
        per_slot * spec.preset.slots_per_epoch,
        spec.preset.shuffle_round_count,
    )


def compute_proposer_index(
    spec: ChainSpec, state, indices: list, seed: bytes
) -> int:
    """Effective-balance-weighted rejection sampling over the shuffled
    active set (beacon_state.rs get_beacon_proposer_index path)."""
    assert indices
    max_byte = 255
    i = 0
    total = len(indices)
    while True:
        shuffled = compute_shuffled_index(
            i % total, total, seed, spec.preset.shuffle_round_count
        )
        candidate = indices[shuffled]
        rand_byte = _hash(seed + (i // 32).to_bytes(8, "little"))[i % 32]
        eff = state.validators[candidate].effective_balance
        if eff * max_byte >= spec.max_effective_balance * rand_byte:
            return candidate
        i += 1


def get_beacon_proposer_index(spec: ChainSpec, state) -> int:
    return get_beacon_proposer_index_at_slot(spec, state, int(state.slot))


def get_beacon_proposer_index_at_slot(spec: ChainSpec, state, slot: int) -> int:
    """Proposer for any `slot` of the state's CURRENT epoch, without
    advancing the state: the seed depends only on the epoch mix and the
    slot number, and the active set + effective balances are fixed
    within an epoch (beacon_proposer_cache.rs computes whole epochs
    this way)."""
    epoch = get_current_epoch(spec, state)
    assert compute_epoch_at_slot(spec, slot) == epoch, "slot outside epoch"
    seed = _hash(
        get_seed(spec, state, epoch, spec.domain_beacon_proposer)
        + int(slot).to_bytes(8, "little")
    )
    return compute_proposer_index(
        spec, state, get_active_validator_indices(state, epoch), seed
    )


def get_next_sync_committee_indices(spec: ChainSpec, state) -> list:
    """Seeded, balance-weighted sampling WITH replacement
    (sync_committee.rs get_next_sync_committee)."""
    epoch = get_current_epoch(spec, state) + 1
    indices = get_active_validator_indices(state, epoch)
    seed = get_seed(spec, state, epoch, spec.domain_sync_committee)
    total = len(indices)
    out = []
    i = 0
    while len(out) < spec.preset.sync_committee_size:
        shuffled = compute_shuffled_index(
            i % total, total, seed, spec.preset.shuffle_round_count
        )
        candidate = indices[shuffled]
        rand_byte = _hash(seed + (i // 32).to_bytes(8, "little"))[i % 32]
        eff = state.validators[candidate].effective_balance
        if eff * 255 >= spec.max_effective_balance * rand_byte:
            out.append(candidate)
        i += 1
    return out


def get_next_sync_committee(spec: ChainSpec, state):
    indices = get_next_sync_committee_indices(spec, state)
    pubkeys = [bytes(state.validators[i].pubkey) for i in indices]
    # sampling is WITH replacement: decompress each distinct key once
    uniq = {pk: PublicKey.from_bytes(pk).point for pk in set(pubkeys)}
    agg = None
    from ..crypto.bls import curve as C

    for pk in pubkeys:
        agg = C.g1_add(agg, uniq[pk])
    agg_bytes = PublicKey(agg).to_bytes() if agg is not None else b"\xc0" + b"\x00" * 47
    return T.SyncCommittee.make(pubkeys=pubkeys, aggregate_pubkey=agg_bytes)


# ---------------------------------------------------------------- mutators


def initiate_validator_exit(spec: ChainSpec, state, index: int) -> None:
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    if spec.electra_enabled(get_current_epoch(spec, state)):
        from . import electra

        electra.initiate_validator_exit(spec, state, index)
        return
    exit_epochs = [
        w.exit_epoch
        for w in state.validators
        if w.exit_epoch != FAR_FUTURE_EPOCH
    ]
    activation_exit = get_current_epoch(spec, state) + 1 + spec.max_seed_lookahead
    exit_queue_epoch = max(exit_epochs + [activation_exit])
    churn = len(
        [w for w in state.validators if w.exit_epoch == exit_queue_epoch]
    )
    if churn >= get_validator_churn_limit(spec, state):
        exit_queue_epoch += 1
    v = seq_get_mut(state.validators, index)  # CoW: never leak to copies
    v.exit_epoch = exit_queue_epoch
    v.withdrawable_epoch = (
        exit_queue_epoch + spec.min_validator_withdrawability_delay
    )


def slash_validator(
    spec: ChainSpec,
    state,
    index: int,
    whistleblower_index: Optional[int] = None,
    _meter_component: str = "attester_slashings",
) -> None:
    epoch = get_current_epoch(spec, state)
    initiate_validator_exit(spec, state, index)
    v = seq_get_mut(state.validators, index)
    v.slashed = True
    v.withdrawable_epoch = max(
        v.withdrawable_epoch, epoch + spec.preset.epochs_per_slashings_vector
    )
    state.slashings[epoch % spec.preset.epochs_per_slashings_vector] += (
        v.effective_balance
    )
    electra_active = spec.electra_enabled(epoch)
    slash_quotient = (
        spec.min_slashing_penalty_quotient_electra
        if electra_active
        else MIN_SLASHING_PENALTY_QUOTIENT
    )
    decrease_balance(state, index, v.effective_balance // slash_quotient)
    proposer_index = get_beacon_proposer_index(spec, state)
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    wb_quotient = (
        spec.whistleblower_reward_quotient_electra
        if electra_active
        else spec.whistleblower_reward_quotient
    )
    whistleblower_reward = v.effective_balance // wb_quotient
    proposer_reward = (
        whistleblower_reward * PROPOSER_WEIGHT // WEIGHT_DENOMINATOR
    )
    increase_balance(state, proposer_index, proposer_reward)
    _meter_add(_meter_component, proposer_reward)
    increase_balance(
        state, whistleblower_index, whistleblower_reward - proposer_reward
    )


# ---------------------------------------------------------------- slots


def process_slots(spec: ChainSpec, state, slot: int) -> None:
    """per_slot_processing.rs:28: advance state to `slot`, running epoch
    processing at each epoch boundary."""
    if state.slot >= slot:
        raise BlockProcessingError("state is ahead of target slot")
    while state.slot < slot:
        _process_slot(spec, state)
        if (state.slot + 1) % spec.preset.slots_per_epoch == 0:
            process_epoch(spec, state)
            # fork boundary: entering electra runs upgrade_to_electra
            # (seeds churn from the pre-fork exit queue)
            next_epoch = compute_epoch_at_slot(spec, state.slot + 1)
            if spec.electra_enabled(next_epoch) and not spec.electra_enabled(
                next_epoch - 1
            ):
                from . import electra

                electra.upgrade_state(spec, state)
        state.slot += 1


def _process_slot(spec: ChainSpec, state) -> None:
    # the dominant pre-advance cost since the columnar epoch transition
    # (ROADMAP item 4): measured always, so every slot lands htr:<field>
    # spans on the timelines and the state_hash_* series move in prod.
    # prewarm (ISSUE 15) batches the dirty chunk subtrees through the
    # lane SHA-256 kernel when the estimate crosses the launch-overhead
    # threshold — epoch-boundary roots (incl. the on_slot_tail overlap,
    # which runs process_slots) and cold roots after a checkpoint join
    # batch in one pass; steady slots stay on the host path
    with _hash_costs.measure("slot_root", slot=int(state.slot)):
        _merkle.prewarm(state, op="slot_root")
        previous_state_root = state.hash_tree_root()
    state.state_roots[state.slot % spec.preset.slots_per_historical_root] = (
        previous_state_root
    )
    if state.latest_block_header.state_root == b"\x00" * 32:
        state.latest_block_header.state_root = previous_state_root
    previous_block_root = state.latest_block_header.hash_tree_root()
    state.block_roots[state.slot % spec.preset.slots_per_historical_root] = (
        previous_block_root
    )


def get_block_root_at_slot(spec: ChainSpec, state, slot: int) -> bytes:
    if not (
        slot < state.slot
        and state.slot <= slot + spec.preset.slots_per_historical_root
    ):
        raise BlockProcessingError("slot out of block-root range")
    return state.block_roots[slot % spec.preset.slots_per_historical_root]


def get_block_root(spec: ChainSpec, state, epoch: int) -> bytes:
    return get_block_root_at_slot(
        spec, state, compute_start_slot_at_epoch(spec, epoch)
    )


# ---------------------------------------------------------------- block


def state_transition(
    spec: ChainSpec, state, signed_block, verify_signatures: bool = True
) -> None:
    """Full transition: slots -> block -> state-root check
    (per_block_processing.rs:100 entry semantics)."""
    block = signed_block.message
    if state.slot < block.slot:
        process_slots(spec, state, block.slot)
    if verify_signatures:
        from .signature_sets import block_proposal_signature_set

        s = block_proposal_signature_set(
            spec,
            _pubkey_getter(state),
            signed_block,
            state.fork,
            state.genesis_validators_root,
        )
        if not bls.verify_signature_sets([s]):
            raise BlockProcessingError("invalid block signature")
    process_block(spec, state, block, verify_signatures=verify_signatures)
    with _hash_costs.measure("state_root_check", slot=int(block.slot)):
        _merkle.prewarm(state, op="state_root_check")
        root = state.hash_tree_root()
    if bytes(block.state_root) != root:
        raise BlockProcessingError("state root mismatch")


def _pubkey_getter(state):
    cache = {}

    def get_pubkey(index: int) -> PublicKey:
        if index not in cache:
            cache[index] = PublicKey.from_bytes(
                bytes(state.validators[index].pubkey)
            )
        return cache[index]

    return get_pubkey


def process_block(
    spec: ChainSpec, state, block, verify_signatures: bool = True
) -> None:
    """per_block_processing.rs:100 order: header, (withdrawals, payload)
    for the execution forks, randao, eth1, operations, sync aggregate."""
    process_block_header(spec, state, block)
    blinded = hasattr(block.body, "execution_payload_header")
    if blinded:
        # builder flow: the body carries only the payload HEADER
        # (process_withdrawals/process_execution_payload blinded arms,
        # per_block_processing.rs on BlindedPayload)
        process_withdrawals_header(
            spec, state, block.body.execution_payload_header
        )
        process_execution_payload_header(spec, state, block.body)
    else:
        process_withdrawals(spec, state, block.body.execution_payload)
        process_execution_payload(spec, state, block.body)
    process_randao(spec, state, block, verify_signatures)
    process_eth1_data(spec, state, block.body)
    process_operations(spec, state, block.body, verify_signatures)
    process_sync_aggregate(spec, state, block.body.sync_aggregate, verify_signatures)


# ------------------------------------------------------- execution payload


def compute_timestamp_at_slot(spec: ChainSpec, state, slot: int) -> int:
    return state.genesis_time + (slot - GENESIS_SLOT) * spec.seconds_per_slot


def is_merge_transition_complete(state) -> bool:
    """True once the state carries a real payload header.
    `interop_genesis_state` pre-fills a genesis EL block hash, so interop
    chains are post-merge from birth and payload ancestry is enforced
    from the first block; only a pristine pre-merge state is False."""
    return (
        bytes(state.latest_execution_payload_header.block_hash) != b"\x00" * 32
        or state.latest_execution_payload_header.block_number != 0
        or bytes(state.latest_execution_payload_header.prev_randao) != b"\x00" * 32
    )


def process_execution_payload(spec: ChainSpec, state, body) -> None:
    """Consensus-side payload checks + header rotation
    (process_execution_payload in per_block_processing.rs; the EL-side
    validity check is notify_new_payload through the engine API, which
    the chain layer drives asynchronously)."""
    payload = body.execution_payload
    if is_merge_transition_complete(state):
        if bytes(payload.parent_hash) != bytes(
            state.latest_execution_payload_header.block_hash
        ):
            raise BlockProcessingError("payload parent hash mismatch")
    if bytes(payload.prev_randao) != get_randao_mix(
        spec, state, get_current_epoch(spec, state)
    ):
        raise BlockProcessingError("payload prev_randao mismatch")
    if payload.timestamp != compute_timestamp_at_slot(spec, state, state.slot):
        raise BlockProcessingError("payload timestamp mismatch")
    if len(body.blob_kzg_commitments) > spec.preset.max_blobs_per_block:
        raise BlockProcessingError("too many blob commitments")
    state.latest_execution_payload_header = T.execution_payload_to_header(
        payload
    )


# ------------------------------------------------------------ withdrawals


def has_eth1_withdrawal_credential(validator) -> bool:
    return bytes(validator.withdrawal_credentials)[:1] == b"\x01"


def is_fully_withdrawable_validator(validator, balance: int, epoch: int) -> bool:
    return (
        has_eth1_withdrawal_credential(validator)
        and validator.withdrawable_epoch <= epoch
        and balance > 0
    )


def is_partially_withdrawable_validator(spec: ChainSpec, validator, balance: int) -> bool:
    return (
        has_eth1_withdrawal_credential(validator)
        and validator.effective_balance == spec.max_effective_balance
        and balance > spec.max_effective_balance
    )


def get_expected_withdrawals(spec: ChainSpec, state) -> list:
    """The deterministic sweep (capella get_expected_withdrawals):
    bounded scan from next_withdrawal_validator_index collecting full
    and excess-balance withdrawals."""
    epoch = get_current_epoch(spec, state)
    widx = state.next_withdrawal_index
    vidx = state.next_withdrawal_validator_index
    withdrawals = []
    n = len(state.validators)
    for _ in range(min(n, spec.preset.max_validators_per_withdrawals_sweep)):
        v = state.validators[vidx]
        balance = state.balances[vidx]
        if is_fully_withdrawable_validator(v, balance, epoch):
            withdrawals.append(
                T.Withdrawal.make(
                    index=widx,
                    validator_index=vidx,
                    address=bytes(v.withdrawal_credentials)[12:],
                    amount=balance,
                )
            )
            widx += 1
        elif is_partially_withdrawable_validator(spec, v, balance):
            withdrawals.append(
                T.Withdrawal.make(
                    index=widx,
                    validator_index=vidx,
                    address=bytes(v.withdrawal_credentials)[12:],
                    amount=balance - spec.max_effective_balance,
                )
            )
            widx += 1
        if len(withdrawals) == spec.preset.max_withdrawals_per_payload:
            break
        vidx = (vidx + 1) % n
    return withdrawals


def process_withdrawals_header(spec: ChainSpec, state, header) -> None:
    """Blinded variant: the header's withdrawals_root must equal the
    root of the state-derived expected withdrawals; the sweep advances
    identically."""
    partials_consumed = 0
    if spec.electra_enabled(get_current_epoch(spec, state)):
        from . import electra

        expected, partials_consumed = electra.get_expected_withdrawals(
            spec, state
        )
    else:
        expected = get_expected_withdrawals(spec, state)
    want = T.List(
        T.Withdrawal, spec.preset.max_withdrawals_per_payload
    ).hash_tree_root(expected)
    if bytes(header.withdrawals_root) != want:
        raise BlockProcessingError("withdrawals_root mismatch")
    _apply_withdrawals(spec, state, expected, partials_consumed)


def process_execution_payload_header(spec: ChainSpec, state, body) -> None:
    """Blinded variant of process_execution_payload: same consensus
    checks against the header, which then rotates into the state."""
    header = body.execution_payload_header
    if is_merge_transition_complete(state):
        if bytes(header.parent_hash) != bytes(
            state.latest_execution_payload_header.block_hash
        ):
            raise BlockProcessingError("payload parent hash mismatch")
    if bytes(header.prev_randao) != get_randao_mix(
        spec, state, get_current_epoch(spec, state)
    ):
        raise BlockProcessingError("payload prev_randao mismatch")
    if header.timestamp != compute_timestamp_at_slot(spec, state, state.slot):
        raise BlockProcessingError("payload timestamp mismatch")
    if len(body.blob_kzg_commitments) > spec.preset.max_blobs_per_block:
        raise BlockProcessingError("too many blob commitments")
    state.latest_execution_payload_header = T.ExecutionPayloadHeader.make(
        **{n: getattr(header, n) for n, _ in T.ExecutionPayloadHeader.fields}
    )


def process_withdrawals(spec: ChainSpec, state, payload) -> None:
    """capella process_withdrawals: the payload's withdrawals must equal
    the state-derived expectation; balances decrease; sweep cursors
    advance."""
    partials_consumed = 0
    if spec.electra_enabled(get_current_epoch(spec, state)):
        from . import electra

        expected, partials_consumed = electra.get_expected_withdrawals(
            spec, state
        )
    else:
        expected = get_expected_withdrawals(spec, state)
    got = list(payload.withdrawals)
    if len(got) != len(expected):
        raise BlockProcessingError("withdrawal count mismatch")
    for w, e in zip(got, expected):
        if (
            w.index != e.index
            or w.validator_index != e.validator_index
            or bytes(w.address) != bytes(e.address)
            or w.amount != e.amount
        ):
            raise BlockProcessingError("withdrawal mismatch")
    _apply_withdrawals(spec, state, expected, partials_consumed)


def _apply_withdrawals(spec, state, expected, partials_consumed) -> None:
    """Shared effect application for the full and blinded arms."""
    for w in expected:
        decrease_balance(state, w.validator_index, w.amount)
    if partials_consumed:
        state.electra.pending_partial_withdrawals = list(
            state.electra.pending_partial_withdrawals
        )[partials_consumed:]
    if expected:
        state.next_withdrawal_index = expected[-1].index + 1
    n = len(state.validators)
    if len(expected) == spec.preset.max_withdrawals_per_payload:
        state.next_withdrawal_validator_index = (
            expected[-1].validator_index + 1
        ) % n
    else:
        # spec: advance by the UNclamped sweep constant (clamping to n
        # diverges from other clients whenever sweep % n != 0)
        state.next_withdrawal_validator_index = (
            state.next_withdrawal_validator_index
            + spec.preset.max_validators_per_withdrawals_sweep
        ) % n


def process_block_header(spec: ChainSpec, state, block) -> None:
    if block.slot != state.slot:
        raise BlockProcessingError("block/state slot mismatch")
    if block.slot <= state.latest_block_header.slot:
        raise BlockProcessingError("block older than latest header")
    if block.proposer_index != get_beacon_proposer_index(spec, state):
        raise BlockProcessingError("wrong proposer")
    if bytes(block.parent_root) != state.latest_block_header.hash_tree_root():
        raise BlockProcessingError("parent root mismatch")
    state.latest_block_header = T.BeaconBlockHeader.make(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=bytes(block.parent_root),
        state_root=b"\x00" * 32,
        body_root=block.body.hash_tree_root(),
    )
    proposer = state.validators[block.proposer_index]
    if proposer.slashed:
        raise BlockProcessingError("proposer slashed")


def process_randao(spec: ChainSpec, state, block, verify_signatures: bool) -> None:
    epoch = get_current_epoch(spec, state)
    body = block.body
    if verify_signatures:
        from .signature_sets import randao_signature_set

        s = randao_signature_set(
            spec,
            _pubkey_getter(state),
            block,
            state.fork,
            state.genesis_validators_root,
        )
        if not bls.verify_signature_sets([s]):
            raise BlockProcessingError("invalid randao reveal")
    mix = bytes(
        a ^ b
        for a, b in zip(
            get_randao_mix(spec, state, epoch), _hash(bytes(body.randao_reveal))
        )
    )
    state.randao_mixes[epoch % spec.preset.epochs_per_historical_vector] = mix


def process_eth1_data(spec: ChainSpec, state, body) -> None:
    state.eth1_data_votes = list(state.eth1_data_votes) + [body.eth1_data]
    period_slots = (
        spec.preset.epochs_per_eth1_voting_period * spec.preset.slots_per_epoch
    )
    votes = [
        v for v in state.eth1_data_votes if v == body.eth1_data
    ]
    if len(votes) * 2 > period_slots:
        state.eth1_data = body.eth1_data


class BlockContext:
    """Per-block caches for values that are constant across a block's
    operations (the reference's ConsensusContext role): proposer index,
    base reward per increment, pubkey->index map. All lazy."""

    def __init__(self, spec: ChainSpec, state):
        self.spec = spec
        self.state = state
        self._proposer = None
        self._brpi = None
        self._pk_index = None

    def proposer_index(self) -> int:
        if self._proposer is None:
            self._proposer = get_beacon_proposer_index(self.spec, self.state)
        return self._proposer

    def base_reward_per_increment(self) -> int:
        if self._brpi is None:
            self._brpi = get_base_reward_per_increment(self.spec, self.state)
        return self._brpi

    def pubkey_index(self, pubkey: bytes) -> Optional[int]:
        if self._pk_index is None:
            self._pk_index = {
                bytes(v.pubkey): i for i, v in enumerate(self.state.validators)
            }
        return self._pk_index.get(pubkey)

    def register_new_validator(self, pubkey: bytes, index: int) -> None:
        if self._pk_index is not None:
            self._pk_index[pubkey] = index


def process_operations(
    spec: ChainSpec, state, body, verify_signatures: bool, ctx=None
) -> None:
    ctx = ctx or BlockContext(spec, state)
    expected_deposits = min(
        spec.preset.max_deposits,
        state.eth1_data.deposit_count - state.eth1_deposit_index,
    )
    if spec.electra_enabled(get_current_epoch(spec, state)):
        from .electra import UNSET_DEPOSIT_REQUESTS_START_INDEX

        start = state.electra.deposit_requests_start_index
        if start != UNSET_DEPOSIT_REQUESTS_START_INDEX:
            # EIP-6110 transition: the legacy eth1 path shuts off at
            # deposit_requests_start_index — past it the SAME deposit
            # would arrive again as a DepositRequest (double credit)
            limit = min(int(state.eth1_data.deposit_count), int(start))
            expected_deposits = (
                min(
                    spec.preset.max_deposits,
                    limit - state.eth1_deposit_index,
                )
                if state.eth1_deposit_index < limit
                else 0
            )
    if len(body.deposits) != expected_deposits:
        raise BlockProcessingError("wrong deposit count")
    for op in body.proposer_slashings:
        process_proposer_slashing(spec, state, op, verify_signatures)
    for op in body.attester_slashings:
        process_attester_slashing(spec, state, op, verify_signatures)
    for op in body.attestations:
        process_attestation(spec, state, op, verify_signatures, ctx=ctx)
    for op in body.deposits:
        process_deposit(spec, state, op, ctx=ctx)
    for op in body.voluntary_exits:
        process_voluntary_exit(spec, state, op, verify_signatures)
    for op in body.bls_to_execution_changes:
        process_bls_to_execution_change(spec, state, op, verify_signatures)
    if spec.electra_enabled(get_current_epoch(spec, state)):
        from . import electra

        electra.process_execution_requests(
            spec, state, body.execution_requests, ctx
        )


def is_slashable_validator(v, epoch: int) -> bool:
    return (not v.slashed) and (
        v.activation_epoch <= epoch < v.withdrawable_epoch
    )


def process_proposer_slashing(
    spec: ChainSpec, state, slashing, verify_signatures: bool
) -> None:
    h1 = slashing.signed_header_1.message
    h2 = slashing.signed_header_2.message
    if h1.slot != h2.slot:
        raise BlockProcessingError("slashing headers differ in slot")
    if h1.proposer_index != h2.proposer_index:
        raise BlockProcessingError("slashing headers differ in proposer")
    if h1.hash_tree_root() == h2.hash_tree_root():
        raise BlockProcessingError("slashing headers identical")
    if not 0 <= int(h1.proposer_index) < len(state.validators):
        raise BlockProcessingError(
            f"slashing for unknown proposer {int(h1.proposer_index)}"
        )
    proposer = state.validators[h1.proposer_index]
    if not is_slashable_validator(proposer, get_current_epoch(spec, state)):
        raise BlockProcessingError("proposer not slashable")
    if verify_signatures:
        from .signature_sets import proposer_slashing_signature_sets

        sets = proposer_slashing_signature_sets(
            spec,
            _pubkey_getter(state),
            slashing,
            state.fork,
            state.genesis_validators_root,
        )
        if not bls.verify_signature_sets(sets):
            raise BlockProcessingError("invalid slashing signatures")
    slash_validator(
        spec, state, h1.proposer_index, _meter_component="proposer_slashings"
    )


def is_slashable_attestation_data(d1, d2) -> bool:
    double = (
        d1.hash_tree_root() != d2.hash_tree_root()
        and d1.target.epoch == d2.target.epoch
    )
    surround = (
        d1.source.epoch < d2.source.epoch and d2.target.epoch < d1.target.epoch
    )
    return double or surround


def process_attester_slashing(
    spec: ChainSpec, state, slashing, verify_signatures: bool
) -> None:
    a1, a2 = slashing.attestation_1, slashing.attestation_2
    if not is_slashable_attestation_data(a1.data, a2.data):
        raise BlockProcessingError("attestations not slashable")
    for a in (a1, a2):
        if not _is_valid_indexed_attestation(spec, state, a, verify_signatures):
            raise BlockProcessingError("invalid indexed attestation")
    slashed_any = False
    epoch = get_current_epoch(spec, state)
    common = sorted(
        set(a1.attesting_indices) & set(a2.attesting_indices)
    )
    n_validators = len(state.validators)
    for index in common:
        # attesting indices are attacker-controlled: out-of-registry
        # entries make the attestation invalid, not a crash
        if index >= n_validators:
            raise BlockProcessingError(
                f"attester slashing names unknown validator {int(index)}"
            )
        if is_slashable_validator(state.validators[index], epoch):
            slash_validator(spec, state, index)
            slashed_any = True
    if not slashed_any:
        raise BlockProcessingError("no one slashed")


def _is_valid_indexed_attestation(
    spec: ChainSpec, state, indexed, verify_signatures: bool
) -> bool:
    idx = list(indexed.attesting_indices)
    if not idx or idx != sorted(set(idx)):
        return False
    if verify_signatures:
        from .signature_sets import indexed_attestation_signature_set

        s = indexed_attestation_signature_set(
            spec,
            _pubkey_getter(state),
            indexed,
            state.fork,
            state.genesis_validators_root,
        )
        return bls.verify_signature_sets([s])
    return True


def get_attestation_participation_flag_indices(
    spec: ChainSpec, state, data, inclusion_delay: int
) -> list:
    justified = (
        state.current_justified_checkpoint
        if data.target.epoch == get_current_epoch(spec, state)
        else state.previous_justified_checkpoint
    )
    is_matching_source = (
        data.source.epoch == justified.epoch
        and bytes(data.source.root) == bytes(justified.root)
    )
    if not is_matching_source:
        raise BlockProcessingError("source checkpoint mismatch")
    is_matching_target = bytes(data.target.root) == get_block_root(
        spec, state, data.target.epoch
    )
    is_matching_head = is_matching_target and bytes(
        data.beacon_block_root
    ) == get_block_root_at_slot(spec, state, data.slot)
    flags = []
    sqrt_epoch = _integer_sqrt(spec.preset.slots_per_epoch)
    if is_matching_source and inclusion_delay <= sqrt_epoch:
        flags.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and inclusion_delay <= spec.preset.slots_per_epoch:
        flags.append(TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == spec.min_attestation_inclusion_delay:
        flags.append(TIMELY_HEAD_FLAG_INDEX)
    return flags


def _integer_sqrt(n: int) -> int:
    # exact integer sqrt: float sqrt is off-by-one above 2^52, which
    # would skew every base reward at mainnet balance scale
    import math

    return math.isqrt(n)


def get_base_reward_per_increment(spec: ChainSpec, state) -> int:
    return (
        spec.effective_balance_increment
        * spec.base_reward_factor
        // _integer_sqrt(get_total_active_balance(spec, state))
    )


def get_base_reward(spec: ChainSpec, state, index: int) -> int:
    increments = (
        state.validators[index].effective_balance
        // spec.effective_balance_increment
    )
    return increments * get_base_reward_per_increment(spec, state)


def resolve_committee_index(spec: ChainSpec, state, attestation) -> int:
    """EIP-7549: post-electra the committee moves to committee_bits
    (data.index must be 0); exactly one bit set in this framework's
    single-committee canonical shape."""
    data = attestation.data
    if spec.electra_enabled(compute_epoch_at_slot(spec, int(data.slot))):
        set_bits = [
            i for i, b in enumerate(attestation.committee_bits) if b
        ]
        # STRICT post-electra (the spec asserts): index lives in the
        # bits, data.index must be zero — a bits-free attestation is
        # invalid, not a legacy fallback (consensus-split risk)
        if int(data.index) != 0:
            raise BlockProcessingError(
                "electra attestation must have data.index == 0"
            )
        if len(set_bits) != 1:
            raise BlockProcessingError(
                "electra attestation needs exactly one committee bit"
            )
        return set_bits[0]
    return int(data.index)


def get_attesting_indices(spec: ChainSpec, state, attestation) -> set:
    committee = get_beacon_committee(
        spec, state, attestation.data.slot,
        resolve_committee_index(spec, state, attestation),
    )
    bits = attestation.aggregation_bits
    if len(bits) != len(committee):
        raise BlockProcessingError("aggregation bits length mismatch")
    return {committee[i] for i, b in enumerate(bits) if b}


def process_attestation(
    spec: ChainSpec, state, attestation, verify_signatures: bool, ctx=None
) -> None:
    ctx = ctx or BlockContext(spec, state)
    data = attestation.data
    cur = get_current_epoch(spec, state)
    prev = get_previous_epoch(spec, state)
    if data.target.epoch not in (cur, prev):
        raise BlockProcessingError("attestation target epoch out of range")
    if data.target.epoch != compute_epoch_at_slot(spec, data.slot):
        raise BlockProcessingError("target epoch != slot epoch")
    if not (
        data.slot + spec.min_attestation_inclusion_delay <= state.slot
    ):
        raise BlockProcessingError("attestation too fresh")
    committee_index = resolve_committee_index(spec, state, attestation)
    if committee_index >= get_committee_count_per_slot(
        spec, state, data.target.epoch
    ):
        raise BlockProcessingError("committee index out of range")

    inclusion_delay = state.slot - data.slot
    flag_indices = get_attestation_participation_flag_indices(
        spec, state, data, inclusion_delay
    )
    attesting = get_attesting_indices(spec, state, attestation)
    if verify_signatures:
        indexed = T.IndexedAttestation.make(
            attesting_indices=sorted(attesting),
            data=data,
            signature=bytes(attestation.signature),
        )
        if not _is_valid_indexed_attestation(spec, state, indexed, True):
            raise BlockProcessingError("invalid attestation signature")

    participation = (
        state.current_epoch_participation
        if data.target.epoch == cur
        else state.previous_epoch_participation
    )
    base_reward_per_inc = ctx.base_reward_per_increment()
    proposer_reward_numerator = 0
    for index in attesting:
        increments = (
            state.validators[index].effective_balance
            // spec.effective_balance_increment
        )
        base_reward = increments * base_reward_per_inc
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if flag_index in flag_indices and not (
                participation[index] & (1 << flag_index)
            ):
                participation[index] |= 1 << flag_index
                proposer_reward_numerator += base_reward * weight
    proposer_reward_denominator = (
        (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
        * WEIGHT_DENOMINATOR
        // PROPOSER_WEIGHT
    )
    att_proposer_reward = proposer_reward_numerator // proposer_reward_denominator
    increase_balance(state, ctx.proposer_index(), att_proposer_reward)
    _meter_add("attestations", att_proposer_reward)


def is_valid_merkle_branch(
    leaf: bytes, branch, depth: int, index: int, root: bytes
) -> bool:
    value = leaf
    for i in range(depth):
        if (index >> i) & 1:
            value = _hash(bytes(branch[i]) + value)
        else:
            value = _hash(value + bytes(branch[i]))
    return value == bytes(root)


def process_deposit(spec: ChainSpec, state, deposit, ctx=None) -> None:
    if not is_valid_merkle_branch(
        deposit.data.hash_tree_root(),
        deposit.proof,
        DEPOSIT_CONTRACT_TREE_DEPTH + 1,  # +1 for the length mix-in
        state.eth1_deposit_index,
        state.eth1_data.deposit_root,
    ):
        raise BlockProcessingError("bad deposit proof")
    state.eth1_deposit_index += 1
    apply_deposit(
        spec,
        state,
        bytes(deposit.data.pubkey),
        bytes(deposit.data.withdrawal_credentials),
        deposit.data.amount,
        bytes(deposit.data.signature),
        ctx=ctx,
    )


def apply_deposit(
    spec: ChainSpec,
    state,
    pubkey: bytes,
    withdrawal_credentials: bytes,
    amount: int,
    signature: bytes,
    ctx=None,
) -> None:
    ctx = ctx or BlockContext(spec, state)
    existing = ctx.pubkey_index(pubkey)
    if existing is None:
        # new validator: deposit signature must verify (its own domain,
        # genesis fork, NO genesis_validators_root) or it is skipped
        deposit_message = T.DepositMessage.make(
            pubkey=pubkey,
            withdrawal_credentials=withdrawal_credentials,
            amount=amount,
        )
        domain = compute_domain(
            spec.domain_deposit, spec.genesis_fork_version, b"\x00" * 32
        )
        signing_root = compute_signing_root(deposit_message, domain)
        try:
            pk = PublicKey.from_bytes(pubkey)
            sig = Signature.from_bytes(signature)
        except Exception:
            return
        if not bls.verify(sig, pk, signing_root):
            return
        index = len(state.validators)
        state.validators.append(
            _validator_from_deposit(spec, pubkey, withdrawal_credentials, amount)
        )
        state.balances.append(amount)
        state.previous_epoch_participation.append(0)
        state.current_epoch_participation.append(0)
        state.inactivity_scores.append(0)
        ctx.register_new_validator(pubkey, index)
    else:
        increase_balance(state, existing, amount)


def _validator_from_deposit(
    spec: ChainSpec, pubkey: bytes, withdrawal_credentials: bytes, amount: int
):
    effective = min(
        amount - amount % spec.effective_balance_increment,
        spec.max_effective_balance,
    )
    return T.Validator.make(
        pubkey=pubkey,
        withdrawal_credentials=withdrawal_credentials,
        effective_balance=effective,
        slashed=False,
        activation_eligibility_epoch=FAR_FUTURE_EPOCH,
        activation_epoch=FAR_FUTURE_EPOCH,
        exit_epoch=FAR_FUTURE_EPOCH,
        withdrawable_epoch=FAR_FUTURE_EPOCH,
    )


def process_voluntary_exit(
    spec: ChainSpec, state, signed_exit, verify_signatures: bool
) -> None:
    exit_msg = signed_exit.message
    if not 0 <= int(exit_msg.validator_index) < len(state.validators):
        # reference ExitInvalid::ValidatorUnknown — a typed processing
        # error, not an index crash
        raise BlockProcessingError(
            f"exit for unknown validator {int(exit_msg.validator_index)}"
        )
    v = state.validators[exit_msg.validator_index]
    cur = get_current_epoch(spec, state)
    if not is_active_validator(v, cur):
        raise BlockProcessingError("exiting validator not active")
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        raise BlockProcessingError("exit already initiated")
    if cur < exit_msg.epoch:
        raise BlockProcessingError("exit not yet valid")
    if cur < v.activation_epoch + spec.shard_committee_period:
        raise BlockProcessingError("validator too young to exit")
    if verify_signatures:
        from .signature_sets import exit_signature_set

        s = exit_signature_set(
            spec,
            _pubkey_getter(state),
            signed_exit,
            state.fork,
            state.genesis_validators_root,
        )
        if not bls.verify_signature_sets([s]):
            raise BlockProcessingError("invalid exit signature")
    if spec.electra_enabled(get_current_epoch(spec, state)):
        from . import electra

        # EIP-7251: no voluntary exit while partial withdrawals pend
        if electra.get_pending_balance_to_withdraw(
            state, int(exit_msg.validator_index)
        ) > 0:
            raise BlockProcessingError(
                "voluntary exit with pending partial withdrawals"
            )
    initiate_validator_exit(spec, state, exit_msg.validator_index)


def process_bls_to_execution_change(
    spec: ChainSpec, state, signed_change, verify_signatures: bool
) -> None:
    change = signed_change.message
    if not 0 <= int(change.validator_index) < len(state.validators):
        raise BlockProcessingError(
            f"bls change for unknown validator {int(change.validator_index)}"
        )
    v = state.validators[change.validator_index]
    wc = bytes(v.withdrawal_credentials)
    if wc[:1] != b"\x00":
        raise BlockProcessingError("not a BLS withdrawal credential")
    if wc[1:] != _hash(bytes(change.from_bls_pubkey))[1:]:
        raise BlockProcessingError("withdrawal credential mismatch")
    if verify_signatures:
        from .signature_sets import bls_execution_change_signature_set

        s = bls_execution_change_signature_set(
            spec, signed_change, state.genesis_validators_root
        )
        if not bls.verify_signature_sets([s]):
            raise BlockProcessingError("invalid bls-change signature")
    seq_get_mut(state.validators, int(change.validator_index)).withdrawal_credentials = (
        b"\x01" + b"\x00" * 11 + bytes(change.to_execution_address)
    )


def process_sync_aggregate(
    spec: ChainSpec, state, aggregate, verify_signatures: bool
) -> None:
    committee_pubkeys = list(state.current_sync_committee.pubkeys)
    participant_pubkeys = [
        pk
        for pk, bit in zip(committee_pubkeys, aggregate.sync_committee_bits)
        if bit
    ]
    if verify_signatures:
        from .signature_sets import sync_aggregate_signature_set

        prev_slot = max(state.slot - 1, 0)
        s = sync_aggregate_signature_set(
            spec,
            [PublicKey.from_bytes(bytes(pk)) for pk in participant_pubkeys],
            aggregate,
            state.slot,
            get_block_root_at_slot(spec, state, prev_slot),
            state.fork,
            state.genesis_validators_root,
        )
        if s is not None and not bls.verify_signature_sets([s]):
            raise BlockProcessingError("invalid sync aggregate signature")

    total_active_increments = (
        get_total_active_balance(spec, state) // spec.effective_balance_increment
    )
    base_reward_per_inc = get_base_reward_per_increment(spec, state)
    total_base_rewards = base_reward_per_inc * total_active_increments
    max_participant_rewards = (
        total_base_rewards
        * SYNC_REWARD_WEIGHT
        // WEIGHT_DENOMINATOR
        // spec.preset.slots_per_epoch
    )
    participant_reward = max_participant_rewards // spec.preset.sync_committee_size
    proposer_reward = (
        participant_reward
        * PROPOSER_WEIGHT
        // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
    )
    pubkey_to_index = {bytes(v.pubkey): i for i, v in enumerate(state.validators)}
    proposer_index = get_beacon_proposer_index(spec, state)
    for pk, bit in zip(committee_pubkeys, aggregate.sync_committee_bits):
        index = pubkey_to_index[bytes(pk)]
        if bit:
            increase_balance(state, index, participant_reward)
            increase_balance(state, proposer_index, proposer_reward)
            _meter_add("sync_aggregate", proposer_reward)
        else:
            decrease_balance(state, index, participant_reward)


# ---------------------------------------------------------------- epoch
#
# Columnar epoch transition (ISSUE 6): per-validator columns come from
# the ChunkedSeq column-cache bridge (one pass over dirty chunks, not
# O(n) np.fromiter rebuilds per call), the whole balance pipeline runs
# as ONE fused program (ops/epoch.py — jitted when JAX reproduces the
# numpy outputs bit-identically), and writebacks go through
# seq_assign_array so only changed chunks re-own and re-hash. Each
# stage records `epoch:<stage>` spans + the
# state_epoch_stage_seconds{stage=} histogram so slot timelines
# attribute the boundary (single_pass.rs analog, SoA-batch shaped).

_EPOCH_CLAMP = 2**62  # FAR_FUTURE_EPOCH sentinel clamp for int64 math

# ops/epoch.py carries private copies of these constants so it stays
# importable standalone; a fork bumping one here must reach the fused
# program, so divergence fails at import, not at differential-test
# time (explicit raise, not assert: python -O must not void this)
if (
    _epoch_ops.WEIGHTS != tuple(PARTICIPATION_FLAG_WEIGHTS)
    or _epoch_ops.WEIGHT_DENOMINATOR != WEIGHT_DENOMINATOR
    or _epoch_ops.TIMELY_TARGET_FLAG_INDEX != TIMELY_TARGET_FLAG_INDEX
    or _epoch_ops.TIMELY_HEAD_FLAG_INDEX != TIMELY_HEAD_FLAG_INDEX
    or _epoch_ops.INACTIVITY_SCORE_BIAS != INACTIVITY_SCORE_BIAS
    or _epoch_ops.INACTIVITY_SCORE_RECOVERY_RATE
    != INACTIVITY_SCORE_RECOVERY_RATE
    or _epoch_ops.INACTIVITY_PENALTY_QUOTIENT
    != INACTIVITY_PENALTY_QUOTIENT
):
    raise ImportError(
        "ops/epoch.py participation/inactivity constants diverge from "
        "consensus/state_transition.py — the fused epoch program would "
        "compute stale rewards/penalties"
    )

_M_EPOCH_STAGE = _metrics.histogram(
    "state_epoch_stage_seconds",
    "Epoch-transition wall time by processing stage",
    labelnames=("stage",),
)


@contextmanager
def _epoch_stage(name: str):
    t0 = time.perf_counter()
    with _tracing.span(f"epoch:{name}"):
        yield
    _M_EPOCH_STAGE.labels(stage=name).observe(time.perf_counter() - t0)


_VALIDATOR_COLS_KEY = "validator_epoch_cols"
_VGET = operator.itemgetter(
    "effective_balance",
    "slashed",
    "activation_epoch",
    "exit_epoch",
    "withdrawable_epoch",
    "activation_eligibility_epoch",
    "withdrawal_credentials",
)


def _validator_columns_builder(chunk) -> tuple:
    """One pass over a validator chunk -> 7 columns (the seq_columns
    builder): eff u64, slashed bool, activation/exit/withdrawable/
    eligibility epochs clamped to int64, compounding-creds bool."""
    if not chunk:
        z = np.empty(0, np.int64)
        return (
            np.empty(0, np.uint64),
            np.empty(0, np.bool_),
            z,
            z.copy(),
            z.copy(),
            z.copy(),
            np.empty(0, np.bool_),
        )
    rows = [_VGET(v._vals) for v in chunk]
    eff, sl, act, ex, wd, el, wc = zip(*rows)

    def clamp(vals):
        return np.minimum(
            np.asarray(vals, np.uint64), np.uint64(_EPOCH_CLAMP)
        ).astype(np.int64)

    return (
        np.asarray(eff, np.uint64),
        np.asarray(sl, np.bool_),
        clamp(act),
        clamp(ex),
        clamp(wd),
        clamp(el),
        np.asarray([w[0] == 2 for w in wc], np.bool_),
    )


class EpochColumns:
    """Every per-validator column one epoch transition reads, built
    once through the token-keyed column cache and threaded down all
    stages — no stage re-derives slashed/withdrawable/... on its own.
    Arrays are read-only; epoch values are clamped at 2**62 so
    FAR_FUTURE_EPOCH compares as `== _EPOCH_CLAMP`."""

    __slots__ = (
        "n",
        "eff",
        "slashed",
        "activation",
        "exit_epoch",
        "withdrawable",
        "eligibility",
        "compounding",
        "prev_part",
        "cur_part",
        "balances",
        "inactivity",
    )

    def __init__(self, state):
        (
            self.eff,
            self.slashed,
            self.activation,
            self.exit_epoch,
            self.withdrawable,
            self.eligibility,
            self.compounding,
        ) = seq_columns(
            state.validators, _VALIDATOR_COLS_KEY, _validator_columns_builder
        )
        self.n = len(self.eff)
        self.prev_part = seq_column(
            state.previous_epoch_participation, np.uint8
        )
        self.cur_part = seq_column(state.current_epoch_participation, np.uint8)
        self.balances = seq_column(state.balances, np.uint64)
        self.inactivity = seq_column(state.inactivity_scores, np.uint64)


def _epoch_arrays(state):
    """Back-compat 7-tuple view over EpochColumns (http_api rewards
    endpoints consume this shape)."""
    c = EpochColumns(state)
    return (
        c.eff,
        c.slashed,
        c.activation,
        c.exit_epoch,
        c.withdrawable,
        c.prev_part,
        c.cur_part,
    )


def _slashing_penalties(
    spec: ChainSpec, state, total_active: int, cols: EpochColumns, epoch: int
) -> np.ndarray:
    """Dense int64 slashing-penalty column (process_slashings): the
    cohort whose withdrawable epoch sits at the half-vector point pays
    proportionally. Per-index Python ints — the increments*adjusted
    product can exceed int64 on pathological electra registries — over
    a vectorized mask scan."""
    vec = spec.preset.epochs_per_slashings_vector
    out = np.zeros(cols.n, np.int64)
    idx = np.nonzero(cols.slashed & (cols.withdrawable == epoch + vec // 2))[0]
    if len(idx):
        total_slashings = sum(int(s) for s in state.slashings)
        adjusted = min(
            total_slashings * PROPORTIONAL_SLASHING_MULTIPLIER, total_active
        )
        inc = spec.effective_balance_increment
        for i in idx:
            numerator = int(cols.eff[i]) // inc * adjusted
            out[i] = numerator // total_active * inc
    return out


def process_epoch(spec: ChainSpec, state) -> None:
    with _epoch_stage("columns"):
        cols = EpochColumns(state)
    cur = get_current_epoch(spec, state)
    prev = get_previous_epoch(spec, state)
    eff = cols.eff
    active_cur = (cols.activation <= cur) & (cur < cols.exit_epoch)
    active_prev = (cols.activation <= prev) & (prev < cols.exit_epoch)
    unslashed_prev = active_prev & ~cols.slashed
    unslashed_cur = active_cur & ~cols.slashed
    inc = spec.effective_balance_increment

    with _epoch_stage("tallies"):
        total_active = max(int(eff[active_cur].sum()), inc)
        flag_balances_prev = [
            int(eff[unslashed_prev & ((cols.prev_part & (1 << f)) != 0)].sum())
            for f in range(3)
        ]
        target_balance_cur = int(
            eff[
                unslashed_cur
                & ((cols.cur_part & (1 << TIMELY_TARGET_FLAG_INDEX)) != 0)
            ].sum()
        )

    with _epoch_stage("justification"):
        process_justification_and_finalization(
            spec,
            state,
            total_active,
            flag_balances_prev[TIMELY_TARGET_FLAG_INDEX],
            target_balance_cur,
        )

    with _epoch_stage("slashings"):
        slash_penalty = _slashing_penalties(spec, state, total_active, cols, cur)

    # Fused balance pipeline: inactivity scores + flag rewards/
    # penalties + slashing application + hysteresis decision in one
    # program. Exactness of the staging: registry updates never touch
    # balances or effective balances, slashed validators' withdrawable
    # epochs are fixed before registry runs (their exit was initiated
    # at slashing time), and in the non-electra flow nothing between
    # process_slashings and the effective-balance stage moves balances
    # — so pre-stage columns feed every output bit-identically to the
    # sequential spec ordering (differentially tested in
    # tests/test_epoch_columnar.py).
    with _epoch_stage("fused_math"):
        eligible = active_prev | (
            cols.slashed & (prev + 1 < cols.withdrawable)
        )
        arrays = {
            "eff": eff.astype(np.int64),
            "unslashed_prev": unslashed_prev,
            "eligible": eligible,
            "prev_part": cols.prev_part.astype(np.int64),
            "scores": cols.inactivity.astype(np.int64),
            "balances": cols.balances.astype(np.int64),
            "slash_penalty": slash_penalty,
        }
        scalars = {
            "do_deltas": np.bool_(cur != GENESIS_EPOCH),
            "leak": np.bool_(is_in_inactivity_leak(spec, state)),
            "base_reward_per_inc": np.int64(
                inc * spec.base_reward_factor // _integer_sqrt(total_active)
            ),
            "total_active_increments": np.int64(total_active // inc),
            "flag_inc_0": np.int64(flag_balances_prev[0] // inc),
            "flag_inc_1": np.int64(flag_balances_prev[1] // inc),
            "flag_inc_2": np.int64(flag_balances_prev[2] // inc),
            "increment": np.int64(inc),
            "cap": np.int64(spec.max_effective_balance),
            "hysteresis_down": np.int64(inc // 4),
            "hysteresis_up": np.int64(inc // 4 * 2),
        }
        # eff_new/eff_mask are the phase0 (flat-cap) hysteresis arm
        # ONLY: electra must re-decide hysteresis AFTER pending
        # deposits/consolidations move balances (spec stage order) and
        # with per-validator caps, so the electra branch below discards
        # these two outputs — a couple of elementwise ops inside an
        # already-fused program, not a separate pass.
        new_scores, new_balances, eff_new, eff_mask = _epoch_ops.epoch_updates(
            arrays, scalars
        )

    with _epoch_stage("inactivity"):
        seq_assign_array(
            state.inactivity_scores, new_scores.astype(np.uint64)
        )
    with _epoch_stage("rewards_and_penalties"):
        seq_assign_array(state.balances, new_balances.astype(np.uint64))

    electra_active = spec.electra_enabled(cur)
    with _epoch_stage("registry_updates"):
        if electra_active:
            from . import electra as _electra

            _electra.process_registry_updates(
                spec, state, cols=cols, total_active=total_active
            )
        else:
            process_registry_updates(spec, state, cols=cols)

    with _epoch_stage("eth1_reset"):
        process_eth1_data_reset(spec, state)

    if electra_active:
        with _epoch_stage("pending_deposits"):
            _electra.process_pending_deposits(
                spec, state, total_active=total_active
            )
        with _epoch_stage("pending_consolidations"):
            _electra.process_pending_consolidations(spec, state)
        with _epoch_stage("effective_balance"):
            # fresh columns: pending deposits may have grown the
            # registry and moved balances (dirty chunks only)
            _electra.process_effective_balance_updates(spec, state)
    else:
        with _epoch_stage("effective_balance"):
            for i in np.nonzero(eff_mask)[0]:
                seq_get_mut(state.validators, int(i)).effective_balance = int(
                    eff_new[i]
                )

    with _epoch_stage("resets"):
        process_slashings_reset(spec, state)
        process_randao_mixes_reset(spec, state)
        process_historical_roots_update(spec, state)
    with _epoch_stage("participation_rotation"):
        process_participation_flag_updates(state)
    with _epoch_stage("sync_committee"):
        process_sync_committee_updates(spec, state)


def process_justification_and_finalization(
    spec: ChainSpec,
    state,
    total_active: int,
    prev_target_balance: int,
    cur_target_balance: int,
) -> None:
    cur = get_current_epoch(spec, state)
    if cur <= GENESIS_EPOCH + 1:
        return
    prev = get_previous_epoch(spec, state)
    old_prev_justified = state.previous_justified_checkpoint
    old_cur_justified = state.current_justified_checkpoint

    state.previous_justified_checkpoint = state.current_justified_checkpoint
    bits = list(state.justification_bits)
    bits = [False] + bits[:3]
    if prev_target_balance * 3 >= total_active * 2:
        state.current_justified_checkpoint = T.Checkpoint.make(
            epoch=prev, root=get_block_root(spec, state, prev)
        )
        bits[1] = True
    if cur_target_balance * 3 >= total_active * 2:
        state.current_justified_checkpoint = T.Checkpoint.make(
            epoch=cur, root=get_block_root(spec, state, cur)
        )
        bits[0] = True
    state.justification_bits = bits

    # finalization rules (2nd/4th cases use the pre-update checkpoints)
    if all(bits[1:4]) and old_prev_justified.epoch + 3 == cur:
        state.finalized_checkpoint = old_prev_justified
    if all(bits[1:3]) and old_prev_justified.epoch + 2 == cur:
        state.finalized_checkpoint = old_prev_justified
    if all(bits[0:3]) and old_cur_justified.epoch + 2 == cur:
        state.finalized_checkpoint = old_cur_justified
    if all(bits[0:2]) and old_cur_justified.epoch + 1 == cur:
        state.finalized_checkpoint = old_cur_justified


def is_in_inactivity_leak(spec: ChainSpec, state) -> bool:
    return (
        get_previous_epoch(spec, state) - state.finalized_checkpoint.epoch
        > spec.min_epochs_to_inactivity_penalty
    )


def process_registry_updates(
    spec: ChainSpec, state, cols: EpochColumns = None
) -> None:
    """Vectorized registry pass: mask scans over the epoch columns
    replace the per-validator Python loop; the churn-limited exit queue
    is replayed sequentially over just the ejected cohort (spec
    initiate_validator_exit semantics, without its O(n) rescan per
    ejection)."""
    cols = cols or EpochColumns(state)
    cur = get_current_epoch(spec, state)
    # eligibility scan
    elig_idx = np.nonzero(
        (cols.eligibility == _EPOCH_CLAMP)
        & (cols.eff == spec.max_effective_balance)
    )[0]
    for i in elig_idx:
        seq_get_mut(state.validators, int(i)).activation_eligibility_epoch = (
            cur + 1
        )
    # ejection sweep, ascending index order as the spec loop visits it
    active_cur = (cols.activation <= cur) & (cur < cols.exit_epoch)
    churn_limit = max(
        spec.min_per_epoch_churn_limit,
        int(active_cur.sum()) // spec.churn_limit_quotient,
    )
    eject_idx = np.nonzero(
        active_cur
        & (cols.eff <= spec.ejection_balance)
        & (cols.exit_epoch == _EPOCH_CLAMP)
    )[0]
    if len(eject_idx):
        real_exits = cols.exit_epoch[cols.exit_epoch != _EPOCH_CLAMP]
        queue_epoch = cur + 1 + spec.max_seed_lookahead
        queue_churn = 0
        if len(real_exits):
            top = int(real_exits.max())
            if top >= queue_epoch:
                queue_epoch = top
                queue_churn = int((real_exits == top).sum())
        for i in eject_idx:
            if queue_churn >= churn_limit:
                queue_epoch += 1
                queue_churn = 0
            v = seq_get_mut(state.validators, int(i))
            v.exit_epoch = queue_epoch
            v.withdrawable_epoch = (
                queue_epoch + spec.min_validator_withdrawability_delay
            )
            queue_churn += 1
    # activation queue, FIFO by (eligibility epoch, index), churn-
    # limited. Re-read eligibility after the eligibility writes above
    # (dirty chunks only) so the queue sees exactly what the one-pass
    # spec loop sees; ejections never touch eligibility, so they don't
    # force a rebuild.
    elig = (
        EpochColumns(state).eligibility if len(elig_idx) else cols.eligibility
    )
    q_idx = np.nonzero(
        (elig <= int(state.finalized_checkpoint.epoch))
        & (cols.activation == _EPOCH_CLAMP)
    )[0]
    if len(q_idx):
        order = q_idx[np.argsort(elig[q_idx], kind="stable")]
        for i in order[:churn_limit]:
            seq_get_mut(state.validators, int(i)).activation_epoch = (
                cur + 1 + spec.max_seed_lookahead
            )


def process_eth1_data_reset(spec: ChainSpec, state) -> None:
    next_epoch = get_current_epoch(spec, state) + 1
    if next_epoch % spec.preset.epochs_per_eth1_voting_period == 0:
        state.eth1_data_votes = []


def apply_effective_balance_hysteresis(spec: ChainSpec, state, cols, cap) -> None:
    """Shared hysteresis pass (phase0 + electra): `cap` is a scalar
    (flat MAX_EFFECTIVE_BALANCE) or a per-validator array (electra's
    compounding-vs-eth1 caps); the masked decision and writeback are
    identical either way."""
    inc = spec.effective_balance_increment
    hysteresis_increment = inc // 4
    downward = hysteresis_increment  # HYSTERESIS_DOWNWARD_MULTIPLIER = 1
    upward = hysteresis_increment * 2  # HYSTERESIS_UPWARD_MULTIPLIER = 2
    balances = cols.balances.astype(np.int64)
    eff = cols.eff.astype(np.int64)
    mask = ((balances + downward) < eff) | ((eff + upward) < balances)
    new_eff = np.minimum(balances - balances % inc, cap)
    for i in np.nonzero(mask)[0]:
        seq_get_mut(state.validators, int(i)).effective_balance = int(
            new_eff[i]
        )


def process_effective_balance_updates(
    spec: ChainSpec, state, cols: EpochColumns = None
) -> None:
    cols = cols or EpochColumns(state)
    apply_effective_balance_hysteresis(
        spec, state, cols, spec.max_effective_balance
    )


def process_slashings_reset(spec: ChainSpec, state) -> None:
    next_epoch = get_current_epoch(spec, state) + 1
    state.slashings[next_epoch % spec.preset.epochs_per_slashings_vector] = 0


def process_randao_mixes_reset(spec: ChainSpec, state) -> None:
    cur = get_current_epoch(spec, state)
    next_epoch = cur + 1
    state.randao_mixes[
        next_epoch % spec.preset.epochs_per_historical_vector
    ] = get_randao_mix(spec, state, cur)


def _state_field_type(name: str):
    return dict(T.BeaconState.fields)[name]


def process_historical_roots_update(spec: ChainSpec, state) -> None:
    """Capella+ accumulates HistoricalSummary records (the pre-Capella
    historical_roots list is frozen, per_epoch_processing historical
    summaries update)."""
    next_epoch = get_current_epoch(spec, state) + 1
    epochs_per_period = (
        spec.preset.slots_per_historical_root // spec.preset.slots_per_epoch
    )
    if next_epoch % epochs_per_period == 0:
        summary = T.HistoricalSummary.make(
            block_summary_root=_state_field_type("block_roots").hash_tree_root(
                state.block_roots
            ),
            state_summary_root=_state_field_type("state_roots").hash_tree_root(
                state.state_roots
            ),
        )
        state.historical_summaries = list(state.historical_summaries) + [summary]


def process_participation_flag_updates(state) -> None:
    # rotate by rebinding: current loses its only other reference, so
    # handing the object over (no list() rebuild) is safe and keeps the
    # ChunkedSeq spine + chunk-root caches intact
    state.previous_epoch_participation = state.current_epoch_participation
    state.current_epoch_participation = [0] * len(state.validators)


def process_sync_committee_updates(spec: ChainSpec, state) -> None:
    next_epoch = get_current_epoch(spec, state) + 1
    if next_epoch % spec.preset.epochs_per_sync_committee_period == 0:
        state.current_sync_committee = state.next_sync_committee
        state.next_sync_committee = get_next_sync_committee(spec, state)


def mock_execution_payload(spec: ChainSpec, state):
    """A payload consistent with `state` (advanced to the block's slot)
    that process_execution_payload/process_withdrawals will accept — the
    MockExecutionLayer role (execution_layer/src/test_utils in the
    reference): parent linked to the state's header, fresh fake block
    hash, expected withdrawals included. Replaced by engine-API
    get_payload when a real EL is attached."""
    parent = bytes(state.latest_execution_payload_header.block_hash)
    payload = T.ExecutionPayload.make(
        parent_hash=parent,
        prev_randao=get_randao_mix(spec, state, get_current_epoch(spec, state)),
        block_number=state.latest_execution_payload_header.block_number + 1,
        gas_limit=30_000_000,
        timestamp=compute_timestamp_at_slot(spec, state, state.slot),
        block_hash=b"\x00" * 32,
        withdrawals=_expected_withdrawals_for_fork(spec, state),
    )
    # the REAL keccak(rlp(header)) hash (round 4): every payload in the
    # system — mock EL included — carries an EL-derivable block hash, so
    # the import-path hash verification can be unconditional
    # (execution_layer/src/block_hash.rs parity)
    from ..execution.block_hash import calculate_execution_block_hash

    # EIP-4788 parent_beacon_block_root = the root of the block this
    # payload's block will sit ON TOP of — the state's latest header
    # (matches the block.parent_root the import path verifies against)
    payload.block_hash, _ = calculate_execution_block_hash(
        payload, state.latest_block_header.hash_tree_root()
    )
    return payload


def _expected_withdrawals_for_fork(spec: ChainSpec, state) -> list:
    """The fork-correct expectation (a produced payload must match what
    process_withdrawals will demand, incl. electra pending partials)."""
    if spec.electra_enabled(get_current_epoch(spec, state)):
        from . import electra

        withdrawals, _ = electra.get_expected_withdrawals(spec, state)
        return withdrawals
    return get_expected_withdrawals(spec, state)


# ---------------------------------------------------------------- genesis


def empty_genesis_shell(spec: ChainSpec, genesis_time: int = 0):
    """A structurally-initialized genesis state with NO validators:
    shared base for the interop path and the deposit-contract path."""
    state = T.BeaconState.default()
    state.genesis_time = genesis_time
    state.fork = T.Fork.make(
        previous_version=spec.genesis_fork_version,
        current_version=spec.genesis_fork_version,
        epoch=GENESIS_EPOCH,
    )
    state.latest_block_header = T.BeaconBlockHeader.make(
        body_root=T.BeaconBlockBody.default().hash_tree_root()
    )
    state.randao_mixes = [b"\x00" * 32] * spec.preset.epochs_per_historical_vector
    state.block_roots = [b"\x00" * 32] * spec.preset.slots_per_historical_root
    state.state_roots = [b"\x00" * 32] * spec.preset.slots_per_historical_root
    state.slashings = [0] * spec.preset.epochs_per_slashings_vector
    state.justification_bits = [False] * 4
    # "no deposit requests seen yet" is the max-uint sentinel, NOT 0:
    # a legitimate first DepositRequest can carry index 0, and the
    # legacy-eth1 shutoff in process_operations keys off this field
    from .electra import UNSET_DEPOSIT_REQUESTS_START_INDEX

    state.electra.deposit_requests_start_index = (
        UNSET_DEPOSIT_REQUESTS_START_INDEX
    )
    return state


def finalize_genesis_state(spec: ChainSpec, state, el_anchor: bytes = b""):
    """Post-registry genesis finishing: validators root, sync
    committees, and the synthetic post-merge EL anchor (a genesis EL
    block hash so payload parent-hash ancestry is enforced from the
    FIRST block — otherwise is_merge_transition_complete is False and
    slot-1 payload ancestry would go unchecked)."""
    state.genesis_validators_root = _state_field_type(
        "validators"
    ).hash_tree_root(state.validators)
    if state.validators:
        state.current_sync_committee = get_next_sync_committee(spec, state)
        state.next_sync_committee = get_next_sync_committee(spec, state)
    state.latest_execution_payload_header = T.ExecutionPayloadHeader.make(
        block_hash=_hash(
            (el_anchor or b"interop-genesis-el-block")
            + bytes(state.genesis_validators_root)
        ),
        timestamp=state.genesis_time,
    )
    return state


def interop_secret_key(index: int):
    """The canonical interop secret key for `index` (seed = index as 4
    big-endian bytes)."""
    from ..crypto.bls.keys import SecretKey

    return SecretKey.from_seed(index.to_bytes(4, "big"))


def interop_pubkeys(count: int) -> list:
    """The canonical interop key derivation (eth2_interop_keypairs
    role). The ONE definition every caller (CLI, lcli, tests) shares."""
    return [
        interop_secret_key(i).public_key().to_bytes() for i in range(count)
    ]


def interop_genesis_state(
    spec: ChainSpec, pubkeys: list, genesis_time: int = 0
):
    """Deterministic test-net genesis from a pubkey list (the
    eth2_interop_keypairs + interop genesis path the reference's
    BeaconChainHarness uses, test_utils.rs)."""
    state = empty_genesis_shell(spec, genesis_time)

    validators, balances = [], []
    for pk in pubkeys:
        wc = b"\x00" + _hash(bytes(pk))[1:]
        v = _validator_from_deposit(spec, bytes(pk), wc, spec.max_effective_balance)
        v.activation_eligibility_epoch = GENESIS_EPOCH
        v.activation_epoch = GENESIS_EPOCH
        validators.append(v)
        balances.append(spec.max_effective_balance)
    state.validators = validators
    state.balances = balances
    state.previous_epoch_participation = [0] * len(validators)
    state.current_epoch_participation = [0] * len(validators)
    state.inactivity_scores = [0] * len(validators)
    return finalize_genesis_state(spec, state)
