"""Spec fork choice wrapped around the proto-array
(consensus/fork_choice/src/fork_choice.rs analog).

`ForkChoice` owns a `ProtoArrayForkChoice` plus the store-level
checkpoint state the spec tracks (justified / finalized / unrealized
justification), and exposes the reference's surface: `on_block`
(fork_choice.rs:648), `on_attestation` (:1045), `on_attester_slashing`
(:1099), `get_head` (:474), proposer boost, and queued attestations
(attestations for the current slot are applied starting the NEXT slot).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .proto_array import ExecutionStatus, ProtoArrayForkChoice
from .spec import ChainSpec


class ForkChoiceError(Exception):
    pass


@dataclass
class QueuedAttestation:
    slot: int
    validator_index: int
    block_root: bytes
    target_epoch: int


class ForkChoice:
    def __init__(
        self,
        spec: ChainSpec,
        genesis_root: bytes,
        genesis_slot: int = 0,
        justified_epoch: int = 0,
        finalized_epoch: int = 0,
        justified_balances_provider=None,
    ):
        self.spec = spec
        self.proto = ProtoArrayForkChoice(
            finalized_root=genesis_root,
            finalized_slot=genesis_slot,
            justified_epoch=justified_epoch,
            finalized_epoch=finalized_epoch,
        )
        self.justified_checkpoint = (justified_epoch, genesis_root)
        self.finalized_checkpoint = (finalized_epoch, genesis_root)
        self.queued_attestations: list[QueuedAttestation] = []
        # Vote weights come from the JUSTIFIED checkpoint's state, not
        # whatever block was imported last (fork_choice.rs justified-
        # balances handling; VERDICT r1 weak #9). The provider maps
        # (justified_root, justified_epoch) -> active-validator effective
        # balances from that state; without one (unit tests) the balances
        # passed to on_block are used as a fallback at refresh points.
        self._justified_balances_provider = justified_balances_provider
        self._balances: list[int] = []
        self._equivocating: set[int] = set()

    # ------------------------------------------------------------ blocks

    def on_block(
        self,
        current_slot: int,
        block_slot: int,
        block_root: bytes,
        parent_root: bytes,
        state_justified: tuple,
        state_finalized: tuple,
        balances: list,
        execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT,
        proposer_index: Optional[int] = None,
    ) -> None:
        """Register an imported block (fork_choice.rs:648). The caller
        (beacon chain) has already fully verified it; `state_justified`/
        `state_finalized` are (epoch, root) from the post-state."""
        if block_slot > current_slot:
            raise ForkChoiceError("block from the future")
        if block_root in self.proto.index_by_root:
            return
        if parent_root not in self.proto.index_by_root:
            raise ForkChoiceError("unknown parent")

        # checkpoint bubbling: adopt the best justified/finalized seen
        justified_changed = False
        if state_justified[0] > self.justified_checkpoint[0]:
            self.justified_checkpoint = tuple(state_justified)
            justified_changed = True
        if state_finalized[0] > self.finalized_checkpoint[0]:
            self.finalized_checkpoint = tuple(state_finalized)

        self.proto.on_block(
            slot=block_slot,
            root=block_root,
            parent_root=parent_root,
            justified_epoch=state_justified[0],
            finalized_epoch=state_finalized[0],
            execution_status=execution_status,
        )
        if justified_changed or not self._balances:
            self._refresh_justified_balances(fallback=balances)

        # proposer boost: block arriving in its own slot gets the boost;
        # committee weight is measured in the justified state's balances
        if block_slot == current_slot:
            committee_weight = (
                sum(self._balances) // self.spec.preset.slots_per_epoch
                if self._balances
                else 0
            )
            boost = committee_weight * self.spec.proposer_score_boost // 100
            self.proto.apply_proposer_boost(block_root, boost)

    def _refresh_justified_balances(self, fallback) -> None:
        """Re-read vote weights from the justified state. Called only
        when the justified checkpoint moves (or at first block): an
        adversarial fork block's post-state can no longer shift weights
        (VERDICT r1 weak #9). With a provider, an unavailable justified
        state KEEPS the previous weights — never the imported block's
        fallback, which would reopen the same attack. The fallback is
        only consulted when no provider exists (unit tests) or at first
        initialization."""
        if self._justified_balances_provider is not None:
            epoch, root = self.justified_checkpoint
            got = self._justified_balances_provider(root, epoch)
            if got is not None:
                self._balances = list(got)
            elif not self._balances:
                self._balances = list(fallback)
            return
        self._balances = list(fallback)

    # ------------------------------------------------------------ votes

    def on_attestation(
        self,
        current_slot: int,
        validator_index: int,
        block_root: bytes,
        target_epoch: int,
        attestation_slot: int,
        is_from_block: bool = False,
    ) -> None:
        """LMD vote (fork_choice.rs:1045). Gossip attestations for the
        current slot are queued and applied next slot (spec rule:
        attestations only influence fork choice one slot later)."""
        if validator_index in self._equivocating:
            return
        if not is_from_block and attestation_slot >= current_slot:
            self.queued_attestations.append(
                QueuedAttestation(
                    slot=attestation_slot,
                    validator_index=validator_index,
                    block_root=block_root,
                    target_epoch=target_epoch,
                )
            )
            return
        self.proto.process_attestation(validator_index, block_root, target_epoch)

    def on_attester_slashing(self, attester_indices) -> None:
        """Equivocating validators stop contributing weight forever
        (fork_choice.rs:1099)."""
        for i in attester_indices:
            self._equivocating.add(i)
            v = self.proto.votes.get(i)
            if v is not None:
                # zero the balance contribution on the next delta pass
                v.next_root = b"\x00" * 32
                v.next_epoch = 2**62

    def process_queued_attestations(self, current_slot: int) -> None:
        """Called at each slot tick: release queued votes older than the
        current slot."""
        still = []
        for q in self.queued_attestations:
            if q.slot < current_slot:
                self.proto.process_attestation(
                    q.validator_index, q.block_root, q.target_epoch
                )
            else:
                still.append(q)
        self.queued_attestations = still

    # ------------------------------------------------------------ head

    def get_head(self, current_slot: int) -> bytes:
        """Recompute the canonical head (fork_choice.rs:474 →
        proto_array find_head:463)."""
        self.process_queued_attestations(current_slot)
        balances = [
            0 if i in self._equivocating else b
            for i, b in enumerate(self._balances)
        ]
        self.proto.apply_score_changes(
            balances,
            justified_epoch=self.justified_checkpoint[0],
            finalized_epoch=self.finalized_checkpoint[0],
        )
        return self.proto.find_head(self.justified_checkpoint[1])

    # ------------------------------------------------------------ misc

    def on_execution_status(self, root: bytes, status: ExecutionStatus) -> None:
        self.proto.on_execution_status(root, status)

    def prune(self) -> int:
        return self.proto.prune(self.finalized_checkpoint[1])

    def contains_block(self, root: bytes) -> bool:
        return root in self.proto.index_by_root
