"""SignatureSet constructors — every signed consensus object becomes a
batchable (signature, pubkeys, message) triple.

Mirror of consensus/state_processing/src/per_block_processing/
signature_sets.rs:74-609 (16 constructors) — the producers that feed the
TPU batch verifier. Each returns a crypto.bls SignatureSet (or a list of
them); `BlockSignatureVerifier` accumulates all of a block's sets and
verifies them in ONE batch (block_signature_verifier.rs:127-138).

Pubkey resolution goes through a caller-supplied `get_pubkey(index) ->
PublicKey` (the decompressed-pubkey-cache seam,
validator_pubkey_cache.rs:138).
"""

from __future__ import annotations

from typing import Callable, Sequence

from ..crypto import bls
from ..crypto.bls.keys import PublicKey, Signature, SignatureSet
from . import types as T
from .domains import (
    compute_domain,
    compute_signing_root,
    get_domain,
    voluntary_exit_domain,
)
from .spec import ChainSpec


class SignatureSetError(Exception):
    pass


def _sig(sig_bytes: bytes) -> Signature:
    return Signature.from_bytes(bytes(sig_bytes))


def _epoch_of_slot(spec: ChainSpec, slot: int) -> int:
    return slot // spec.preset.slots_per_epoch


# -- 1: block proposal (signature_sets.rs block_proposal_signature_set)


def block_proposal_signature_set(
    spec: ChainSpec,
    get_pubkey: Callable[[int], PublicKey],
    signed_block,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    block = signed_block.message
    epoch = _epoch_of_slot(spec, block.slot)
    domain = get_domain(
        spec, spec.domain_beacon_proposer, epoch, fork, genesis_validators_root
    )
    message = compute_signing_root(block, domain)
    return SignatureSet.single_pubkey(
        _sig(signed_block.signature), get_pubkey(block.proposer_index), message
    )


# -- 2: block header (for proposer slashings)


def block_header_signature_set(
    spec: ChainSpec,
    get_pubkey,
    signed_header,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    header = signed_header.message
    epoch = _epoch_of_slot(spec, header.slot)
    domain = get_domain(
        spec, spec.domain_beacon_proposer, epoch, fork, genesis_validators_root
    )
    message = compute_signing_root(header, domain)
    return SignatureSet.single_pubkey(
        _sig(signed_header.signature), get_pubkey(header.proposer_index), message
    )


# -- 3: randao reveal


def randao_signature_set(
    spec: ChainSpec,
    get_pubkey,
    block,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    epoch = _epoch_of_slot(spec, block.slot)
    domain = get_domain(
        spec, spec.domain_randao, epoch, fork, genesis_validators_root
    )
    message = compute_signing_root(_EpochSSZ(epoch), domain)
    return SignatureSet.single_pubkey(
        _sig(block.body.randao_reveal), get_pubkey(block.proposer_index), message
    )


class _EpochSSZ:
    """uint64 epoch as a signable object (hash_tree_root of the int)."""

    def __init__(self, epoch: int):
        self.epoch = epoch

    def hash_tree_root(self) -> bytes:
        return self.epoch.to_bytes(32, "little")


# -- 4: proposer slashing (two header sets)


def proposer_slashing_signature_sets(
    spec: ChainSpec,
    get_pubkey,
    slashing,
    fork,
    genesis_validators_root: bytes,
) -> list:
    return [
        block_header_signature_set(
            spec, get_pubkey, slashing.signed_header_1, fork, genesis_validators_root
        ),
        block_header_signature_set(
            spec, get_pubkey, slashing.signed_header_2, fork, genesis_validators_root
        ),
    ]


# -- 5/6: indexed attestation (by index, and from resolved pubkeys)


def indexed_attestation_signature_set(
    spec: ChainSpec,
    get_pubkey,
    indexed_att,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    pubkeys = [get_pubkey(i) for i in indexed_att.attesting_indices]
    return indexed_attestation_signature_set_from_pubkeys(
        spec, pubkeys, indexed_att, fork, genesis_validators_root
    )


def indexed_attestation_signature_set_from_pubkeys(
    spec: ChainSpec,
    pubkeys: Sequence[PublicKey],
    indexed_att,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    data = indexed_att.data
    domain = get_domain(
        spec,
        spec.domain_beacon_attester,
        data.target.epoch,
        fork,
        genesis_validators_root,
    )
    message = compute_signing_root(data, domain)
    return SignatureSet.multiple_pubkeys(
        _sig(indexed_att.signature), pubkeys, message
    )


# -- 7: attester slashing (two indexed attestation sets)


def attester_slashing_signature_sets(
    spec: ChainSpec,
    get_pubkey,
    slashing,
    fork,
    genesis_validators_root: bytes,
) -> list:
    return [
        indexed_attestation_signature_set(
            spec, get_pubkey, slashing.attestation_1, fork, genesis_validators_root
        ),
        indexed_attestation_signature_set(
            spec, get_pubkey, slashing.attestation_2, fork, genesis_validators_root
        ),
    ]


# -- 8: deposit (genesis-fork domain, pubkey from the deposit itself)


def deposit_signature_set(spec: ChainSpec, deposit_data) -> SignatureSet:
    message_obj = T.DepositMessage.make(
        pubkey=deposit_data.pubkey,
        withdrawal_credentials=deposit_data.withdrawal_credentials,
        amount=deposit_data.amount,
    )
    domain = compute_domain(
        spec.domain_deposit, spec.genesis_fork_version, b"\x00" * 32
    )
    message = compute_signing_root(message_obj, domain)
    return SignatureSet.single_pubkey(
        _sig(deposit_data.signature),
        PublicKey.from_bytes(bytes(deposit_data.pubkey)),
        message,
    )


# -- 9: voluntary exit


def exit_signature_set(
    spec: ChainSpec,
    get_pubkey,
    signed_exit,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    exit_msg = signed_exit.message
    # EIP-7044: Deneb+ states pin the Capella fork version for exits
    domain = voluntary_exit_domain(
        spec, exit_msg.epoch, fork, genesis_validators_root
    )
    message = compute_signing_root(exit_msg, domain)
    return SignatureSet.single_pubkey(
        _sig(signed_exit.signature), get_pubkey(exit_msg.validator_index), message
    )


# -- 10: aggregate selection proof (slot signature)


def signed_aggregate_selection_proof_signature_set(
    spec: ChainSpec,
    get_pubkey,
    signed_aggregate,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    msg = signed_aggregate.message
    slot = msg.aggregate.data.slot
    domain = get_domain(
        spec,
        spec.domain_selection_proof,
        _epoch_of_slot(spec, slot),
        fork,
        genesis_validators_root,
    )
    message = compute_signing_root(_EpochSSZ(slot), domain)
    return SignatureSet.single_pubkey(
        _sig(msg.selection_proof), get_pubkey(msg.aggregator_index), message
    )


# -- 11: aggregate-and-proof wrapper signature


def signed_aggregate_signature_set(
    spec: ChainSpec,
    get_pubkey,
    signed_aggregate,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    msg = signed_aggregate.message
    slot = msg.aggregate.data.slot
    domain = get_domain(
        spec,
        spec.domain_aggregate_and_proof,
        _epoch_of_slot(spec, slot),
        fork,
        genesis_validators_root,
    )
    message = compute_signing_root(msg, domain)
    return SignatureSet.single_pubkey(
        _sig(signed_aggregate.signature), get_pubkey(msg.aggregator_index), message
    )


# -- 12: sync committee message


def sync_committee_message_set(
    spec: ChainSpec,
    get_pubkey,
    validator_index: int,
    slot: int,
    beacon_block_root: bytes,
    signature_bytes: bytes,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    domain = get_domain(
        spec,
        spec.domain_sync_committee,
        _epoch_of_slot(spec, slot),
        fork,
        genesis_validators_root,
    )
    message = compute_signing_root(_Bytes32SSZ(beacon_block_root), domain)
    return SignatureSet.single_pubkey(
        _sig(signature_bytes), get_pubkey(validator_index), message
    )


class _Bytes32SSZ:
    def __init__(self, data: bytes):
        self.data = bytes(data)

    def hash_tree_root(self) -> bytes:
        return self.data


# -- 13: sync committee contribution (aggregate over subcommittee)


def sync_committee_contribution_signature_set(
    spec: ChainSpec,
    pubkeys: Sequence[PublicKey],
    contribution,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    domain = get_domain(
        spec,
        spec.domain_sync_committee,
        _epoch_of_slot(spec, contribution.slot),
        fork,
        genesis_validators_root,
    )
    message = compute_signing_root(
        _Bytes32SSZ(contribution.beacon_block_root), domain
    )
    return SignatureSet.multiple_pubkeys(
        _sig(contribution.signature), pubkeys, message
    )


# -- 14: sync aggregator selection proof


def signed_sync_aggregate_selection_proof_signature_set(
    spec: ChainSpec,
    get_pubkey,
    signed_contribution,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    msg = signed_contribution.message
    selection_data = T.SyncAggregatorSelectionData.make(
        slot=msg.contribution.slot,
        subcommittee_index=msg.contribution.subcommittee_index,
    )
    domain = get_domain(
        spec,
        spec.domain_sync_committee_selection_proof,
        _epoch_of_slot(spec, msg.contribution.slot),
        fork,
        genesis_validators_root,
    )
    message = compute_signing_root(selection_data, domain)
    return SignatureSet.single_pubkey(
        _sig(msg.selection_proof), get_pubkey(msg.aggregator_index), message
    )


# -- 15: signed contribution-and-proof wrapper


def signed_sync_aggregate_signature_set(
    spec: ChainSpec,
    get_pubkey,
    signed_contribution,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet:
    msg = signed_contribution.message
    domain = get_domain(
        spec,
        spec.domain_contribution_and_proof,
        _epoch_of_slot(spec, msg.contribution.slot),
        fork,
        genesis_validators_root,
    )
    message = compute_signing_root(msg, domain)
    return SignatureSet.single_pubkey(
        _sig(signed_contribution.signature),
        get_pubkey(msg.aggregator_index),
        message,
    )


# -- 16: sync aggregate in a block + bls-to-execution-change


def sync_aggregate_signature_set(
    spec: ChainSpec,
    pubkeys: Sequence[PublicKey],
    sync_aggregate,
    slot: int,
    previous_block_root: bytes,
    fork,
    genesis_validators_root: bytes,
) -> SignatureSet | None:
    """The block-embedded sync aggregate signs the PREVIOUS slot's block
    root. Returns None when no bits are set and the signature is the
    point at infinity (valid empty aggregate)."""
    if not any(sync_aggregate.sync_committee_bits):
        sig = Signature.from_bytes(bytes(sync_aggregate.sync_committee_signature))
        if sig.is_infinity():
            return None
        raise SignatureSetError("non-infinity signature with empty bits")
    prev_slot = max(slot - 1, 0)
    domain = get_domain(
        spec,
        spec.domain_sync_committee,
        _epoch_of_slot(spec, prev_slot),
        fork,
        genesis_validators_root,
    )
    message = compute_signing_root(_Bytes32SSZ(previous_block_root), domain)
    return SignatureSet.multiple_pubkeys(
        _sig(sync_aggregate.sync_committee_signature), pubkeys, message
    )


def bls_execution_change_signature_set(
    spec: ChainSpec, signed_change, genesis_validators_root: bytes
) -> SignatureSet:
    """Signed with the GENESIS fork version regardless of current fork
    (capella rule), keyed by the change's own BLS pubkey."""
    domain = compute_domain(
        spec.domain_bls_to_execution_change,
        spec.genesis_fork_version,
        genesis_validators_root,
    )
    message = compute_signing_root(signed_change.message, domain)
    return SignatureSet.single_pubkey(
        _sig(signed_change.signature),
        PublicKey.from_bytes(bytes(signed_change.message.from_bls_pubkey)),
        message,
    )


# ---------------------------------------------------------------- verifier


class BlockSignatureVerifier:
    """Accumulate every signature set in a block, verify in one batch
    (block_signature_verifier.rs:73-397 analog). `include_*` mirror the
    reference's composition; `verify()` funnels into
    bls.verify_signature_sets — CPU or TPU backend."""

    def __init__(self, spec: ChainSpec, get_pubkey, fork, genesis_validators_root):
        self.spec = spec
        self.get_pubkey = get_pubkey
        self.fork = fork
        self.gvr = genesis_validators_root
        self.sets: list[SignatureSet] = []

    def include_block_proposal(self, signed_block):
        self.sets.append(
            block_proposal_signature_set(
                self.spec, self.get_pubkey, signed_block, self.fork, self.gvr
            )
        )

    def include_randao_reveal(self, block):
        self.sets.append(
            randao_signature_set(
                self.spec, self.get_pubkey, block, self.fork, self.gvr
            )
        )

    def include_proposer_slashings(self, block):
        for sl in block.body.proposer_slashings:
            self.sets.extend(
                proposer_slashing_signature_sets(
                    self.spec, self.get_pubkey, sl, self.fork, self.gvr
                )
            )

    def include_attester_slashings(self, block):
        for sl in block.body.attester_slashings:
            self.sets.extend(
                attester_slashing_signature_sets(
                    self.spec, self.get_pubkey, sl, self.fork, self.gvr
                )
            )

    def include_attestations(self, block, indexed_by_attestation):
        """indexed_by_attestation: att -> IndexedAttestation (committee
        resolution is the state's job, attestation->indices)."""
        for att in block.body.attestations:
            self.sets.append(
                indexed_attestation_signature_set(
                    self.spec,
                    self.get_pubkey,
                    indexed_by_attestation(att),
                    self.fork,
                    self.gvr,
                )
            )

    def include_exits(self, block):
        for ex in block.body.voluntary_exits:
            self.sets.append(
                exit_signature_set(
                    self.spec, self.get_pubkey, ex, self.fork, self.gvr
                )
            )

    def include_sync_aggregate(self, block, sync_pubkeys, previous_block_root):
        s = sync_aggregate_signature_set(
            self.spec,
            sync_pubkeys,
            block.body.sync_aggregate,
            block.slot,
            previous_block_root,
            self.fork,
            self.gvr,
        )
        if s is not None:
            self.sets.append(s)

    def include_bls_to_execution_changes(self, block):
        for ch in block.body.bls_to_execution_changes:
            self.sets.append(
                bls_execution_change_signature_set(self.spec, ch, self.gvr)
            )

    def include_all(self, spec: ChainSpec, state, signed_block):
        """Everything verify_entire_block batches
        (block_signature_verifier.rs:127-138): proposal, randao, both
        slashing kinds, attestations (committee-resolved against
        `state`, already advanced to the block's slot), exits, the sync
        aggregate, and bls-to-execution changes."""
        from . import state_transition as st
        from . import types as T

        block = signed_block.message
        self.include_block_proposal(signed_block)
        self.include_randao_reveal(block)
        self.include_proposer_slashings(block)
        self.include_attester_slashings(block)

        def indexed(att):
            indices = sorted(st.get_attesting_indices(spec, state, att))
            return T.IndexedAttestation.make(
                attesting_indices=indices,
                data=att.data,
                signature=bytes(att.signature),
            )

        self.include_attestations(block, indexed)
        self.include_exits(block)
        sync_pubkeys = [
            self.get_pubkey_bytes(bytes(pk))
            for pk, bit in zip(
                state.current_sync_committee.pubkeys,
                block.body.sync_aggregate.sync_committee_bits,
            )
            if bit
        ]
        prev_slot = max(block.slot - 1, 0)
        prev_root = st.get_block_root_at_slot(spec, state, prev_slot)
        self.include_sync_aggregate(block, sync_pubkeys, prev_root)
        self.include_bls_to_execution_changes(block)

    def get_pubkey_bytes(self, pubkey_bytes: bytes) -> PublicKey:
        """Resolve a raw compressed pubkey (sync committee members are
        stored by bytes, not index)."""
        return PublicKey.from_bytes(pubkey_bytes)

    def verify(self, backend: str = None) -> bool:
        """ALL of the block's signatures in ONE verify_signature_sets
        call (ParallelSignatureSets::verify,
        block_signature_verifier.rs:380-397)."""
        if not self.sets:
            return True
        return bls.verify_signature_sets(self.sets, backend=backend)
