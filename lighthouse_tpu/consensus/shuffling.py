"""Swap-or-not committee shuffling (consensus/swap_or_not_shuffle analog).

Implements the spec's compute_shuffled_index and the whole-list
single-pass shuffle the reference benches
(consensus/swap_or_not_shuffle/benches/benches.rs), plus committee
assignment helpers built on it.
"""

import hashlib


def _hash(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def compute_shuffled_index(
    index: int, index_count: int, seed: bytes, rounds: int
) -> int:
    """Spec swap-or-not network, one index at a time."""
    assert 0 <= index < index_count
    for r in range(rounds):
        pivot = (
            int.from_bytes(_hash(seed + bytes([r]))[:8], "little") % index_count
        )
        flip = (pivot + index_count - index) % index_count
        position = max(index, flip)
        source = _hash(
            seed + bytes([r]) + (position // 256).to_bytes(4, "little")
        )
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) & 1:
            index = flip
    return index


def shuffle_list(indices: list, seed: bytes, rounds: int) -> list:
    """Whole-list shuffle: shuffled[i] = indices[shuffled_index(i)].

    Batched hash reuse per (round, position-block) keeps it O(n * rounds)
    hashes worst case with a small cache; a numpy-vectorized whole-list
    pass (the form the reference optimizes and benches) is a planned
    speedup — semantics fixed by compute_shuffled_index.
    """
    n = len(indices)
    cache = {}

    def src(r: int, block: int) -> bytes:
        key = (r, block)
        if key not in cache:
            cache[key] = _hash(seed + bytes([r]) + block.to_bytes(4, "little"))
        return cache[key]

    pivots = [
        int.from_bytes(_hash(seed + bytes([r]))[:8], "little") % n
        for r in range(rounds)
    ]
    out = []
    for i in range(n):
        idx = i
        for r in range(rounds):
            pivot = pivots[r]
            flip = (pivot + n - idx) % n
            position = max(idx, flip)
            byte = src(r, position // 256)[(position % 256) // 8]
            if (byte >> (position % 8)) & 1:
                idx = flip
        out.append(indices[idx])
    return out


def compute_committee(
    indices: list, seed: bytes, index: int, count: int, rounds: int
) -> list:
    """Slice `index` of `count` committees over the shuffled indices."""
    n = len(indices)
    start = n * index // count
    end = n * (index + 1) // count
    return [
        indices[compute_shuffled_index(i, n, seed, rounds)]
        for i in range(start, end)
    ]
