"""Swap-or-not committee shuffling (consensus/swap_or_not_shuffle analog).

Implements the spec's compute_shuffled_index and the whole-list
single-pass shuffle the reference benches
(consensus/swap_or_not_shuffle/benches/benches.rs), plus committee
assignment helpers built on it.
"""

import hashlib


def _hash(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def compute_shuffled_index(
    index: int, index_count: int, seed: bytes, rounds: int
) -> int:
    """Spec swap-or-not network, one index at a time."""
    assert 0 <= index < index_count
    for r in range(rounds):
        pivot = (
            int.from_bytes(_hash(seed + bytes([r]))[:8], "little") % index_count
        )
        flip = (pivot + index_count - index) % index_count
        position = max(index, flip)
        source = _hash(
            seed + bytes([r]) + (position // 256).to_bytes(4, "little")
        )
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) & 1:
            index = flip
    return index


def shuffle_list(indices: list, seed: bytes, rounds: int) -> list:
    """Whole-list shuffle: shuffled[i] = indices[shuffled_index(i)].

    Batched hash reuse per (round, position-block) keeps it O(n * rounds)
    hashes worst case with a small cache; a numpy-vectorized whole-list
    pass (the form the reference optimizes and benches) is a planned
    speedup — semantics fixed by compute_shuffled_index.
    """
    n = len(indices)
    cache = {}

    def src(r: int, block: int) -> bytes:
        key = (r, block)
        if key not in cache:
            cache[key] = _hash(seed + bytes([r]) + block.to_bytes(4, "little"))
        return cache[key]

    pivots = [
        int.from_bytes(_hash(seed + bytes([r]))[:8], "little") % n
        for r in range(rounds)
    ]
    out = []
    for i in range(n):
        idx = i
        for r in range(rounds):
            pivot = pivots[r]
            flip = (pivot + n - idx) % n
            position = max(idx, flip)
            byte = src(r, position // 256)[(position % 256) // 8]
            if (byte >> (position % 8)) & 1:
                idx = flip
        out.append(indices[idx])
    return out


def shuffle_permutation(n: int, seed: bytes, rounds: int):
    """Vectorized whole-list swap-or-not: perm[i] == compute_shuffled_
    index(i, n, seed, rounds) for all i, as one numpy array.

    Per round: ceil(n/256) source hashes (their 32-byte blocks
    concatenated give global byte pos//8 for position pos) and ~6
    whole-array ops — the form the reference optimizes and benches
    (consensus/swap_or_not_shuffle). 500k validators: ~0.5 s vs minutes
    per-element."""
    import numpy as np

    if n == 0:
        return np.zeros(0, dtype=np.int64)
    idx = np.arange(n, dtype=np.int64)
    nblocks = (n + 255) // 256
    for r in range(rounds):
        pivot = int.from_bytes(_hash(seed + bytes([r]))[:8], "little") % n
        hs = b"".join(
            _hash(seed + bytes([r]) + b.to_bytes(4, "little"))
            for b in range(nblocks)
        )
        hbytes = np.frombuffer(hs, dtype=np.uint8)
        flip = (pivot - idx) % n
        pos = np.maximum(idx, flip)
        bits = (hbytes[pos >> 3] >> (pos & 7).astype(np.uint8)) & 1
        idx = np.where(bits.astype(bool), flip, idx)
    return idx


# (n, seed, rounds) -> permutation array. The permutation is identical
# for every committee of every slot of an epoch (the seed binds epoch +
# domain), so one entry serves ~2048 mainnet committee resolutions —
# without it a 500k-validator slot cost ~10 minutes (round-4 scale
# probe, BASELINE.md §scale). Keyed on pure inputs: safe under state
# mutation. Tiny LRU: epochs roll, two seeds (current+previous) live.
_PERM_CACHE: dict = {}


def _perm_cached(n: int, seed: bytes, rounds: int):
    key = (n, seed, rounds)
    p = _PERM_CACHE.get(key)
    if p is None:
        p = shuffle_permutation(n, seed, rounds)
        while len(_PERM_CACHE) >= 4:
            _PERM_CACHE.pop(next(iter(_PERM_CACHE)))
        _PERM_CACHE[key] = p
    return p


def compute_committee(
    indices: list, seed: bytes, index: int, count: int, rounds: int
) -> list:
    """Slice `index` of `count` committees over the shuffled indices."""
    n = len(indices)
    start = n * index // count
    end = n * (index + 1) // count
    if end - start > 64 or (n, seed, rounds) in _PERM_CACHE:
        perm = _perm_cached(n, seed, rounds)
        return [indices[p] for p in perm[start:end]]
    return [
        indices[compute_shuffled_index(i, n, seed, rounds)]
        for i in range(start, end)
    ]
