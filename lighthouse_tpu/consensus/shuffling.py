"""Swap-or-not committee shuffling (consensus/swap_or_not_shuffle analog).

Implements the spec's compute_shuffled_index and the whole-list
single-pass shuffle the reference benches
(consensus/swap_or_not_shuffle/benches/benches.rs), plus committee
assignment helpers built on it.
"""

import hashlib


def _hash(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def compute_shuffled_index(
    index: int, index_count: int, seed: bytes, rounds: int
) -> int:
    """Spec swap-or-not network, one index at a time."""
    assert 0 <= index < index_count
    for r in range(rounds):
        pivot = (
            int.from_bytes(_hash(seed + bytes([r]))[:8], "little") % index_count
        )
        flip = (pivot + index_count - index) % index_count
        position = max(index, flip)
        source = _hash(
            seed + bytes([r]) + (position // 256).to_bytes(4, "little")
        )
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) & 1:
            index = flip
    return index


def shuffle_list(indices: list, seed: bytes, rounds: int) -> list:
    """Whole-list shuffle: shuffled[i] = indices[shuffled_index(i)].

    Runs as ONE numpy pass over the whole list (shuffle_permutation, the
    form the reference optimizes and benches in
    consensus/swap_or_not_shuffle/benches/benches.rs) — semantics fixed
    by compute_shuffled_index; the permutation is cached on its pure
    inputs so the per-epoch committee sweep pays for it once."""
    if not indices:
        return []
    perm = _perm_cached(len(indices), seed, rounds)
    return [indices[p] for p in perm]


def shuffle_permutation(n: int, seed: bytes, rounds: int):
    """Vectorized whole-list swap-or-not: perm[i] == compute_shuffled_
    index(i, n, seed, rounds) for all i, as one numpy array.

    Per round: ceil(n/256) source hashes (their 32-byte blocks
    concatenated give global byte pos//8 for position pos) and ~6
    whole-array ops — the form the reference optimizes and benches
    (consensus/swap_or_not_shuffle). 500k validators: ~0.5 s vs minutes
    per-element."""
    import numpy as np

    if n == 0:
        return np.zeros(0, dtype=np.int64)
    idx = np.arange(n, dtype=np.int64)
    nblocks = (n + 255) // 256
    for r in range(rounds):
        pivot = int.from_bytes(_hash(seed + bytes([r]))[:8], "little") % n
        hs = b"".join(
            _hash(seed + bytes([r]) + b.to_bytes(4, "little"))
            for b in range(nblocks)
        )
        hbytes = np.frombuffer(hs, dtype=np.uint8)
        flip = (pivot - idx) % n
        pos = np.maximum(idx, flip)
        bits = (hbytes[pos >> 3] >> (pos & 7).astype(np.uint8)) & 1
        idx = np.where(bits.astype(bool), flip, idx)
    return idx


# (n, seed, rounds) -> permutation array. The permutation is identical
# for every committee of every slot of an epoch (the seed binds epoch +
# domain), so one entry serves ~2048 mainnet committee resolutions —
# without it a 500k-validator slot cost ~10 minutes (round-4 scale
# probe, BASELINE.md §scale). Keyed on pure inputs: safe under state
# mutation. Small LRU: current+previous epoch attester seeds plus the
# occasional proposer/sync-committee seed across two fork branches.
_PERM_CACHE: dict = {}
_PERM_CACHE_MAX = 8


def _perm_cached(n: int, seed: bytes, rounds: int):
    key = (n, bytes(seed), rounds)
    p = _PERM_CACHE.get(key)
    if p is None:
        p = shuffle_permutation(n, seed, rounds)
        try:  # FIFO eviction; benign under concurrent evictors (same
            # guard as state_transition's _ACTIVE_CACHE/_TAB_CACHE —
            # two racing misses can pop the same first key)
            while len(_PERM_CACHE) >= _PERM_CACHE_MAX:
                _PERM_CACHE.pop(next(iter(_PERM_CACHE)))
        except (KeyError, StopIteration, RuntimeError):
            pass
        _PERM_CACHE[key] = p
    return p


def compute_committee(
    indices: list, seed: bytes, index: int, count: int, rounds: int
) -> list:
    """Slice `index` of `count` committees over the shuffled indices.
    Always resolved from the cached whole-list permutation: every
    committee of the epoch shares one vectorized shuffle."""
    n = len(indices)
    start = n * index // count
    end = n * (index + 1) // count
    perm = _perm_cached(n, seed, rounds)
    return [indices[p] for p in perm[start:end]]
