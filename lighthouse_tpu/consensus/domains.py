"""Domain separation + signing roots (spec helpers the reference keeps
in consensus/types/src/chain_spec.rs + signing machinery)."""

from . import types as T
from .spec import ChainSpec


def compute_fork_data_root(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return T.ForkData.make(
        current_version=current_version,
        genesis_validators_root=genesis_validators_root,
    ).hash_tree_root()


def compute_fork_digest(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return compute_fork_data_root(current_version, genesis_validators_root)[:4]


def compute_domain(
    domain_type: bytes,
    fork_version: bytes,
    genesis_validators_root: bytes,
) -> bytes:
    root = compute_fork_data_root(fork_version, genesis_validators_root)
    return domain_type + root[:28]


def get_domain(
    spec: ChainSpec,
    domain_type: bytes,
    epoch: int,
    fork,
    genesis_validators_root: bytes,
) -> bytes:
    version = (
        fork.previous_version if epoch < fork.epoch else fork.current_version
    )
    return compute_domain(domain_type, version, genesis_validators_root)


def voluntary_exit_domain(
    spec: ChainSpec,
    exit_epoch: int,
    fork,
    genesis_validators_root: bytes,
    strict: bool = False,
) -> bytes:
    """EIP-7044 exit domain (chain_spec.rs compute_domain handling via
    Fork::Deneb special case in exit_signature_set): from Deneb onward
    the voluntary-exit domain is pinned to the CAPELLA fork version so
    exits remain valid across future forks, regardless of exit epoch.
    Pre-Deneb, the domain follows the fork at the exit epoch as usual.

    The state fork is identified from `fork.current_version`. With
    `strict=True` (the CLI signing path) an unrecognized version is an
    error — it means the local spec doesn't match the node's network
    and the signed exit would be invalid; non-strict callers (node-side
    verification on custom testnets) fall back to the schedule at
    `fork.epoch`.
    """
    by_version = {v: k for k, v in spec.fork_versions.items()}
    version = bytes(fork.current_version)
    if strict and version not in by_version:
        raise ValueError(
            f"fork version 0x{version.hex()} is not in the configured "
            f"spec's fork schedule — wrong --network for this node?"
        )
    state_fork = by_version.get(
        version, spec.fork_name_at_epoch(fork.epoch)
    )
    from .spec import FORK_ORDER

    if FORK_ORDER.index(state_fork) >= FORK_ORDER.index("deneb"):
        return compute_domain(
            spec.domain_voluntary_exit,
            spec.fork_versions["capella"],
            genesis_validators_root,
        )
    return get_domain(
        spec, spec.domain_voluntary_exit, exit_epoch, fork,
        genesis_validators_root,
    )


def compute_signing_root(ssz_value, domain: bytes) -> bytes:
    return T.SigningData.make(
        object_root=ssz_value.hash_tree_root(), domain=domain
    ).hash_tree_root()
