"""Domain separation + signing roots (spec helpers the reference keeps
in consensus/types/src/chain_spec.rs + signing machinery)."""

from . import types as T
from .spec import ChainSpec


def compute_fork_data_root(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return T.ForkData.make(
        current_version=current_version,
        genesis_validators_root=genesis_validators_root,
    ).hash_tree_root()


def compute_fork_digest(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    return compute_fork_data_root(current_version, genesis_validators_root)[:4]


def compute_domain(
    domain_type: bytes,
    fork_version: bytes,
    genesis_validators_root: bytes,
) -> bytes:
    root = compute_fork_data_root(fork_version, genesis_validators_root)
    return domain_type + root[:28]


def get_domain(
    spec: ChainSpec,
    domain_type: bytes,
    epoch: int,
    fork,
    genesis_validators_root: bytes,
) -> bytes:
    version = (
        fork.previous_version if epoch < fork.epoch else fork.current_version
    )
    return compute_domain(domain_type, version, genesis_validators_root)


def compute_signing_root(ssz_value, domain: bytes) -> bytes:
    return T.SigningData.make(
        object_root=ssz_value.hash_tree_root(), domain=domain
    ).hash_tree_root()
