"""Beacon chain SSZ containers (consensus/types analog).

One canonical (Deneb-shaped) set of containers built on consensus.ssz
descriptors. The reference stamps per-fork variants with superstruct
(consensus/types/src/beacon_block.rs); here fork-awareness lives in the
spec's fork schedule + domains, and the container set carries the union
of fields the signature constructors need. Per-fork SSZ-exact variants
are a widening item (tracked for later rounds), not a structural change.
"""

from .ssz import (
    Container,
    List,
    Vector,
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    uint8,
    uint64,
    uint256,
    boolean,
    Bytes4,
    Bytes20,
    Bytes32,
    Bytes48,
    Bytes96,
)
from .spec import MAINNET_PRESET as _P

# ---------------------------------------------------------------- basics

Fork = Container(
    "Fork",
    [
        ("previous_version", Bytes4),
        ("current_version", Bytes4),
        ("epoch", uint64),
    ],
)

ForkData = Container(
    "ForkData",
    [("current_version", Bytes4), ("genesis_validators_root", Bytes32)],
)

SigningData = Container(
    "SigningData", [("object_root", Bytes32), ("domain", Bytes32)]
)

Checkpoint = Container("Checkpoint", [("epoch", uint64), ("root", Bytes32)])

Validator = Container(
    "Validator",
    [
        ("pubkey", Bytes48),
        ("withdrawal_credentials", Bytes32),
        ("effective_balance", uint64),
        ("slashed", boolean),
        ("activation_eligibility_epoch", uint64),
        ("activation_epoch", uint64),
        ("exit_epoch", uint64),
        ("withdrawable_epoch", uint64),
    ],
)

Eth1Data = Container(
    "Eth1Data",
    [
        ("deposit_root", Bytes32),
        ("deposit_count", uint64),
        ("block_hash", Bytes32),
    ],
)

# ---------------------------------------------------------------- attestations

AttestationData = Container(
    "AttestationData",
    [
        ("slot", uint64),
        ("index", uint64),
        ("beacon_block_root", Bytes32),
        ("source", Checkpoint),
        ("target", Checkpoint),
    ],
)

Attestation = Container(
    "Attestation",
    [
        ("aggregation_bits", Bitlist(_P.max_validators_per_committee)),
        ("data", AttestationData),
        ("signature", Bytes96),
        # Electra (EIP-7549): data.index moves to committee_bits; pre-
        # electra this stays all-zero. One committee per attestation in
        # this framework's canonical shape (aggregation_bits stays
        # committee-scoped).
        ("committee_bits", Bitvector(_P.max_committees_per_slot)),
    ],
)

IndexedAttestation = Container(
    "IndexedAttestation",
    [
        ("attesting_indices", List(uint64, _P.max_validators_per_committee)),
        ("data", AttestationData),
        ("signature", Bytes96),
    ],
)

AggregateAndProof = Container(
    "AggregateAndProof",
    [
        ("aggregator_index", uint64),
        ("aggregate", Attestation),
        ("selection_proof", Bytes96),
    ],
)

SignedAggregateAndProof = Container(
    "SignedAggregateAndProof",
    [("message", AggregateAndProof), ("signature", Bytes96)],
)

# ---------------------------------------------------------------- blocks

BeaconBlockHeader = Container(
    "BeaconBlockHeader",
    [
        ("slot", uint64),
        ("proposer_index", uint64),
        ("parent_root", Bytes32),
        ("state_root", Bytes32),
        ("body_root", Bytes32),
    ],
)

SignedBeaconBlockHeader = Container(
    "SignedBeaconBlockHeader",
    [("message", BeaconBlockHeader), ("signature", Bytes96)],
)

ProposerSlashing = Container(
    "ProposerSlashing",
    [
        ("signed_header_1", SignedBeaconBlockHeader),
        ("signed_header_2", SignedBeaconBlockHeader),
    ],
)

AttesterSlashing = Container(
    "AttesterSlashing",
    [
        ("attestation_1", IndexedAttestation),
        ("attestation_2", IndexedAttestation),
    ],
)

DepositData = Container(
    "DepositData",
    [
        ("pubkey", Bytes48),
        ("withdrawal_credentials", Bytes32),
        ("amount", uint64),
        ("signature", Bytes96),
    ],
)

DepositMessage = Container(
    "DepositMessage",
    [
        ("pubkey", Bytes48),
        ("withdrawal_credentials", Bytes32),
        ("amount", uint64),
    ],
)

Deposit = Container(
    "Deposit",
    [("proof", Vector(Bytes32, 33)), ("data", DepositData)],
)

VoluntaryExit = Container(
    "VoluntaryExit", [("epoch", uint64), ("validator_index", uint64)]
)

SignedVoluntaryExit = Container(
    "SignedVoluntaryExit",
    [("message", VoluntaryExit), ("signature", Bytes96)],
)

BLSToExecutionChange = Container(
    "BLSToExecutionChange",
    [
        ("validator_index", uint64),
        ("from_bls_pubkey", Bytes48),
        ("to_execution_address", Bytes20),
    ],
)

SignedBLSToExecutionChange = Container(
    "SignedBLSToExecutionChange",
    [("message", BLSToExecutionChange), ("signature", Bytes96)],
)

SyncAggregate = Container(
    "SyncAggregate",
    [
        ("sync_committee_bits", Bitvector(_P.sync_committee_size)),
        ("sync_committee_signature", Bytes96),
    ],
)

Withdrawal = Container(
    "Withdrawal",
    [
        ("index", uint64),
        ("validator_index", uint64),
        ("address", Bytes20),
        ("amount", uint64),
    ],
)

# EL transactions are opaque SSZ byte lists (engine boundary)
Transaction = ByteList(_P.max_bytes_per_transaction)

# the common (parent_hash .. base_fee_per_gas) prefix of payload/header
_PAYLOAD_PREFIX = [
    ("parent_hash", Bytes32),
    ("fee_recipient", Bytes20),
    ("state_root", Bytes32),
    ("receipts_root", Bytes32),
    ("logs_bloom", ByteVector(_P.bytes_per_logs_bloom)),
    ("prev_randao", Bytes32),
    ("block_number", uint64),
    ("gas_limit", uint64),
    ("gas_used", uint64),
    ("timestamp", uint64),
    ("extra_data", ByteList(_P.max_extra_data_bytes)),
    ("base_fee_per_gas", uint256),
    ("block_hash", Bytes32),
]

# Full payload as carried in block bodies (Deneb shape,
# consensus/types/src/execution_payload.rs)
ExecutionPayload = Container(
    "ExecutionPayload",
    _PAYLOAD_PREFIX
    + [
        ("transactions", List(Transaction, _P.max_transactions_per_payload)),
        ("withdrawals", List(Withdrawal, _P.max_withdrawals_per_payload)),
        ("blob_gas_used", uint64),
        ("excess_blob_gas", uint64),
    ],
)

# Header form kept in the state (and in blinded blocks,
# consensus/types/src/execution_payload_header.rs)
ExecutionPayloadHeader = Container(
    "ExecutionPayloadHeader",
    _PAYLOAD_PREFIX
    + [
        ("transactions_root", Bytes32),
        ("withdrawals_root", Bytes32),
        ("blob_gas_used", uint64),
        ("excess_blob_gas", uint64),
    ],
)


def execution_payload_to_header(payload) -> "ExecutionPayloadHeader":
    """payload -> header: roots replace the variable-size lists
    (ExecutionPayloadHeader::from in the reference)."""
    fields = {name: getattr(payload, name) for name, _ in _PAYLOAD_PREFIX}
    fields["transactions_root"] = List(
        Transaction, _P.max_transactions_per_payload
    ).hash_tree_root(payload.transactions)
    fields["withdrawals_root"] = List(
        Withdrawal, _P.max_withdrawals_per_payload
    ).hash_tree_root(payload.withdrawals)
    fields["blob_gas_used"] = payload.blob_gas_used
    fields["excess_blob_gas"] = payload.excess_blob_gas
    return ExecutionPayloadHeader.make(**fields)

# ------------------------------------------------------- electra (EIP-7251/6110/7002)

DepositRequest = Container(
    "DepositRequest",
    [
        ("pubkey", Bytes48),
        ("withdrawal_credentials", Bytes32),
        ("amount", uint64),
        ("signature", Bytes96),
        ("index", uint64),
    ],
)

WithdrawalRequest = Container(
    "WithdrawalRequest",
    [
        ("source_address", Bytes20),
        ("validator_pubkey", Bytes48),
        ("amount", uint64),
    ],
)

ConsolidationRequest = Container(
    "ConsolidationRequest",
    [
        ("source_address", Bytes20),
        ("source_pubkey", Bytes48),
        ("target_pubkey", Bytes48),
    ],
)

# EL-sourced requests carried in the body (electra
# beacon_block_body.rs execution_requests; limits are the spec's
# MAX_DEPOSIT/WITHDRAWAL/CONSOLIDATION_REQUESTS_PER_PAYLOAD)
ExecutionRequests = Container(
    "ExecutionRequests",
    [
        ("deposits", List(DepositRequest, 8192)),
        ("withdrawals", List(WithdrawalRequest, 16)),
        ("consolidations", List(ConsolidationRequest, 2)),
    ],
)

PendingDeposit = Container(
    "PendingDeposit",
    [
        ("pubkey", Bytes48),
        ("withdrawal_credentials", Bytes32),
        ("amount", uint64),
        ("signature", Bytes96),
        ("slot", uint64),
    ],
)

PendingPartialWithdrawal = Container(
    "PendingPartialWithdrawal",
    [
        ("validator_index", uint64),
        ("amount", uint64),
        ("withdrawable_epoch", uint64),
    ],
)

PendingConsolidation = Container(
    "PendingConsolidation",
    [("source_index", uint64), ("target_index", uint64)],
)

# The electra-only state surface lives in ONE sub-container field so
# the canonical BeaconState keeps its 32-leaf tree (light-client
# gindices 54/55/105 stay valid). DEVIATION from spec-exact SSZ (the
# spec appends 9 top-level fields); documented in SURVEY parity notes.
ElectraStateExtras = Container(
    "ElectraStateExtras",
    [
        ("deposit_requests_start_index", uint64),
        ("deposit_balance_to_consume", uint64),
        ("exit_balance_to_consume", uint64),
        ("earliest_exit_epoch", uint64),
        ("consolidation_balance_to_consume", uint64),
        ("earliest_consolidation_epoch", uint64),
        ("pending_deposits", List(PendingDeposit, 2**27)),
        (
            "pending_partial_withdrawals",
            List(PendingPartialWithdrawal, 2**27),
        ),
        ("pending_consolidations", List(PendingConsolidation, 2**18)),
    ],
)

BeaconBlockBody = Container(
    "BeaconBlockBody",
    [
        ("randao_reveal", Bytes96),
        ("eth1_data", Eth1Data),
        ("graffiti", Bytes32),
        ("proposer_slashings", List(ProposerSlashing, _P.max_proposer_slashings)),
        ("attester_slashings", List(AttesterSlashing, _P.max_attester_slashings)),
        ("attestations", List(Attestation, _P.max_attestations)),
        ("deposits", List(Deposit, _P.max_deposits)),
        ("voluntary_exits", List(SignedVoluntaryExit, _P.max_voluntary_exits)),
        ("sync_aggregate", SyncAggregate),
        ("execution_payload", ExecutionPayload),
        (
            "bls_to_execution_changes",
            List(SignedBLSToExecutionChange, _P.max_bls_to_execution_changes),
        ),
        (
            "blob_kzg_commitments",
            List(Bytes48, _P.max_blob_commitments_per_block),
        ),
        # Electra+: EL-sourced deposit/withdrawal/consolidation requests
        ("execution_requests", ExecutionRequests),
    ],
)

# Blinded variant (builder/MEV flow): the payload is replaced by its
# header. Field-root equality (htr(List) == the stored list root)
# makes htr(BlindedBeaconBlockBody) == htr(BeaconBlockBody) for the
# same content, so a signature over a blinded block commits to the
# revealed full block (consensus/types/src/beacon_block_body.rs
# BlindedBeaconBlockBody via superstruct).
BlindedBeaconBlockBody = Container(
    "BlindedBeaconBlockBody",
    [
        (
            ("execution_payload_header", ExecutionPayloadHeader)
            if n == "execution_payload"
            else (n, t)
        )
        for n, t in BeaconBlockBody.fields
    ],
)

BeaconBlock = Container(
    "BeaconBlock",
    [
        ("slot", uint64),
        ("proposer_index", uint64),
        ("parent_root", Bytes32),
        ("state_root", Bytes32),
        ("body", BeaconBlockBody),
    ],
)

SignedBeaconBlock = Container(
    "SignedBeaconBlock",
    [("message", BeaconBlock), ("signature", Bytes96)],
)

BlindedBeaconBlock = Container(
    "BlindedBeaconBlock",
    [
        ("slot", uint64),
        ("proposer_index", uint64),
        ("parent_root", Bytes32),
        ("state_root", Bytes32),
        ("body", BlindedBeaconBlockBody),
    ],
)

SignedBlindedBeaconBlock = Container(
    "SignedBlindedBeaconBlock",
    [("message", BlindedBeaconBlock), ("signature", Bytes96)],
)


def block_to_blinded(block) -> "BlindedBeaconBlock":
    """Full block -> blinded (payload replaced by its header)."""
    body = block.body
    fields = {}
    for n, _ in BlindedBeaconBlockBody.fields:
        if n == "execution_payload_header":
            fields[n] = execution_payload_to_header(body.execution_payload)
        else:
            fields[n] = getattr(body, n)
    return BlindedBeaconBlock.make(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=bytes(block.parent_root),
        state_root=bytes(block.state_root),
        body=BlindedBeaconBlockBody.make(**fields),
    )


def blinded_to_full(signed_blinded, payload) -> "SignedBeaconBlock":
    """Signed blinded block + revealed payload -> signed full block.
    Raises if the payload does not match the committed header root."""
    msg = signed_blinded.message
    header = msg.body.execution_payload_header
    if ExecutionPayloadHeader.hash_tree_root(
        execution_payload_to_header(payload)
    ) != ExecutionPayloadHeader.hash_tree_root(header):
        raise ValueError("revealed payload does not match blinded header")
    fields = {}
    for n, _ in BeaconBlockBody.fields:
        if n == "execution_payload":
            fields[n] = payload
        else:
            fields[n] = getattr(msg.body, n)
    block = BeaconBlock.make(
        slot=msg.slot,
        proposer_index=msg.proposer_index,
        parent_root=bytes(msg.parent_root),
        state_root=bytes(msg.state_root),
        body=BeaconBlockBody.make(**fields),
    )
    return SignedBeaconBlock.make(
        message=block, signature=bytes(signed_blinded.signature)
    )

# builder registration (builder-specs ValidatorRegistrationV1 message)
ValidatorRegistrationData = Container(
    "ValidatorRegistrationData",
    [
        ("fee_recipient", Bytes20),
        ("gas_limit", uint64),
        ("timestamp", uint64),
        ("pubkey", Bytes48),
    ],
)

# ---------------------------------------------------------------- sync duty

SyncCommitteeMessage = Container(
    "SyncCommitteeMessage",
    [
        ("slot", uint64),
        ("beacon_block_root", Bytes32),
        ("validator_index", uint64),
        ("signature", Bytes96),
    ],
)

SyncCommitteeContribution = Container(
    "SyncCommitteeContribution",
    [
        ("slot", uint64),
        ("beacon_block_root", Bytes32),
        ("subcommittee_index", uint64),
        (
            "aggregation_bits",
            Bitvector(_P.sync_committee_size // _P.sync_committee_subnet_count),
        ),
        ("signature", Bytes96),
    ],
)

ContributionAndProof = Container(
    "ContributionAndProof",
    [
        ("aggregator_index", uint64),
        ("contribution", SyncCommitteeContribution),
        ("selection_proof", Bytes96),
    ],
)

SignedContributionAndProof = Container(
    "SignedContributionAndProof",
    [("message", ContributionAndProof), ("signature", Bytes96)],
)

SyncAggregatorSelectionData = Container(
    "SyncAggregatorSelectionData",
    [("slot", uint64), ("subcommittee_index", uint64)],
)

# cache_root: the two state committees are re-rooted EVERY slot at
# 1,028 compressions each (512 per-pubkey Bytes48 roots + combines) —
# the largest steady-slot line in the PR 11 census — yet rotate once
# per ~256 epochs. The content-keyed cache makes an unchanged
# committee cost 0 compressions (ISSUE 15 satellite).
SyncCommittee = Container(
    "SyncCommittee",
    [
        ("pubkeys", Vector(Bytes48, _P.sync_committee_size)),
        ("aggregate_pubkey", Bytes48),
    ],
    cache_root=True,
)

# ---------------------------------------------------------------- blobs / DA

# Blob = FIELD_ELEMENTS_PER_BLOB 32-byte scalars (Deneb, EIP-4844)
Blob = ByteVector(_P.field_elements_per_blob * 32)

# depth of blob_kzg_commitments[i] in the body merkle tree: 4 bits for
# the 12-field body (padded to 16) + 1 length mix-in + 12 for the
# 4096-limit commitment list = 17 (KZG_COMMITMENT_INCLUSION_PROOF_DEPTH)
KZG_COMMITMENT_INCLUSION_PROOF_DEPTH = 17

BlobSidecar = Container(
    "BlobSidecar",
    [
        ("index", uint64),
        ("blob", Blob),
        ("kzg_commitment", Bytes48),
        ("kzg_proof", Bytes48),
        ("signed_block_header", SignedBeaconBlockHeader),
        (
            "kzg_commitment_inclusion_proof",
            Vector(Bytes32, KZG_COMMITMENT_INCLUSION_PROOF_DEPTH),
        ),
    ],
)

BlobIdentifier = Container(
    "BlobIdentifier", [("block_root", Bytes32), ("index", uint64)]
)

HistoricalSummary = Container(
    "HistoricalSummary",
    [("block_summary_root", Bytes32), ("state_summary_root", Bytes32)],
)



# ---------------------------------------------------------------- state

BeaconState = Container(
    "BeaconState",
    [
        ("genesis_time", uint64),
        ("genesis_validators_root", Bytes32),
        ("slot", uint64),
        ("fork", Fork),
        ("latest_block_header", BeaconBlockHeader),
        ("block_roots", Vector(Bytes32, _P.slots_per_historical_root)),
        ("state_roots", Vector(Bytes32, _P.slots_per_historical_root)),
        ("historical_roots", List(Bytes32, _P.historical_roots_limit)),
        ("eth1_data", Eth1Data),
        ("eth1_data_votes", List(Eth1Data, _P.epochs_per_eth1_voting_period * _P.slots_per_epoch)),
        ("eth1_deposit_index", uint64),
        ("validators", List(Validator, _P.validator_registry_limit)),
        ("balances", List(uint64, _P.validator_registry_limit)),
        ("randao_mixes", Vector(Bytes32, _P.epochs_per_historical_vector)),
        ("slashings", Vector(uint64, _P.epochs_per_slashings_vector)),
        ("previous_epoch_participation", List(uint8, _P.validator_registry_limit)),
        ("current_epoch_participation", List(uint8, _P.validator_registry_limit)),
        ("justification_bits", Bitvector(4)),
        ("previous_justified_checkpoint", Checkpoint),
        ("current_justified_checkpoint", Checkpoint),
        ("finalized_checkpoint", Checkpoint),
        ("inactivity_scores", List(uint64, _P.validator_registry_limit)),
        ("current_sync_committee", SyncCommittee),
        ("next_sync_committee", SyncCommittee),
        # Bellatrix+
        ("latest_execution_payload_header", ExecutionPayloadHeader),
        # Capella+
        ("next_withdrawal_index", uint64),
        ("next_withdrawal_validator_index", uint64),
        ("historical_summaries", List(HistoricalSummary, _P.historical_roots_limit)),
        # Electra+ (ONE sub-container field keeps the 32-leaf state
        # tree; see ElectraStateExtras)
        ("electra", ElectraStateExtras),
    ],
)
