"""Light-client protocol: types, state-proof construction, and the
client-side update verifier (consensus/types light-client containers +
Altair sync-protocol analog; reference consensus/types/src/light_client_
{header,bootstrap,update,finality_update,optimistic_update}.rs).

A light client tracks the chain from block HEADERS plus sync-committee
signatures, using two merkle proofs into the state:

  * next_sync_committee  — state field, depth-5 branch
  * finalized_checkpoint.root — state field sub-tree, depth-6 branch

Generalized indices derive from THIS framework's canonical BeaconState
container (28 fields → 32 leaves): current_sync_committee gindex 54,
next 55, finalized root 105 — numerically equal to mainnet Altair's
because the field count rounds to the same tree width.

Proof construction uses only the public SSZ surface (per-field
hash_tree_root + merkle_branch), no tree internals.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .merkle_proof import merkle_branch, verify_merkle_branch
from .spec import ChainSpec
from .ssz import Bytes32, Bytes96, Container, Vector, uint64
from .types import (
    BeaconBlockHeader,
    BeaconState,
    SyncAggregate,
    SyncCommittee,
)

# ---------------------------------------------------------------- indices

_STATE_FIELDS = [f for f, _ in BeaconState.fields]
_TREE_WIDTH = 1 << (len(_STATE_FIELDS) - 1).bit_length()  # 32
STATE_PROOF_DEPTH = _TREE_WIDTH.bit_length() - 1  # 5

CURRENT_SYNC_COMMITTEE_INDEX = _TREE_WIDTH + _STATE_FIELDS.index(
    "current_sync_committee"
)  # 54
NEXT_SYNC_COMMITTEE_INDEX = _TREE_WIDTH + _STATE_FIELDS.index(
    "next_sync_committee"
)  # 55
# finalized_checkpoint is a 2-field container; .root is leaf 1 of it
FINALIZED_ROOT_INDEX = (
    _TREE_WIDTH + _STATE_FIELDS.index("finalized_checkpoint")
) * 2 + 1  # 105
FINALITY_PROOF_DEPTH = STATE_PROOF_DEPTH + 1  # 6

# ---------------------------------------------------------------- types

LightClientHeader = Container(
    "LightClientHeader", [("beacon", BeaconBlockHeader)]
)

LightClientBootstrap = Container(
    "LightClientBootstrap",
    [
        ("header", LightClientHeader),
        ("current_sync_committee", SyncCommittee),
        (
            "current_sync_committee_branch",
            Vector(Bytes32, STATE_PROOF_DEPTH),
        ),
    ],
)

LightClientUpdate = Container(
    "LightClientUpdate",
    [
        ("attested_header", LightClientHeader),
        ("next_sync_committee", SyncCommittee),
        ("next_sync_committee_branch", Vector(Bytes32, STATE_PROOF_DEPTH)),
        ("finalized_header", LightClientHeader),
        ("finality_branch", Vector(Bytes32, FINALITY_PROOF_DEPTH)),
        ("sync_aggregate", SyncAggregate),
        ("signature_slot", uint64),
    ],
)

LightClientFinalityUpdate = Container(
    "LightClientFinalityUpdate",
    [
        ("attested_header", LightClientHeader),
        ("finalized_header", LightClientHeader),
        ("finality_branch", Vector(Bytes32, FINALITY_PROOF_DEPTH)),
        ("sync_aggregate", SyncAggregate),
        ("signature_slot", uint64),
    ],
)

LightClientOptimisticUpdate = Container(
    "LightClientOptimisticUpdate",
    [
        ("attested_header", LightClientHeader),
        ("sync_aggregate", SyncAggregate),
        ("signature_slot", uint64),
    ],
)

LightClientUpdatesByRangeRequest = Container(
    "LightClientUpdatesByRangeRequest",
    [("start_period", uint64), ("count", uint64)],
)

# ---------------------------------------------------------------- proofs


def _state_field_roots(state) -> list:
    return [
        ftype.hash_tree_root(getattr(state, fname))
        for fname, ftype in BeaconState.fields
    ]


def state_field_branch(state, field_name: str, roots: list = None) -> list:
    """Depth-5 branch proving one state field against the state root.
    Pass precomputed `roots` (_state_field_roots) when deriving several
    branches from one state — hashing the 28 fields dominates."""
    if roots is None:
        roots = _state_field_roots(state)
    return merkle_branch(roots, _TREE_WIDTH, _STATE_FIELDS.index(field_name))


def finality_branch(state, roots: list = None) -> list:
    """Depth-6 branch for finalized_checkpoint.root: one step inside
    the Checkpoint container, then the depth-5 field branch."""
    from .ssz import uint64 as _u64

    cp = state.finalized_checkpoint
    epoch_root = _u64.hash_tree_root(cp.epoch)
    return [epoch_root] + state_field_branch(
        state, "finalized_checkpoint", roots
    )


def header_for_block(block_message) -> "LightClientHeader":
    return LightClientHeader.make(
        beacon=BeaconBlockHeader.make(
            slot=block_message.slot,
            proposer_index=block_message.proposer_index,
            parent_root=bytes(block_message.parent_root),
            state_root=bytes(block_message.state_root),
            body_root=block_message.body.hash_tree_root(),
        )
    )


# ---------------------------------------------------------------- periods


def sync_committee_period(spec: ChainSpec, slot: int) -> int:
    p = spec.preset
    return slot // p.slots_per_epoch // p.epochs_per_sync_committee_period


# ------------------------------------------------------------- verification


class LightClientError(Exception):
    pass


@dataclass
class LightClientStore:
    """The client's persistent view (sync-protocol LightClientStore)."""

    finalized_header: object
    current_sync_committee: object
    next_sync_committee: Optional[object] = None
    best_valid_update: Optional[object] = None
    optimistic_header: Optional[object] = None
    previous_max_active_participants: int = 0
    current_max_active_participants: int = 0


def validate_bootstrap(trusted_block_root: bytes, bootstrap) -> LightClientStore:
    """Check the bootstrap against an out-of-band trusted root and open
    a store from it."""
    header_root = BeaconBlockHeader.hash_tree_root(bootstrap.header.beacon)
    if header_root != bytes(trusted_block_root):
        raise LightClientError("bootstrap header != trusted root")
    ok = verify_merkle_branch(
        SyncCommittee.hash_tree_root(bootstrap.current_sync_committee),
        [bytes(b) for b in bootstrap.current_sync_committee_branch],
        STATE_PROOF_DEPTH,
        CURRENT_SYNC_COMMITTEE_INDEX % _TREE_WIDTH,
        bytes(bootstrap.header.beacon.state_root),
    )
    if not ok:
        raise LightClientError("bad current-sync-committee branch")
    return LightClientStore(
        finalized_header=bootstrap.header,
        current_sync_committee=bootstrap.current_sync_committee,
        optimistic_header=bootstrap.header,
    )


def _verify_sync_aggregate(
    spec: ChainSpec,
    genesis_validators_root: bytes,
    committee,
    sync_aggregate,
    attested_root: bytes,
    signature_slot: int,
    backend: Optional[str] = None,
) -> int:
    """Verify the committee signature over the attested block root;
    returns the participant count. The message/domain construction
    mirrors the VC's sync-message signing exactly."""
    from ..crypto import bls
    from ..crypto.bls.keys import PublicKey, Signature, SignatureSet
    from .domains import compute_signing_root, get_domain
    from .signature_sets import _Bytes32SSZ
    from . import state_transition as st

    bits = list(sync_aggregate.sync_committee_bits)
    participants = [
        PublicKey.from_bytes(bytes(committee.pubkeys[i]))
        for i, b in enumerate(bits)
        if b
    ]
    n = len(participants)
    if n == 0:
        return 0
    prev_slot = max(1, int(signature_slot)) - 1
    epoch = st.compute_epoch_at_slot(spec, prev_slot)
    domain = get_domain(
        spec,
        spec.domain_sync_committee,
        epoch,
        spec.fork_at_epoch(epoch),
        genesis_validators_root,
    )
    root = compute_signing_root(_Bytes32SSZ(attested_root), domain)
    sset = SignatureSet.multiple_pubkeys(
        Signature.from_bytes(bytes(sync_aggregate.sync_committee_signature)),
        participants,
        root,
    )
    if not bls.verify_signature_sets([sset], backend=backend):
        raise LightClientError("sync aggregate signature invalid")
    return n


def process_light_client_update(
    store: LightClientStore,
    update,
    current_slot: int,
    spec: ChainSpec,
    genesis_validators_root: bytes,
    bls_backend: Optional[str] = None,
) -> None:
    """The sync-protocol's process_light_client_update, collapsed to the
    force-update-free happy path: verify branches + signature, advance
    finalized/optimistic headers, rotate committees across periods."""
    attested = update.attested_header.beacon
    finalized = update.finalized_header.beacon
    sig_slot = int(update.signature_slot)
    if not (int(attested.slot) < sig_slot <= current_slot):
        raise LightClientError("update slots out of order")

    store_period = sync_committee_period(
        spec, int(store.finalized_header.beacon.slot)
    )
    update_period = sync_committee_period(spec, int(attested.slot))
    if update_period not in (store_period, store_period + 1):
        raise LightClientError("update period not adjacent to store")

    # finality proof: finalized header root sits in the attested state
    if int(finalized.slot) > 0:
        ok = verify_merkle_branch(
            BeaconBlockHeader.hash_tree_root(finalized),
            [bytes(b) for b in update.finality_branch],
            FINALITY_PROOF_DEPTH,
            FINALIZED_ROOT_INDEX % (1 << FINALITY_PROOF_DEPTH),
            bytes(attested.state_root),
        )
        if not ok:
            raise LightClientError("bad finality branch")

    # next-committee proof against the attested state
    has_next = any(
        bytes(pk) != b"\x00" * 48 for pk in update.next_sync_committee.pubkeys[:1]
    )
    if has_next:
        ok = verify_merkle_branch(
            SyncCommittee.hash_tree_root(update.next_sync_committee),
            [bytes(b) for b in update.next_sync_committee_branch],
            STATE_PROOF_DEPTH,
            NEXT_SYNC_COMMITTEE_INDEX % _TREE_WIDTH,
            bytes(attested.state_root),
        )
        if not ok:
            raise LightClientError("bad next-sync-committee branch")

    # signature by the committee of the signature slot's period (the
    # spec's compute_sync_committee_period_at_slot(signature_slot) —
    # the -1 applies only to the DOMAIN epoch; a boundary-slot block is
    # verified against the post-rotation committee, matching
    # process_sync_aggregate's use of the state's current committee)
    sig_period = sync_committee_period(spec, sig_slot)
    if sig_period == store_period:
        committee = store.current_sync_committee
    elif sig_period == store_period + 1 and store.next_sync_committee is not None:
        committee = store.next_sync_committee
    else:
        raise LightClientError("no committee known for signature period")
    n = _verify_sync_aggregate(
        spec,
        genesis_validators_root,
        committee,
        update.sync_aggregate,
        BeaconBlockHeader.hash_tree_root(attested),
        sig_slot,
        backend=bls_backend,
    )
    if 3 * n < 2 * spec.preset.sync_committee_size:
        raise LightClientError("insufficient sync participation")

    # apply
    store.current_max_active_participants = max(
        store.current_max_active_participants, n
    )
    if store.optimistic_header is None or int(attested.slot) > int(
        store.optimistic_header.beacon.slot
    ):
        store.optimistic_header = update.attested_header
    if int(finalized.slot) > int(store.finalized_header.beacon.slot):
        finalized_period = sync_committee_period(spec, int(finalized.slot))
        if has_next and store.next_sync_committee is None:
            # spec apply_light_client_update: learning the next committee
            # without a rotation is only sound for the CURRENT period —
            # accepting a later-period committee here would leave
            # current_sync_committee one period stale and fail every
            # subsequent signature check
            if finalized_period != store_period:
                raise LightClientError(
                    "next-committee update from a later period"
                )
            store.next_sync_committee = update.next_sync_committee
        elif finalized_period == store_period + 1:
            # period rollover: next becomes current
            if store.next_sync_committee is None:
                raise LightClientError("rollover without next committee")
            store.current_sync_committee = store.next_sync_committee
            store.next_sync_committee = (
                update.next_sync_committee if has_next else None
            )
            store.previous_max_active_participants = (
                store.current_max_active_participants
            )
            store.current_max_active_participants = 0
        store.finalized_header = update.finalized_header
    elif has_next and store.next_sync_committee is None:
        # non-finality update: learn the next committee only when the
        # attested state is in OUR period (the committee the proof is
        # checked against); a later-period update is simply not
        # learnable here — skip, don't treat the peer as faulty
        if sync_committee_period(spec, int(attested.slot)) == store_period:
            store.next_sync_committee = update.next_sync_committee
