"""Generalized merkle proofs over the SSZ tree (consensus/merkle_proof
analog, merkle_proof/src/lib.rs:607).

Proof convention: sibling hashes bottom-up; `index` is the leaf's
position flattened under the proof's root (gindex minus 2^depth), so
bit i of `index` says whether the node at level i is a right child.
`verify_merkle_branch` is the spec's is_valid_merkle_branch.

The concrete proof this round exists for: BlobSidecar's 17-deep
kzg_commitment inclusion proof into the block body
(deneb verify_blob_sidecar_inclusion_proof; the reference builds these
in beacon_chain/src/kzg_utils.rs blob->sidecar construction).
"""

from __future__ import annotations

import hashlib
from typing import Sequence

from . import types as T
from .ssz import _ZERO_CHUNKS, _next_pow2


def _hash(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def merkle_branch(chunks: Sequence[bytes], limit: int, index: int) -> list:
    """Sibling path (bottom-up) for leaf `index` in the zero-padded tree
    of `limit` leaves over `chunks`."""
    width = _next_pow2(limit)
    depth = width.bit_length() - 1
    layer = list(chunks)
    branch = []
    for d in range(depth):
        if len(layer) % 2:
            layer.append(_ZERO_CHUNKS[d])
        sib = index ^ 1
        branch.append(layer[sib] if sib < len(layer) else _ZERO_CHUNKS[d])
        layer = [
            _hash(layer[i], layer[i + 1]) for i in range(0, len(layer), 2)
        ]
        index //= 2
    return branch


def verify_merkle_branch(
    leaf: bytes, branch: Sequence[bytes], depth: int, index: int, root: bytes
) -> bool:
    """Spec is_valid_merkle_branch."""
    node = leaf
    for i in range(depth):
        if (index >> i) & 1:
            node = _hash(bytes(branch[i]), node)
        else:
            node = _hash(node, bytes(branch[i]))
    return node == root


# ------------------------------------------------- blob inclusion proofs

_BODY_FIELDS = [name for name, _ in T.BeaconBlockBody.fields]
_COMMITMENTS_FIELD_INDEX = _BODY_FIELDS.index("blob_kzg_commitments")
_COMMITMENTS_TYPE = dict(T.BeaconBlockBody.fields)["blob_kzg_commitments"]
_BODY_WIDTH = _next_pow2(len(_BODY_FIELDS))
_BODY_DEPTH = _BODY_WIDTH.bit_length() - 1  # 4
_LIST_DEPTH = _next_pow2(_COMMITMENTS_TYPE.limit).bit_length() - 1  # 12

# flattened leaf index under the body root for commitment i:
#   body field (depth 4) -> left child of length mix-in (depth 1)
#   -> list leaf (depth 12)
assert (
    _BODY_DEPTH + 1 + _LIST_DEPTH == T.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH
)


def blob_inclusion_index(blob_index: int) -> int:
    return (
        _COMMITMENTS_FIELD_INDEX * 2 ** (1 + _LIST_DEPTH)  # body levels
        + 0 * 2**_LIST_DEPTH  # list root is the LEFT child of the mix-in
        + blob_index
    )


def compute_blob_inclusion_proof(body, blob_index: int) -> list:
    """The 17 siblings proving body.blob_kzg_commitments[blob_index]
    against the body root (KZG_COMMITMENT_INCLUSION_PROOF_DEPTH)."""
    commitments = list(body.blob_kzg_commitments)
    elem = _COMMITMENTS_TYPE.elem
    leaves = [elem.hash_tree_root(c) for c in commitments]
    proof = merkle_branch(leaves, _COMMITMENTS_TYPE.limit, blob_index)
    # length mix-in: the sibling is the length chunk (we sit on the left)
    proof.append(len(commitments).to_bytes(32, "little"))
    # body container levels
    field_roots = [
        ftype.hash_tree_root(getattr(body, fname))
        for fname, ftype in T.BeaconBlockBody.fields
    ]
    proof.extend(
        merkle_branch(field_roots, _BODY_WIDTH, _COMMITMENTS_FIELD_INDEX)
    )
    return proof


def verify_blob_inclusion_proof(
    body_root: bytes, commitment: bytes, blob_index: int, proof: Sequence[bytes]
) -> bool:
    """deneb verify_blob_sidecar_inclusion_proof."""
    if len(proof) != T.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH:
        return False
    leaf = _COMMITMENTS_TYPE.elem.hash_tree_root(commitment)
    return verify_merkle_branch(
        leaf,
        proof,
        T.KZG_COMMITMENT_INCLUSION_PROOF_DEPTH,
        blob_inclusion_index(blob_index),
        body_root,
    )
