"""Chain specification: runtime constants + fork schedule.

The two-level config of the reference (SURVEY.md §5.6): compile-time
presets (mainnet/minimal — consensus/types/src/eth_spec.rs:605) become
`Preset` instances; runtime constants (consensus/types/src/chain_spec.rs)
become `ChainSpec` fields, YAML-free but dict round-trippable.
"""

from __future__ import annotations

from dataclasses import dataclass, field, asdict

FAR_FUTURE_EPOCH = 2**64 - 1
GENESIS_EPOCH = 0
GENESIS_SLOT = 0


@dataclass(frozen=True)
class Preset:
    """Compile-time-ish size constants (eth_spec.rs presets)."""

    name: str
    slots_per_epoch: int
    max_committees_per_slot: int
    target_committee_size: int
    max_validators_per_committee: int
    shuffle_round_count: int
    epochs_per_eth1_voting_period: int
    slots_per_historical_root: int
    epochs_per_historical_vector: int
    epochs_per_slashings_vector: int
    historical_roots_limit: int
    validator_registry_limit: int
    max_proposer_slashings: int
    max_attester_slashings: int
    max_attestations: int
    max_deposits: int
    max_voluntary_exits: int
    max_bls_to_execution_changes: int
    max_blob_commitments_per_block: int
    sync_committee_size: int
    sync_committee_subnet_count: int
    epochs_per_sync_committee_period: int
    # execution payload (Bellatrix+)
    max_bytes_per_transaction: int
    max_transactions_per_payload: int
    bytes_per_logs_bloom: int
    max_extra_data_bytes: int
    # withdrawals (Capella+)
    max_withdrawals_per_payload: int
    max_validators_per_withdrawals_sweep: int
    # blobs (Deneb+)
    field_elements_per_blob: int
    max_blobs_per_block: int


MAINNET_PRESET = Preset(
    name="mainnet",
    slots_per_epoch=32,
    max_committees_per_slot=64,
    target_committee_size=128,
    max_validators_per_committee=2048,
    shuffle_round_count=90,
    epochs_per_eth1_voting_period=64,
    slots_per_historical_root=8192,
    epochs_per_historical_vector=65536,
    epochs_per_slashings_vector=8192,
    historical_roots_limit=2**24,
    validator_registry_limit=2**40,
    max_proposer_slashings=16,
    max_attester_slashings=2,
    max_attestations=128,
    max_deposits=16,
    max_voluntary_exits=16,
    max_bls_to_execution_changes=16,
    max_blob_commitments_per_block=4096,
    sync_committee_size=512,
    sync_committee_subnet_count=4,
    epochs_per_sync_committee_period=256,
    max_bytes_per_transaction=2**30,
    max_transactions_per_payload=2**20,
    bytes_per_logs_bloom=256,
    max_extra_data_bytes=32,
    max_withdrawals_per_payload=16,
    max_validators_per_withdrawals_sweep=16384,
    field_elements_per_blob=4096,
    max_blobs_per_block=6,
)

MINIMAL_PRESET = Preset(
    name="minimal",
    slots_per_epoch=8,
    max_committees_per_slot=4,
    target_committee_size=4,
    max_validators_per_committee=2048,
    shuffle_round_count=10,
    epochs_per_eth1_voting_period=4,
    slots_per_historical_root=64,
    epochs_per_historical_vector=64,
    epochs_per_slashings_vector=64,
    historical_roots_limit=2**24,
    validator_registry_limit=2**40,
    max_proposer_slashings=16,
    max_attester_slashings=2,
    max_attestations=128,
    max_deposits=16,
    max_voluntary_exits=16,
    max_bls_to_execution_changes=16,
    max_blob_commitments_per_block=4096,
    sync_committee_size=32,
    sync_committee_subnet_count=4,
    epochs_per_sync_committee_period=8,
    max_bytes_per_transaction=2**30,
    max_transactions_per_payload=2**20,
    bytes_per_logs_bloom=256,
    max_extra_data_bytes=32,
    max_withdrawals_per_payload=4,
    max_validators_per_withdrawals_sweep=16,
    field_elements_per_blob=4,
    max_blobs_per_block=6,
)


FORK_ORDER = ["phase0", "altair", "bellatrix", "capella", "deneb", "electra"]


@dataclass(frozen=True)
class ForkInfo:
    """Duck-compatible with the SSZ Fork container for get_domain."""

    previous_version: bytes
    current_version: bytes
    epoch: int


@dataclass
class ChainSpec:
    """Runtime constants (chain_spec.rs analog)."""

    preset: Preset = MAINNET_PRESET
    config_name: str = "mainnet"
    seconds_per_slot: int = 12
    min_genesis_time: int = 1606824000
    genesis_delay: int = 604800
    min_genesis_active_validator_count: int = 16384
    min_deposit_amount: int = 10**9
    max_effective_balance: int = 32 * 10**9
    effective_balance_increment: int = 10**9
    ejection_balance: int = 16 * 10**9
    min_attestation_inclusion_delay: int = 1
    min_seed_lookahead: int = 1
    max_seed_lookahead: int = 4
    min_epochs_to_inactivity_penalty: int = 4
    min_validator_withdrawability_delay: int = 256
    shard_committee_period: int = 256
    base_reward_factor: int = 64
    whistleblower_reward_quotient: int = 512
    proposer_reward_quotient: int = 8
    inactivity_penalty_quotient: int = 2**26
    min_slashing_penalty_quotient: int = 128
    proportional_slashing_multiplier: int = 1
    min_per_epoch_churn_limit: int = 4
    churn_limit_quotient: int = 65536
    proposer_score_boost: int = 40
    target_aggregators_per_committee: int = 16
    # electra (EIP-7251 MaxEB / EIP-7002 / EIP-6110 / EIP-7549)
    min_activation_balance: int = 32 * 10**9
    max_effective_balance_electra: int = 2048 * 10**9
    min_per_epoch_churn_limit_electra: int = 128 * 10**9  # gwei
    max_per_epoch_activation_exit_churn_limit: int = 256 * 10**9
    min_slashing_penalty_quotient_electra: int = 4096
    whistleblower_reward_quotient_electra: int = 4096
    max_pending_partials_per_withdrawals_sweep: int = 8
    max_pending_deposits_per_epoch: int = 16
    # deposit contract (chain_spec.rs deposit_chain_id/_network_id/_contract)
    deposit_chain_id: int = 1
    deposit_contract_address: str = "0x00000000219ab540356cBB839Cbe05303d7705Fa"
    # known genesis_validators_root for networks whose genesis is fixed
    # (None until genesis is computed/synced)
    genesis_validators_root: bytes = None
    # domain types (4-byte little-endian constants, spec values)
    domain_beacon_proposer: bytes = bytes.fromhex("00000000")
    domain_beacon_attester: bytes = bytes.fromhex("01000000")
    domain_randao: bytes = bytes.fromhex("02000000")
    domain_deposit: bytes = bytes.fromhex("03000000")
    domain_voluntary_exit: bytes = bytes.fromhex("04000000")
    domain_selection_proof: bytes = bytes.fromhex("05000000")
    domain_aggregate_and_proof: bytes = bytes.fromhex("06000000")
    domain_sync_committee: bytes = bytes.fromhex("07000000")
    domain_sync_committee_selection_proof: bytes = bytes.fromhex("08000000")
    domain_contribution_and_proof: bytes = bytes.fromhex("09000000")
    domain_bls_to_execution_change: bytes = bytes.fromhex("0A000000")
    domain_application_mask: bytes = bytes.fromhex("00000001")
    # fork schedule: name -> (version bytes, activation epoch)
    genesis_fork_version: bytes = bytes.fromhex("00000000")
    fork_versions: dict = field(
        default_factory=lambda: {
            "phase0": bytes.fromhex("00000000"),
            "altair": bytes.fromhex("01000000"),
            "bellatrix": bytes.fromhex("02000000"),
            "capella": bytes.fromhex("03000000"),
            "deneb": bytes.fromhex("04000000"),
            "electra": bytes.fromhex("05000000"),
        }
    )
    fork_epochs: dict = field(
        default_factory=lambda: {
            "phase0": 0,
            "altair": 74240,
            "bellatrix": 144896,
            "capella": 194048,
            "deneb": 269568,
            "electra": 364032,
        }
    )

    def fork_name_at_epoch(self, epoch: int) -> str:
        current = "phase0"
        for name in FORK_ORDER:
            e = self.fork_epochs.get(name, FAR_FUTURE_EPOCH)
            if e <= epoch:
                current = name
        return current

    def fork_at_least(self, epoch: int, name: str) -> bool:
        """Is fork `name` (or a later one) active at `epoch`? The
        fork_name.rs ordering comparison every fork gate uses."""
        return FORK_ORDER.index(self.fork_name_at_epoch(epoch)) >= (
            FORK_ORDER.index(name)
        )

    def electra_enabled(self, epoch: int) -> bool:
        return self.fork_at_least(epoch, "electra")

    def fork_version_at_epoch(self, epoch: int) -> bytes:
        return self.fork_versions[self.fork_name_at_epoch(epoch)]

    def fork_at_epoch(self, epoch: int) -> "ForkInfo":
        """The Fork (previous/current version + activation epoch) in
        effect at `epoch` — for signing domains of HISTORICAL objects
        where no state of that era is at hand (backfill verification)."""
        name = self.fork_name_at_epoch(epoch)
        idx = FORK_ORDER.index(name)
        prev_name = FORK_ORDER[max(0, idx - 1)]
        return ForkInfo(
            previous_version=self.fork_versions[prev_name],
            current_version=self.fork_versions[name],
            epoch=self.fork_epochs.get(name, 0),
        )

    def to_dict(self) -> dict:
        d = asdict(self)
        d["preset"] = self.preset.name
        return d


def mainnet_spec() -> ChainSpec:
    return ChainSpec()


def minimal_spec() -> ChainSpec:
    return ChainSpec(preset=MINIMAL_PRESET, config_name="minimal")
