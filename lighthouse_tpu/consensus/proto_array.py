"""LMD-GHOST proto-array fork choice.

Analog of consensus/proto_array (proto_array_fork_choice.rs): a flat
node array in insertion order (parents before children), vote-delta
accumulation (compute_deltas :900), one O(nodes) backward pass to
propagate weights and select best descendants, and find_head (:463-501)
as a forward walk over best_child pointers. Includes proposer boost,
execution-status (optimistic sync) invalidation, and finality pruning.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Optional


class ExecutionStatus(Enum):
    VALID = "valid"
    INVALID = "invalid"
    OPTIMISTIC = "optimistic"  # not yet verified by the execution layer
    IRRELEVANT = "irrelevant"  # pre-merge


@dataclass
class ProtoNode:
    slot: int
    root: bytes
    parent: Optional[int]           # index into the array
    justified_epoch: int
    finalized_epoch: int
    weight: int = 0
    best_child: Optional[int] = None
    best_descendant: Optional[int] = None
    execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT


@dataclass
class VoteTracker:
    current_root: bytes = b"\x00" * 32
    next_root: bytes = b"\x00" * 32
    next_epoch: int = 0


class ProtoArrayForkChoice:
    def __init__(
        self,
        finalized_root: bytes,
        finalized_slot: int,
        justified_epoch: int,
        finalized_epoch: int,
    ):
        self.nodes: list[ProtoNode] = []
        self.index_by_root: dict[bytes, int] = {}
        self.votes: dict[int, VoteTracker] = {}  # validator index -> tracker
        self.balances: list[int] = []
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self.proposer_boost_root: bytes = b"\x00" * 32
        self.proposer_boost_amount: int = 0
        self._applied_boost: tuple = (b"\x00" * 32, 0)
        self.on_block(
            slot=finalized_slot,
            root=finalized_root,
            parent_root=None,
            justified_epoch=justified_epoch,
            finalized_epoch=finalized_epoch,
        )

    # ------------------------------------------------------------ mutation

    def on_block(
        self,
        slot: int,
        root: bytes,
        parent_root: Optional[bytes],
        justified_epoch: int,
        finalized_epoch: int,
        execution_status: ExecutionStatus = ExecutionStatus.IRRELEVANT,
    ) -> None:
        if root in self.index_by_root:
            return
        parent = self.index_by_root.get(parent_root) if parent_root else None
        node = ProtoNode(
            slot=slot,
            root=root,
            parent=parent,
            justified_epoch=justified_epoch,
            finalized_epoch=finalized_epoch,
            execution_status=execution_status,
        )
        self.index_by_root[root] = len(self.nodes)
        self.nodes.append(node)

    def process_attestation(
        self, validator_index: int, block_root: bytes, target_epoch: int
    ) -> None:
        """LMD vote update (latest message per validator)."""
        fresh = validator_index not in self.votes
        v = self.votes.setdefault(validator_index, VoteTracker())
        # A brand-new tracker must accept its first vote even at target
        # epoch 0 (the tracker default), hence the `fresh` escape.
        if fresh or target_epoch > v.next_epoch:
            v.next_epoch = target_epoch
            v.next_root = block_root

    def apply_proposer_boost(self, root: bytes, amount: int) -> None:
        self.proposer_boost_root = root
        self.proposer_boost_amount = amount

    # ------------------------------------------------------------ deltas

    def _compute_deltas(self, new_balances: list[int]) -> list[int]:
        """Per-node weight delta from vote movement + balance changes
        (proto_array_fork_choice.rs:900)."""
        deltas = [0] * len(self.nodes)
        for vi, vote in self.votes.items():
            old_bal = self.balances[vi] if vi < len(self.balances) else 0
            new_bal = new_balances[vi] if vi < len(new_balances) else 0
            if vote.current_root in self.index_by_root and old_bal:
                deltas[self.index_by_root[vote.current_root]] -= old_bal
            if vote.next_root in self.index_by_root and new_bal:
                deltas[self.index_by_root[vote.next_root]] += new_bal
            # The old vote is subtracted exactly once: advance the
            # tracker unconditionally (even when the new target is
            # unknown or the new balance is 0), or the next pass would
            # subtract it again.
            vote.current_root = vote.next_root
        self.balances = list(new_balances)
        return deltas

    # ------------------------------------------------------------ scoring

    def _node_viable(self, node: ProtoNode) -> bool:
        if node.execution_status == ExecutionStatus.INVALID:
            return False
        return (
            node.justified_epoch == self.justified_epoch
            or self.justified_epoch == 0
        ) and (
            node.finalized_epoch == self.finalized_epoch
            or self.finalized_epoch == 0
        )

    def _viable_for_head(self, idx: int) -> bool:
        node = self.nodes[idx]
        if node.best_descendant is not None:
            return self._node_viable(self.nodes[node.best_descendant])
        return self._node_viable(node)

    def apply_score_changes(
        self,
        new_balances: list[int],
        justified_epoch: int = None,
        finalized_epoch: int = None,
    ) -> None:
        """Backward pass: apply deltas, bubble weights to parents, and
        maintain best_child/best_descendant pointers."""
        if justified_epoch is not None:
            self.justified_epoch = justified_epoch
        if finalized_epoch is not None:
            self.finalized_epoch = finalized_epoch
        deltas = self._compute_deltas(new_balances)
        # proposer boost is transient: remove last pass's boost, apply
        # the currently-set one, then mark it consumed
        prev_root, prev_amount = self._applied_boost
        if prev_amount:
            prev_idx = self.index_by_root.get(prev_root)
            if prev_idx is not None:
                deltas[prev_idx] -= prev_amount
        cur_idx = self.index_by_root.get(self.proposer_boost_root)
        if cur_idx is not None and self.proposer_boost_amount:
            deltas[cur_idx] += self.proposer_boost_amount
            self._applied_boost = (
                self.proposer_boost_root,
                self.proposer_boost_amount,
            )
        else:
            self._applied_boost = (b"\x00" * 32, 0)
        self.proposer_boost_root = b"\x00" * 32
        self.proposer_boost_amount = 0

        # best_child/best_descendant pointers are NOT maintained here:
        # find_head recomputes them from scratch (one authoritative
        # computation over final weights; maintaining them mid-delta-pass
        # would compare against stale sibling weights).
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            node.weight += deltas[i]
            if node.parent is not None:
                deltas[node.parent] += deltas[i]

    def _maybe_update_best_child(self, parent_idx: int, child_idx: int):
        parent = self.nodes[parent_idx]
        child = self.nodes[child_idx]
        child_viable = self._viable_for_head(child_idx)
        child_leads = False
        if parent.best_child is None:
            child_leads = child_viable
        else:
            best = self.nodes[parent.best_child]
            best_viable = self._viable_for_head(parent.best_child)
            if child_viable and not best_viable:
                child_leads = True
            elif child_viable and (
                child.weight > best.weight
                or (child.weight == best.weight and child.root > best.root)
            ):
                child_leads = True
        if child_leads:
            parent.best_child = child_idx
            parent.best_descendant = (
                child.best_descendant
                if child.best_descendant is not None
                else child_idx
            )

    # ------------------------------------------------------------ head

    def find_head(self, justified_root: bytes) -> bytes:
        """Walk best_child pointers from the justified root
        (proto_array_fork_choice.rs:463-501). Recomputes pointers with a
        full backward sweep first for simplicity+correctness."""
        # full refresh of best pointers (O(nodes), same complexity class
        # as the reference's delta pass)
        for node in self.nodes:
            node.best_child = None
            node.best_descendant = None
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.parent is None:
                continue
            self._maybe_update_best_child(node.parent, i)

        start = self.index_by_root.get(justified_root)
        if start is None:
            raise KeyError("unknown justified root")
        node = self.nodes[start]
        if node.best_descendant is not None and self._viable_for_head(
            node.best_descendant
        ):
            return self.nodes[node.best_descendant].root
        idx = start
        while self.nodes[idx].best_child is not None:
            idx = self.nodes[idx].best_child
        return self.nodes[idx].root

    # ------------------------------------------------------------ optimism

    def on_execution_status(self, root: bytes, status: ExecutionStatus):
        """Optimistic-sync resolution: VALID propagates to ancestors,
        INVALID propagates to all descendants."""
        idx = self.index_by_root.get(root)
        if idx is None:
            return
        self.nodes[idx].execution_status = status
        if status == ExecutionStatus.VALID:
            p = self.nodes[idx].parent
            while p is not None and self.nodes[p].execution_status == ExecutionStatus.OPTIMISTIC:
                self.nodes[p].execution_status = ExecutionStatus.VALID
                p = self.nodes[p].parent
        elif status == ExecutionStatus.INVALID:
            invalid = {idx}
            for i in range(idx + 1, len(self.nodes)):
                if self.nodes[i].parent in invalid:
                    self.nodes[i].execution_status = ExecutionStatus.INVALID
                    invalid.add(i)

    # ------------------------------------------------------------ pruning

    def prune(self, finalized_root: bytes) -> int:
        """Drop everything not descended from the new finalized root."""
        fidx = self.index_by_root.get(finalized_root)
        if fidx is None:
            raise KeyError("unknown finalized root")
        keep = {fidx}
        for i in range(fidx + 1, len(self.nodes)):
            if self.nodes[i].parent in keep:
                keep.add(i)
        remap = {}
        new_nodes = []
        for i in sorted(keep):
            remap[i] = len(new_nodes)
            node = self.nodes[i]
            node.parent = remap.get(node.parent) if i != fidx else None
            new_nodes.append(node)
        pruned = len(self.nodes) - len(new_nodes)
        self.nodes = new_nodes
        self.index_by_root = {n.root: i for i, n in enumerate(self.nodes)}
        for n in self.nodes:
            n.best_child = None
            n.best_descendant = None
        return pruned
