"""X25519 (RFC 7748) — the Diffie-Hellman primitive under the libp2p
noise transport (lighthouse_network's snow/Noise dependency). Pure
Python; handshakes happen once per connection, so speed is irrelevant.
Pinned against the RFC 7748 §5.2 test vectors in tests/test_noise.py."""

from __future__ import annotations

P = 2**255 - 19
_A24 = 121665


def _decode_u(u: bytes) -> int:
    if len(u) != 32:
        raise ValueError("x25519 u-coordinate must be 32 bytes")
    x = bytearray(u)
    x[31] &= 0x7F  # mask the high bit per RFC 7748
    return int.from_bytes(bytes(x), "little") % P


def _decode_scalar(k: bytes) -> int:
    if len(k) != 32:
        raise ValueError("x25519 scalar must be 32 bytes")
    s = bytearray(k)
    s[0] &= 248
    s[31] &= 127
    s[31] |= 64
    return int.from_bytes(bytes(s), "little")


def x25519(k: bytes, u: bytes) -> bytes:
    """scalar * u-coordinate -> shared u-coordinate (RFC 7748 §5)."""
    scalar = _decode_scalar(k)
    x1 = _decode_u(u)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in reversed(range(255)):
        k_t = (scalar >> t) & 1
        swap ^= k_t
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % P
        aa = a * a % P
        b = (x2 - z2) % P
        bb = b * b % P
        e = (aa - bb) % P
        c = (x3 + z3) % P
        d = (x3 - z3) % P
        da = d * a % P
        cb = c * b % P
        x3 = (da + cb) % P
        x3 = x3 * x3 % P
        z3 = (da - cb) % P
        z3 = x1 * (z3 * z3 % P) % P
        x2 = aa * bb % P
        z2 = e * (aa + _A24 * e) % P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, P - 2, P) % P
    return out.to_bytes(32, "little")


BASEPOINT = (9).to_bytes(32, "little")


def public_key(private: bytes) -> bytes:
    return x25519(private, BASEPOINT)
