"""Legacy Keccak-256 (pre-NIST padding 0x01) — Ethereum's hash.

hashlib ships SHA3-256 (padding 0x06) but not the legacy Keccak the
execution layer uses for block hashes / RLP tries (the reference binds
keccak-hash / alloy at beacon_node/execution_layer/src/keccak.rs).
Sponge with rate 136, Keccak-f[1600], 24 rounds; pure Python — the
block-hash path hashes one ~600-byte header per payload, so speed is
irrelevant next to correctness.
"""

from __future__ import annotations

_ROUNDS = 24
_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]
# rotation offsets r[x][y]
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_MASK = (1 << 64) - 1


def _rol(v: int, n: int) -> int:
    n %= 64
    return ((v << n) | (v >> (64 - n))) & _MASK


def _keccak_f(A: list) -> None:
    """In-place Keccak-f[1600] on a 5x5 lane matrix A[x][y]."""
    for rnd in range(_ROUNDS):
        # theta
        C = [A[x][0] ^ A[x][1] ^ A[x][2] ^ A[x][3] ^ A[x][4] for x in range(5)]
        D = [C[(x - 1) % 5] ^ _rol(C[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                A[x][y] ^= D[x]
        # rho + pi
        B = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                B[y][(2 * x + 3 * y) % 5] = _rol(A[x][y], _ROT[x][y])
        # chi
        for x in range(5):
            for y in range(5):
                A[x][y] = B[x][y] ^ ((~B[(x + 1) % 5][y]) & B[(x + 2) % 5][y])
        # iota
        A[0][0] ^= _RC[rnd]


def keccak256(data: bytes) -> bytes:
    rate = 136  # 1088-bit rate for 256-bit output
    # legacy multi-rate padding: 0x01 ... 0x80
    padlen = rate - (len(data) % rate)
    padded = data + (
        b"\x81" if padlen == 1 else b"\x01" + b"\x00" * (padlen - 2) + b"\x80"
    )
    A = [[0] * 5 for _ in range(5)]
    for off in range(0, len(padded), rate):
        block = padded[off : off + rate]
        for i in range(rate // 8):
            lane = int.from_bytes(block[8 * i : 8 * i + 8], "little")
            A[i % 5][i // 5] ^= lane
        _keccak_f(A)
    out = b""
    for i in range(4):  # 32 bytes = 4 lanes
        out += A[i % 5][i // 5].to_bytes(8, "little")
    return out
