"""KZG polynomial commitments for EIP-4844 blobs (crypto/kzg analog).

The reference wraps c-kzg (C) behind `Kzg` with batch entry points
(crypto/kzg/src/lib.rs:50-54,156-183). Here the same surface is
implemented natively: Fr arithmetic and the bit-reversed roots-of-unity
evaluation domain on the host, commitments/proofs over the Lagrange-form
trusted setup, and the Fiat-Shamir batch check

    e(sum r^i (C_i - [y_i]G1) + sum r^i z_i P_i, G2)
      * e(-sum r^i P_i, [tau]G2) == 1

which reduces any number of blob proofs to ONE MSM + two pairings —
the same kernel family as BLS batch verification (SURVEY.md §2.7 item
2). The G1 MSM over the 4096-element blob is the device-offloadable
hot op (ops/msm.py); pairings use the validated host pairing.

Trusted setup: `TrustedSetup.dev(n)` derives an INSECURE deterministic
setup from a fixed tau (for tests/benchmarks — tau is public!);
`TrustedSetup.from_json` loads a real ceremony file when provided.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from ..bls import curve as C
from ..bls import fields as FF
from ..bls import pairing_fast as PF
from ..bls.params import P, R, G1X, G1Y, G2X, G2Y

FIELD_ELEMENTS_PER_BLOB = 4096
BYTES_PER_FIELD_ELEMENT = 32
BYTES_PER_BLOB = FIELD_ELEMENTS_PER_BLOB * BYTES_PER_FIELD_ELEMENT

# Fr: the BLS12-381 scalar field. 2-adicity 32, generator 7.
_PRIMITIVE_ROOT = 7
_MAINNET_SETUP = None

FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_DOMAIN = b"RCKZGBATCH___V1_"

G1_GEN = (G1X, G1Y)
G2_GEN = (G2X, G2Y)


class KzgError(Exception):
    pass


# ---------------------------------------------------------------- Fr / domain


def _bit_reverse(n: int, order: int) -> int:
    bits = order.bit_length() - 1
    out = 0
    for i in range(bits):
        out |= ((n >> i) & 1) << (bits - 1 - i)
    return out


def compute_roots_of_unity(order: int) -> list:
    """Bit-reversal-permuted roots of unity for the evaluation domain
    (the layout c-kzg uses in memory)."""
    assert order & (order - 1) == 0
    w = pow(_PRIMITIVE_ROOT, (R - 1) // order, R)
    roots = [pow(w, i, R) for i in range(order)]
    return [roots[_bit_reverse(i, order)] for i in range(order)]


def bit_reversal_permutation(values: list) -> list:
    """c-kzg's load-time brp: ceremony files ship g1_lagrange in NATURAL
    domain order; the in-memory basis must match the brp evaluation
    domain. Round 4: the unpermuted load made in-repo mainnet
    commitments non-interoperable (caught by committing the
    test_blobs_bundle fixture blob and comparing against its c-kzg
    commitment — tests/test_external_vectors.py)."""
    n = len(values)
    assert n & (n - 1) == 0
    return [values[_bit_reverse(i, n)] for i in range(n)]


def bytes_to_fr(b: bytes) -> int:
    x = int.from_bytes(b, "big")
    if x >= R:
        raise KzgError("scalar not canonical")
    return x


def fr_to_bytes(x: int) -> bytes:
    return (x % R).to_bytes(32, "big")


def blob_to_field_elements(blob: bytes, n: int = FIELD_ELEMENTS_PER_BLOB) -> list:
    if len(blob) != n * BYTES_PER_FIELD_ELEMENT:
        raise KzgError("bad blob length")
    return [bytes_to_fr(blob[i * 32 : (i + 1) * 32]) for i in range(n)]


def fr_batch_inverse(xs: list) -> list:
    """Montgomery batch inversion: ONE Fermat pow for any number of
    nonzero elements (zero maps to zero)."""
    prefix = []
    acc = 1
    for x in xs:
        prefix.append(acc)
        if x % R:
            acc = acc * x % R
    inv = pow(acc, R - 2, R)
    out = [0] * len(xs)
    for i in range(len(xs) - 1, -1, -1):
        if xs[i] % R == 0:
            continue
        out[i] = inv * prefix[i] % R
        inv = inv * xs[i] % R
    return out


# ---------------------------------------------------------------- setup


@dataclass
class TrustedSetup:
    g1_lagrange: list          # [L_i(tau)]G1, bit-reversed domain order
    g2_tau: tuple              # [tau]G2
    roots: list                # domain, bit-reversed order
    # monomial powers — required only by the PeerDAS cell ops
    # (coefficient-form quotient proofs); None for Lagrange-only setups
    g1_monomial: list = None   # [[tau^i]G1]
    g2_monomial: list = None   # [[tau^i]G2] (up to cell size + 1)

    @classmethod
    def mainnet(cls) -> "TrustedSetup":
        """The REAL KZG ceremony output (4096 Lagrange G1 points + G2
        monomials), from the same trusted_setup.json the reference
        embeds (crypto/kzg/trusted_setup.json, loaded at
        crypto/kzg/src/trusted_setup.rs). Public ceremony data; points
        are decompressed without subgroup checks (ceremony-validated).
        Cached in-process after first load, and on disk as a pickle of
        the decompressed coordinates: the 4096+ G1 decompressions cost
        ~20 s of sqrt-heavy host math per process otherwise — enough to
        blow the driver bench's time budget on its own."""
        global _MAINNET_SETUP
        if _MAINNET_SETUP is None:
            import json as _json
            import pickle as _pickle
            from pathlib import Path as _Path

            src = _Path(__file__).parent / "trusted_setup_mainnet.json"
            st_ = src.stat()
            # cache key = loader version + source json identity, so a
            # json update or a loader change (e.g. the round-4 brp fix)
            # can never silently serve stale points
            want_key = (2, st_.st_size, int(st_.st_mtime))
            cache = _Path(__file__).parent / "trusted_setup_mainnet.cache.pkl"
            if cache.exists():
                try:
                    key, g1l, g2m, g1m = _pickle.loads(cache.read_bytes())
                    if tuple(key) != want_key:
                        raise ValueError("stale setup cache")
                    _MAINNET_SETUP = cls(
                        g1_lagrange=g1l,
                        g2_tau=g2m[1],
                        roots=compute_roots_of_unity(len(g1l)),
                        g1_monomial=g1m,
                        g2_monomial=g2m,
                    )
                    return _MAINNET_SETUP
                except Exception:
                    pass  # stale/corrupt cache: fall through to the json
            raw = _json.loads(src.read_text())
            g1l = bit_reversal_permutation(
                [
                    C.g1_decompress(bytes.fromhex(h[2:]), subgroup_check=False)
                    for h in raw["g1_lagrange"]
                ]
            )
            g2m = [
                C.g2_decompress(bytes.fromhex(h[2:]), subgroup_check=False)
                for h in raw["g2_monomial"]
            ]
            g1m = [
                C.g1_decompress(bytes.fromhex(h[2:]), subgroup_check=False)
                for h in raw["g1_monomial"]
            ]
            try:
                cache.write_bytes(_pickle.dumps((want_key, g1l, g2m, g1m)))
            except OSError:
                pass  # read-only checkout: in-process cache still applies
            _MAINNET_SETUP = cls(
                g1_lagrange=g1l,
                g2_tau=g2m[1],
                roots=compute_roots_of_unity(len(g1l)),
                g1_monomial=g1m,
                g2_monomial=g2m,
            )
        return _MAINNET_SETUP

    @classmethod
    def dev(cls, n: int = FIELD_ELEMENTS_PER_BLOB, with_monomial=None) -> "TrustedSetup":
        """Deterministic INSECURE setup: tau is derived from a public
        seed, so proofs can be forged — dev/test/bench only."""
        tau = (
            int.from_bytes(
                hashlib.sha256(b"lighthouse-tpu insecure dev tau").digest(),
                "big",
            )
            % R
        )
        roots = compute_roots_of_unity(n)
        n_inv = pow(n, R - 2, R)
        zn = (pow(tau, n, R) - 1) % R
        g1s = []
        for w in roots:
            if tau == w:
                li = 1  # degenerate (never for a hash-derived tau)
            else:
                li = (
                    w
                    * n_inv
                    % R
                    * zn
                    % R
                    * pow((tau - w) % R, R - 2, R)
                    % R
                )
            g1s.append(C.g1_mul(G1_GEN, li))
        # monomial powers for the PeerDAS cell ops (dev setup knows
        # tau). Host G1 muls are ~0.5s each in pure Python, so large
        # setups skip them unless asked — blob commit/verify paths
        # only need the Lagrange basis.
        if with_monomial is None:
            with_monomial = n <= 512
        g1m = g2m = None
        if with_monomial:
            g1m, acc = [], 1
            for _ in range(n):
                g1m.append(C.g1_mul(G1_GEN, acc))
                acc = acc * tau % R
            g2m, acc = [], 1
            for _ in range(min(n, 65) + 1):
                g2m.append(C.g2_mul(G2_GEN, acc))
                acc = acc * tau % R
        return cls(
            g1_lagrange=g1s,
            g2_tau=C.g2_mul(G2_GEN, tau),
            roots=roots,
            g1_monomial=g1m,
            g2_monomial=g2m,
        )

    @classmethod
    def from_json(cls, obj: dict) -> "TrustedSetup":
        """Load a ceremony file (the standard trusted_setup.json shape:
        g1_lagrange / g2_monomial hex point lists; lagrange points are
        brp'd into the in-memory domain order like c-kzg's loader)."""
        g1s = bit_reversal_permutation(
            [
                C.g1_decompress(
                    bytes.fromhex(h[2:] if h.startswith("0x") else h)
                )
                for h in obj["g1_lagrange"]
            ]
        )
        def _pt2(h):
            return C.g2_decompress(
                bytes.fromhex(h[2:] if h.startswith("0x") else h)
            )

        g2s = obj["g2_monomial"]
        g2_tau = _pt2(g2s[1])
        g1m = None
        if "g1_monomial" in obj:
            g1m = [
                C.g1_decompress(
                    bytes.fromhex(h[2:] if h.startswith("0x") else h)
                )
                for h in obj["g1_monomial"]
            ]
        return cls(
            g1_lagrange=g1s,
            g2_tau=g2_tau,
            roots=compute_roots_of_unity(len(g1s)),
            g1_monomial=g1m,
            g2_monomial=[_pt2(h) for h in g2s],
        )


# ---------------------------------------------------------------- core


def _msm_host(points: list, scalars: list):
    """Host MSM control path; ops/msm.py is the device path."""
    acc = None
    for p, s in zip(points, scalars):
        if s == 0 or p is None:
            continue
        acc = C.g1_add(acc, C.g1_mul(p, s))
    return acc


class Kzg:
    """The reference's `Kzg` service object (crypto/kzg/src/lib.rs:50)."""

    def __init__(
        self, setup: TrustedSetup = None, msm=None, pairing=None, msm_multi=None
    ):
        self.setup = setup or TrustedSetup.dev()
        self.n = len(self.setup.g1_lagrange)
        self._msm = msm or _msm_host  # device seam: batched G1 MSM
        # optional segmented-MSM seam: fn(points, scalars, group_ids,
        # n_groups) -> [point | None]; one ladder walk for the batch
        # check's two sums (ops/lane/msm.msm_g1_groups)
        self._msm_multi = msm_multi
        # device seam: pairing-product check ([(G1, G2)] -> bool);
        # host control = validated pure-Python pairing
        self._pairing = pairing or (
            lambda pairs: PF.pairings_product_is_one_fast(pairs)
        )

    # -- commitments

    def blob_to_kzg_commitment(self, blob: bytes):
        scalars = blob_to_field_elements(blob, self.n)
        return self._msm(self.setup.g1_lagrange, scalars)

    def commitment_bytes(self, commitment) -> bytes:
        return C.g1_compress(commitment)

    # -- evaluation

    def evaluate_polynomial(self, blob_fields: list, z: int) -> int:
        """p(z) from evaluation form via the barycentric formula (batch
        inversion: one Fermat pow for the whole domain)."""
        roots = self.setup.roots
        n = len(roots)
        for i, w in enumerate(roots):
            if z == w:
                return blob_fields[i]
        zn = (pow(z, n, R) - 1) % R
        n_inv = pow(n, R - 2, R)
        invs = fr_batch_inverse([(z - w) % R for w in roots])
        total = 0
        for fi, w, iv in zip(blob_fields, roots, invs):
            total = (total + fi * w % R * iv) % R
        return total * zn % R * n_inv % R

    # -- proofs

    def compute_kzg_proof(self, blob: bytes, z: int) -> tuple:
        """(proof point, y = p(z)). Quotient in evaluation form
        (c-kzg compute_kzg_proof_impl semantics), batch-inverted."""
        fields = blob_to_field_elements(blob, self.n)
        roots = self.setup.roots
        n = len(roots)
        y = self.evaluate_polynomial(fields, z)
        m = None
        for i, w in enumerate(roots):
            if z == w:
                m = i
        invs = fr_batch_inverse([(w - z) % R for w in roots])
        q = [0] * n
        for i, (w, iv) in enumerate(zip(roots, invs)):
            if i == m:
                continue
            q[i] = (fields[i] - y) % R * iv % R
        if m is not None:
            # z ON the domain: q_m = sum_{i!=m} (f_i - y) w_i /
            # (w_m (w_m - w_i))
            wm = roots[m]
            wm_inv = pow(wm, R - 2, R)
            dinvs = fr_batch_inverse(
                [(wm - w) % R if i != m else 1 for i, w in enumerate(roots)]
            )
            acc = 0
            for i, (w, div) in enumerate(zip(roots, dinvs)):
                if i == m:
                    continue
                qi = (fields[i] - y) % R * div % R
                acc = (acc + qi * w) % R
            q[m] = acc * wm_inv % R
        return self._msm(self.setup.g1_lagrange, q), y

    def compute_blob_kzg_proof(self, blob: bytes, commitment) -> tuple:
        z = self._blob_challenge(blob, commitment)
        return self.compute_kzg_proof(blob, z)

    # -- verification

    def verify_kzg_proof(self, commitment, z: int, y: int, proof) -> bool:
        """e(C - [y]G1, G2) == e(proof, [tau - z]G2), as the 2-pairing
        product check."""
        return self._pairing_batch([(commitment, z, y, proof)])

    def verify_blob_kzg_proof(self, blob: bytes, commitment, proof) -> bool:
        z = self._blob_challenge(blob, commitment)
        y = self._evaluate_blobs([blob], [z])[0]
        return self.verify_kzg_proof(commitment, z, y, proof)

    def verify_blob_kzg_proof_batch(
        self, blobs: list, commitments: list, proofs: list
    ) -> bool:
        """crypto/kzg/src/lib.rs:156-183 semantics: one combined check
        for the whole batch."""
        if not (len(blobs) == len(commitments) == len(proofs)):
            raise KzgError("length mismatch")
        if not blobs:
            return True
        zs = [
            self._blob_challenge(blob, cm)
            for blob, cm in zip(blobs, commitments)
        ]
        ys = self._evaluate_blobs(blobs, zs)
        items = [
            (cm, z, y, pr)
            for cm, z, y, pr in zip(commitments, zs, ys, proofs)
        ]
        return self._pairing_batch(items)

    def _evaluate_blobs(self, blobs: list, zs: list) -> list:
        """p_j(z_j) for each blob — native Fr engine when built (the
        c-kzg-speed host path), pure-Python barycentric otherwise."""
        from . import _fr_native

        if all(len(b) == self.n * BYTES_PER_FIELD_ELEMENT for b in blobs):
            try:
                ys = _fr_native.eval_barycentric_batch(
                    blobs, zs, self.setup.roots
                )
            except ValueError as e:
                raise KzgError(str(e))
            if ys is not None:
                return ys
        return [
            self.evaluate_polynomial(blob_to_field_elements(b, self.n), z)
            for b, z in zip(blobs, zs)
        ]

    # -- internals

    def _blob_challenge(self, blob: bytes, commitment) -> int:
        # KZG_ENDIANNESS is 'big' throughout the spec's Fiat-Shamir —
        # including the 16-byte polynomial degree. (Caught by the
        # external c-kzg fixture, tests/test_external_vectors.py.)
        h = hashlib.sha256(
            FIAT_SHAMIR_PROTOCOL_DOMAIN
            + self.n.to_bytes(16, "big")
            + blob
            + C.g1_compress(commitment)
        ).digest()
        return int.from_bytes(h, "big") % R

    def _batch_r_powers(self, items) -> list:
        # spec compute_r_powers transcript: domain | degree (16B big) |
        # count (8B big) | commitments | zs | ys | proofs. The value is
        # verifier-local (any RLC is sound), but keep the transcript
        # spec-exact like _blob_challenge.
        data = (
            RANDOM_CHALLENGE_DOMAIN
            + self.n.to_bytes(16, "big")
            + len(items).to_bytes(8, "big")
        )
        data += b"".join(C.g1_compress(cm) for cm, _, _, _ in items)
        data += b"".join(fr_to_bytes(z) for _, z, _, _ in items)
        data += b"".join(fr_to_bytes(y) for _, _, y, _ in items)
        data += b"".join(C.g1_compress(pr) for _, _, _, pr in items)
        r = int.from_bytes(hashlib.sha256(data).digest(), "big") % R
        out = [1]
        for _ in range(len(items) - 1):
            out.append(out[-1] * r % R)
        return out

    def _pairing_batch(self, items) -> bool:
        """Combined check over [(C, z, y, proof)]:
        e(sum r^i (C_i - [y_i]G1 + [z_i]P_i), G2) * e(-sum r^i P_i,
        [tau]G2) == 1.

        The G1 generator terms fold into ONE point with the combined
        scalar -sum(y_i r^i) (scalar math is host-cheap), and with a
        segmented-MSM backend both point sums share one ladder walk."""
        rs = self._batch_r_powers(items)
        lhs_points, lhs_scalars = [], []
        proof_points, proof_scalars = [], []
        gen_scalar = 0
        for (cm, z, y, pr), r in zip(items, rs):
            lhs_points.append(cm)
            lhs_scalars.append(r)
            gen_scalar = (gen_scalar - y * r) % R
            lhs_points.append(pr)
            lhs_scalars.append(z * r % R)
            proof_points.append(pr)
            proof_scalars.append(r)
        lhs_points.append(G1_GEN)
        lhs_scalars.append(gen_scalar)
        if self._msm_multi is not None:
            pts = lhs_points + proof_points
            scs = lhs_scalars + proof_scalars
            gids = [0] * len(lhs_points) + [1] * len(proof_points)
            lhs, pagg = self._msm_multi(pts, scs, gids, 2)
        else:
            lhs = self._msm(lhs_points, lhs_scalars)
            pagg = self._msm(proof_points, proof_scalars)
        if pagg is None:
            return lhs is None
        pairs = []
        if lhs is not None:
            pairs.append((lhs, G2_GEN))
        pairs.append((C.g1_neg(pagg), self.setup.g2_tau))
        return self._pairing(pairs)
