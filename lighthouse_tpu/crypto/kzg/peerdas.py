"""PeerDAS cell operations (EIP-7594; reference crypto/kzg's
rust_eth_kzg DASContext: compute_cells_and_proofs lib.rs:221,
verify_cell_proof_batch lib.rs:240, recover_cells_and_compute_kzg_proofs
lib.rs:267).

A blob's polynomial (degree < n, evaluation form) is Reed-Solomon
extended to 2n points and split into CELLS_PER_EXT_BLOB multiplicative
cosets of FIELD_ELEMENTS_PER_CELL points each: with w the 2n-th root of
unity, cell i is the coset  w^{rbo(i)} · H,  H = <w^{cells}>.  Each cell
carries a KZG multi-opening proof [q_i(tau)]G1 for

    q_i(X) = (p(X) - I_i(X)) / Z_i(X),   Z_i(X) = X^c - h_i^c

(c = cell size, h_i the coset shift, I_i the coset interpolant), which
one pairing pair batch-verifies via a random linear combination:

    e(sum r_i (C_i - [I_i(tau)]G1 + h_i^c P_i), G2)
      * e(-sum r_i P_i, [tau^c]G2) == 1

Recovery from any >=50% of cells runs the standard vanishing-polynomial
erasure decoder (zero-poly over missing cosets, coset-FFT division).

Fr FFTs run on the host; the G1 MSMs ride the same device seam as the
blob commitments (ops/msm.py). Cell layout inside a cell is the c-kzg
bit-reversed enumeration of the natural coset order.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Sequence

from ..bls import curve as C
from . import (
    G1_GEN,
    G2_GEN,
    KzgError,
    R,
    TrustedSetup,
    _bit_reverse,
    _msm_host,
    fr_batch_inverse,
    fr_to_bytes,
    bytes_to_fr,
    blob_to_field_elements,
)
from ..bls import pairing_fast as PF

# mainnet constants (EIP-7594)
CELLS_PER_EXT_BLOB = 128
FIELD_ELEMENTS_PER_CELL = 64
BYTES_PER_CELL = FIELD_ELEMENTS_PER_CELL * 32

RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN = b"RCKZGCBATCH__V1_"

_PRIMITIVE_ROOT = 7


def _root_of_unity(order: int) -> int:
    """Primitive `order`-th root in Fr (2-adicity 32)."""
    assert order & (order - 1) == 0
    return pow(_PRIMITIVE_ROOT, (R - 1) // order, R)


def fft(vals: Sequence[int], inverse: bool = False) -> list:
    """Iterative radix-2 NTT over Fr, natural order in/out."""
    n = len(vals)
    assert n & (n - 1) == 0
    a = [v % R for v in vals]
    # bit-reversal permutation (_bit_reverse takes the domain SIZE)
    for i in range(n):
        j = _bit_reverse(i, n)
        if i < j:
            a[i], a[j] = a[j], a[i]
    root = _root_of_unity(n)
    if inverse:
        root = pow(root, R - 2, R)
    length = 2
    while length <= n:
        w_len = pow(root, n // length, R)
        for start in range(0, n, length):
            w = 1
            half = length // 2
            for k in range(start, start + half):
                u = a[k]
                v = a[k + half] * w % R
                a[k] = (u + v) % R
                a[k + half] = (u - v) % R
                w = w * w_len % R
        length *= 2
    if inverse:
        n_inv = pow(n, R - 2, R)
        a = [x * n_inv % R for x in a]
    return a


class CellContext:
    """DASContext analog: cell compute/verify/recover over one setup.

    `n` is the blob size (power of two), `cells` the cell count; mainnet
    is (4096, 128); tests shrink both. Requires a monomial setup."""

    def __init__(
        self,
        setup: Optional[TrustedSetup] = None,
        n: int = None,
        cells: int = CELLS_PER_EXT_BLOB,
        msm=None,
        pairing=None,
    ):
        # cell ops REQUIRE the monomial bases — ask dev() for them
        # explicitly (its default skips them above n=512)
        self.setup = setup or TrustedSetup.dev(with_monomial=True)
        self.n = n or len(self.setup.g1_lagrange)
        if self.setup.g1_monomial is None:
            raise KzgError("cell ops need a monomial trusted setup")
        if len(self.setup.g1_monomial) < self.n:
            raise KzgError("monomial setup shorter than blob size")
        self.ext_n = 2 * self.n
        self.cells = cells
        self.cell_size = self.ext_n // cells
        if self.cell_size < 1:
            raise KzgError("cell size underflow")
        if len(self.setup.g2_monomial or []) <= self.cell_size:
            raise KzgError("g2 monomial setup shorter than cell size + 1")
        self._msm = msm or _msm_host
        self._pairing = pairing or (
            lambda pairs: PF.pairings_product_is_one_fast(pairs)
        )
        self._w_ext = _root_of_unity(self.ext_n)

    # ------------------------------------------------------------ layout

    def coset_shift(self, cell_index: int) -> int:
        return pow(self._w_ext, _bit_reverse(cell_index, self.cells), R)

    def _coset_points(self, cell_index: int) -> list:
        h = self.coset_shift(cell_index)
        g = pow(self._w_ext, self.cells, R)  # order = cell_size
        pts, acc = [], h
        for _ in range(self.cell_size):
            pts.append(acc)
            acc = acc * g % R
        return pts

    # ------------------------------------------------------- compute

    def blob_to_coeffs(self, blob: bytes) -> list:
        """Evaluation form (bit-reversed domain, the 4844 layout) ->
        coefficient form."""
        fields = blob_to_field_elements(blob, self.n)
        nat = [0] * self.n
        for i, v in enumerate(fields):
            nat[_bit_reverse(i, self.n)] = v
        return fft(nat, inverse=True)

    def compute_cells_and_proofs(self, blob: bytes) -> tuple:
        """-> ([cells]: list of list[int], [proof points])."""
        coeffs = self.blob_to_coeffs(blob)
        ext_evals = fft(coeffs + [0] * (self.ext_n - self.n))
        cells_out = []
        for i in range(self.cells):
            shift_pow = _bit_reverse(i, self.cells)
            vals = []
            for j in range(self.cell_size):
                m = _bit_reverse(j, self.cell_size)
                idx = (shift_pow + self.cells * m) % self.ext_n
                vals.append(ext_evals[idx])
            cells_out.append(vals)
        proofs = [
            self._cell_proof(coeffs, i) for i in range(self.cells)
        ]
        return cells_out, proofs

    def _quotient_and_interpolant(self, coeffs: list, zc: int) -> tuple:
        """Divide p by Z(X) = X^c - zc: p = q Z + r, deg r < c.
        O(n) because X^c ≡ zc (mod Z)."""
        c = self.cell_size
        r = list(coeffs) + [0] * ((-len(coeffs)) % c)
        q = [0] * max(len(r) - c, 0)
        for i in range(len(r) - 1, c - 1, -1):
            q[i - c] = (q[i - c] + r[i]) % R
            r[i - c] = (r[i - c] + zc * r[i]) % R
            r[i] = 0
        return q, r[:c]

    def _cell_proof(self, coeffs: list, cell_index: int):
        h = self.coset_shift(cell_index)
        zc = pow(h, self.cell_size, R)
        q, _ = self._quotient_and_interpolant(coeffs, zc)
        if not any(q):
            return None  # identity proof (constant polynomial)
        return self._msm(self.setup.g1_monomial[: len(q)], q)

    # -------------------------------------------------------- verify

    def _interpolant_commitment(self, cell_index: int, cell_vals: list):
        """[I(tau)]G1 for the coset interpolant of one cell: un-bit-
        reverse to natural coset order, subgroup-IFFT, unscale by h."""
        c = self.cell_size
        nat = [0] * c
        for j, v in enumerate(cell_vals):
            nat[_bit_reverse(j, self.cell_size)] = v
        # I(h x) has subgroup-IFFT coeffs a_k; I coeffs = a_k h^{-k}.
        # The order-c subgroup's canonical root IS _root_of_unity(c)
        # (= w_ext^cells), so the plain size-c IFFT is the subgroup IFFT.
        sub = fft(nat, inverse=True)
        h_inv = pow(self.coset_shift(cell_index), R - 2, R)
        coeff, acc = [], 1
        for a in sub:
            coeff.append(a * acc % R)
            acc = acc * h_inv % R
        return coeff

    def verify_cell_proof_batch(
        self,
        commitments: Sequence,
        cell_indices: Sequence[int],
        cells: Sequence[Sequence[int]],
        proofs: Sequence,
    ) -> bool:
        """verify_cell_kzg_proof_batch: ONE pairing pair for any number
        of (commitment, cell, proof) rows via RLC."""
        if not (
            len(commitments) == len(cell_indices) == len(cells) == len(proofs)
        ):
            raise KzgError("length mismatch")
        if not cells:
            return True
        for idx, vals in zip(cell_indices, cells):
            if not 0 <= idx < self.cells:
                raise KzgError("cell index out of range")
            if len(vals) != self.cell_size:
                raise KzgError("bad cell size")
        rs = self._batch_challenges(commitments, cell_indices, cells, proofs)
        c = self.cell_size
        lhs_pts, lhs_scalars = [], []
        p_pts, p_scalars = [], []
        for (cm, idx, vals, pr), r in zip(
            zip(commitments, cell_indices, cells, proofs), rs
        ):
            h_c = pow(self.coset_shift(idx), c, R)
            lhs_pts.append(cm)
            lhs_scalars.append(r)
            icoeff = self._interpolant_commitment(idx, list(vals))
            for k, a in enumerate(icoeff):
                lhs_pts.append(self.setup.g1_monomial[k])
                lhs_scalars.append((-(a * r)) % R)
            if pr is not None:
                lhs_pts.append(pr)
                lhs_scalars.append(h_c * r % R)
                p_pts.append(pr)
                p_scalars.append(r)
        lhs = self._msm(lhs_pts, lhs_scalars)
        pagg = self._msm(p_pts, p_scalars)
        pairs = []
        if lhs is not None:
            pairs.append((lhs, G2_GEN))
        if pagg is not None:
            pairs.append((C.g1_neg(pagg), self.setup.g2_monomial[c]))
        if not pairs:
            return True
        return self._pairing(pairs)

    def _batch_challenges(self, commitments, indices, cells, proofs) -> list:
        data = RANDOM_CHALLENGE_KZG_CELL_BATCH_DOMAIN
        data += self.n.to_bytes(8, "little") + len(cells).to_bytes(8, "little")
        for cm, idx, vals, pr in zip(commitments, indices, cells, proofs):
            data += C.g1_compress(cm) + int(idx).to_bytes(8, "little")
            for v in vals:
                data += fr_to_bytes(v)
            data += C.g1_compress(pr) if pr is not None else b"\xc0" + b"\x00" * 47
        r = int.from_bytes(hashlib.sha256(data).digest(), "big") % R
        out, acc = [], 1
        for _ in cells:
            out.append(acc)
            acc = acc * r % R
        return out

    # ------------------------------------------------------- recover

    def recover_cells_and_proofs(
        self, cell_indices: Sequence[int], cells: Sequence[Sequence[int]]
    ) -> tuple:
        """Erasure-recover the full cell set (plus fresh proofs) from
        any >= 50% of cells (recover_cells_and_compute_kzg_proofs)."""
        have = dict(zip((int(i) for i in cell_indices), cells))
        if len(have) * 2 < self.cells:
            raise KzgError("need at least half the cells to recover")
        if len(have) == self.cells:
            coeffs = self._cells_to_coeffs(have)
        else:
            coeffs = self._recover_coeffs(have)
        # re-derive all cells directly from the coefficients
        ext_evals = fft(coeffs + [0] * (self.ext_n - self.n))
        out_cells = []
        for i in range(self.cells):
            shift_pow = _bit_reverse(i, self.cells)
            vals = []
            for j in range(self.cell_size):
                m = _bit_reverse(j, self.cell_size)
                vals.append(ext_evals[(shift_pow + self.cells * m) % self.ext_n])
            out_cells.append(vals)
        proofs = [self._cell_proof(coeffs, i) for i in range(self.cells)]
        return out_cells, proofs

    def _cells_to_coeffs(self, have: dict) -> list:
        ext = [0] * self.ext_n
        for i, vals in have.items():
            shift_pow = _bit_reverse(i, self.cells)
            for j, v in enumerate(vals):
                m = _bit_reverse(j, self.cell_size)
                ext[(shift_pow + self.cells * m) % self.ext_n] = v
        coeffs = fft(ext, inverse=True)
        if any(x != 0 for x in coeffs[self.n :]):
            raise KzgError("cells are not a degree-n extension")
        return coeffs[: self.n]

    def _recover_coeffs(self, have: dict) -> list:
        """Vanishing-polynomial erasure decoding (c-kzg recover):
        Z vanishes on missing cosets; (pZ) is recoverable from the
        received points; divide on a shifted domain."""
        missing = [i for i in range(self.cells) if i not in have]
        # Z(X) = prod (X^c - h_i^c): build by convolving sparse factors
        z = [1]
        c = self.cell_size
        for i in missing:
            hc = pow(self.coset_shift(i), c, R)
            nz = [0] * (len(z) + c)
            for d, coef in enumerate(z):
                nz[d] = (nz[d] - hc * coef) % R  # -h^c * X^d
                nz[d + c] = (nz[d + c] + coef) % R  # X^{d+c}
            z = nz
        z += [0] * (self.ext_n - len(z))
        z_evals = fft(z)

        ext = [0] * self.ext_n
        for i, vals in have.items():
            shift_pow = _bit_reverse(i, self.cells)
            for j, v in enumerate(vals):
                m = _bit_reverse(j, self.cell_size)
                ext[(shift_pow + self.cells * m) % self.ext_n] = v
        pz_evals = [e * zv % R for e, zv in zip(ext, z_evals)]
        pz_coeffs = fft(pz_evals, inverse=True)

        # divide on the coset s·domain where Z has no roots
        s = _PRIMITIVE_ROOT
        s_pows, acc = [], 1
        for _ in range(self.ext_n):
            s_pows.append(acc)
            acc = acc * s % R
        pz_shift = fft([a * sp % R for a, sp in zip(pz_coeffs, s_pows)])
        z_shift = fft([a * sp % R for a, sp in zip(z, s_pows)])
        inv_z = fr_batch_inverse(z_shift)
        p_shift = [a * b % R for a, b in zip(pz_shift, inv_z)]
        p_scaled = fft(p_shift, inverse=True)
        s_inv = pow(s, R - 2, R)
        coeffs, acc = [], 1
        for a in p_scaled:
            coeffs.append(a * acc % R)
            acc = acc * s_inv % R
        if any(x != 0 for x in coeffs[self.n :]):
            raise KzgError("recovered polynomial exceeds blob degree")
        return coeffs[: self.n]

    # ------------------------------------------------------ bytes I/O

    def cell_to_bytes(self, vals: Sequence[int]) -> bytes:
        return b"".join(fr_to_bytes(v) for v in vals)

    def cell_from_bytes(self, raw: bytes) -> list:
        if len(raw) != self.cell_size * 32:
            raise KzgError("bad cell byte length")
        return [
            bytes_to_fr(raw[i : i + 32]) for i in range(0, len(raw), 32)
        ]


