"""ctypes binding for the native Fr batch engine (native/fr_field.cpp).

The reference's KZG host math is C (c-kzg via crypto/kzg/src/lib.rs);
this is the analogous native seam for the barycentric-evaluation hot
path. Builds on demand with g++ (cached by source mtime, same pattern
as node/native_store.py); callers fall back to the pure-Python Fr path
when no toolchain is available — identical results, just slower.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_SRC = os.path.join(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    ),
    "native",
    "fr_field.cpp",
)
_SO = os.path.join(os.path.dirname(_SRC), "build", "libfr_field.so")

_lib = None
_build_err: Optional[str] = None
_build_lock = threading.Lock()


def _load():
    global _lib, _build_err
    with _build_lock:
        if _lib is not None or _build_err is not None:
            return _lib
        try:
            os.makedirs(os.path.dirname(_SO), exist_ok=True)
            if (
                not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            ):
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
                    check=True,
                    capture_output=True,
                )
            lib = ctypes.CDLL(_SO)
            lib.fr_eval_barycentric.restype = ctypes.c_int
            lib.fr_eval_barycentric.argtypes = [
                ctypes.c_char_p,
                ctypes.c_char_p,
                ctypes.c_char_p,
                ctypes.c_long,
                ctypes.c_long,
                ctypes.c_char_p,
            ]
            lib.fr_batch_inverse.restype = ctypes.c_int
            lib.fr_batch_inverse.argtypes = [
                ctypes.c_char_p,
                ctypes.c_long,
                ctypes.c_char_p,
            ]
            _lib = lib
        except (OSError, subprocess.CalledProcessError) as e:
            _build_err = str(e)
        return _lib


def available() -> bool:
    return _load() is not None


_ROOTS_BYTES_CACHE: dict = {}


def _roots_bytes(roots) -> bytes:
    # roots lists are long-lived TrustedSetup members; key by identity.
    # The cache entry HOLDS the keying list so its id can never be
    # recycled by a different roots object while the entry lives
    # (id-reuse after GC would silently serve another setup's domain).
    entry = _ROOTS_BYTES_CACHE.get(id(roots))
    if entry is None or entry[0] is not roots:
        encoded = b"".join(int(w).to_bytes(32, "big") for w in roots)
        _ROOTS_BYTES_CACHE.clear()  # setups change rarely; keep one
        _ROOTS_BYTES_CACHE[id(roots)] = (roots, encoded)
        return encoded
    return entry[1]


def eval_barycentric_batch(blobs, zs, roots) -> Optional[list]:
    """[blob bytes] x [z ints] -> [y ints] via the native engine, or
    None when the library is unavailable. Raises ValueError on a
    non-canonical field element (mirrors bytes_to_fr)."""
    lib = _load()
    if lib is None:
        return None
    n = len(roots)
    fields = b"".join(blobs)
    zbytes = b"".join(int(z).to_bytes(32, "big") for z in zs)
    out = ctypes.create_string_buffer(32 * len(blobs))
    rc = lib.fr_eval_barycentric(
        fields, zbytes, _roots_bytes(roots), len(blobs), n, out
    )
    if rc != 0:
        raise ValueError(f"non-canonical field element (index {-rc - 1})")
    raw = out.raw
    return [
        int.from_bytes(raw[32 * i : 32 * (i + 1)], "big")
        for i in range(len(blobs))
    ]
