"""Device execution for KZG batch verification (VERDICT r1 #9: the MSM
AND the pairings move off the host).

`device_kzg(setup)` builds a `Kzg` whose MSM seam is the windowed
device kernel (ops/lane/msm — round 3 moved it onto the lane-major
Pallas stack) and whose pairing seam runs the 2-pairing product check
as one jitted program: batched Miller loops + shared final
exponentiation (ops/lane/pairing), the same kernel family the BLS
verifier uses (crypto/kzg/src/lib.rs:156-183 parity on TPU).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops.lane import fp, msm as dev_msm, pairing as OP, tower
from . import Kzg, TrustedSetup


@jax.jit
def _pairing_product_kernel(px, py, p_inf, qx, qy, q_inf):
    """e-product over packed affine pairs == 1 (after final exp)."""
    fs = OP.miller_loop(px, py, qx, qy, p_inf=p_inf, q_inf=q_inf)
    return jnp.all(OP.pairing_product_is_one(fs, px.shape[-1]))


def pairings_product_is_one_device(pairs) -> bool:
    """pairs: [(G1 affine | None, G2 affine | None)] host points."""
    p_inf = [g1 is None for g1, _ in pairs] or [True]
    q_inf = [g2 is None for _, g2 in pairs] or [True]
    px = fp.pack([g1[0] if g1 else 0 for g1, _ in pairs] or [0])
    py = fp.pack([g1[1] if g1 else 0 for g1, _ in pairs] or [0])
    qx = tower.f2_pack_many(
        [g2[0] if g2 else (0, 0) for _, g2 in pairs] or [(0, 0)]
    )
    qy = tower.f2_pack_many(
        [g2[1] if g2 else (0, 0) for _, g2 in pairs] or [(0, 0)]
    )
    out = _pairing_product_kernel(
        jnp.asarray(px),
        jnp.asarray(py),
        jnp.asarray(np.array(p_inf)),
        jnp.asarray(qx),
        jnp.asarray(qy),
        jnp.asarray(np.array(q_inf)),
    )
    return bool(np.asarray(out))


def device_kzg(setup: TrustedSetup = None) -> Kzg:
    """A Kzg service with device MSM + device pairing seams."""
    return Kzg(
        setup,
        msm=dev_msm.msm_g1,
        pairing=pairings_product_is_one_device,
        msm_multi=dev_msm.msm_g1_groups,
    )
