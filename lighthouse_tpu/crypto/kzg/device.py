"""Device execution for KZG batch verification (VERDICT r1 #9: the MSM
AND the pairings move off the host).

`device_kzg(setup)` builds a `Kzg` whose MSM seam is the windowed
device kernel (ops/msm) and whose pairing seam runs the 2-pairing
product check as one jitted program: batched Miller loops + shared
final exponentiation (ops/pairing), the same kernel family the BLS
verifier uses (crypto/kzg/src/lib.rs:156-183 parity on TPU).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...ops import fp, msm as dev_msm, pairing as OP, tower
from . import Kzg, TrustedSetup


@jax.jit
def _pairing_product_kernel(px, py, p_inf, qx, qy, q_inf):
    """e-product over packed affine pairs == 1 (after final exp)."""
    fs = OP.miller_loop(px, py, qx, qy, p_inf=p_inf, q_inf=q_inf)
    return OP.pairing_product_is_one(fs, px.shape[0])


def pairings_product_is_one_device(pairs) -> bool:
    """pairs: [(G1 affine | None, G2 affine | None)] host points."""
    n = max(1, len(pairs))
    px, py, qx, qy, p_inf, q_inf = [], [], [], [], [], []
    for g1, g2 in pairs:
        p_inf.append(g1 is None)
        q_inf.append(g2 is None)
        px.append(fp.to_limbs(g1[0] if g1 else 0))
        py.append(fp.to_limbs(g1[1] if g1 else 0))
        qx.append(tower.f2_pack(g2[0] if g2 else (0, 0)))
        qy.append(tower.f2_pack(g2[1] if g2 else (0, 0)))
    while len(px) < n:  # empty input: trivially one
        p_inf.append(True)
        q_inf.append(True)
        px.append(fp.to_limbs(0))
        py.append(fp.to_limbs(0))
        qx.append(tower.f2_pack((0, 0)))
        qy.append(tower.f2_pack((0, 0)))
    out = _pairing_product_kernel(
        jnp.asarray(np.stack(px)),
        jnp.asarray(np.stack(py)),
        jnp.asarray(np.array(p_inf)),
        jnp.asarray(np.stack(qx)),
        jnp.asarray(np.stack(qy)),
        jnp.asarray(np.array(q_inf)),
    )
    return bool(np.asarray(out))


def device_kzg(setup: TrustedSetup = None) -> Kzg:
    """A Kzg service with device MSM + device pairing seams."""
    return Kzg(
        setup,
        msm=dev_msm.msm_g1,
        pairing=pairings_product_is_one_device,
    )
