"""ChaCha20-Poly1305 AEAD (RFC 8439) — the noise transport cipher
(lighthouse_network's Noise_XX_25519_ChaChaPoly_SHA256 stack). Pure
Python, pinned against the RFC 8439 §2.4.2/§2.5.2/§2.8.2 vectors in
tests/test_noise.py."""

from __future__ import annotations

import struct

_MASK32 = 0xFFFFFFFF


def _rotl(v: int, n: int) -> int:
    return ((v << n) | (v >> (32 - n))) & _MASK32


def _quarter(state, a, b, c, d):
    state[a] = (state[a] + state[b]) & _MASK32
    state[d] = _rotl(state[d] ^ state[a], 16)
    state[c] = (state[c] + state[d]) & _MASK32
    state[b] = _rotl(state[b] ^ state[c], 12)
    state[a] = (state[a] + state[b]) & _MASK32
    state[d] = _rotl(state[d] ^ state[a], 8)
    state[c] = (state[c] + state[d]) & _MASK32
    state[b] = _rotl(state[b] ^ state[c], 7)


def _chacha20_block(key: bytes, counter: int, nonce: bytes) -> bytes:
    state = (
        [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574]
        + list(struct.unpack("<8I", key))
        + [counter]
        + list(struct.unpack("<3I", nonce))
    )
    working = list(state)
    for _ in range(10):
        _quarter(working, 0, 4, 8, 12)
        _quarter(working, 1, 5, 9, 13)
        _quarter(working, 2, 6, 10, 14)
        _quarter(working, 3, 7, 11, 15)
        _quarter(working, 0, 5, 10, 15)
        _quarter(working, 1, 6, 11, 12)
        _quarter(working, 2, 7, 8, 13)
        _quarter(working, 3, 4, 9, 14)
    return struct.pack(
        "<16I", *((w + s) & _MASK32 for w, s in zip(working, state))
    )


def chacha20_xor(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
    out = bytearray()
    for i in range(0, len(data), 64):
        block = _chacha20_block(key, counter + i // 64, nonce)
        chunk = data[i : i + 64]
        out += bytes(a ^ b for a, b in zip(chunk, block))
    return bytes(out)


def poly1305(key: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key[16:32], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        n = int.from_bytes(block + b"\x01", "little")
        acc = (acc + n) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    return b"\x00" * (-len(data) % 16)


def seal(key: bytes, nonce: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
    """AEAD encrypt -> ciphertext || 16-byte tag (RFC 8439 §2.8)."""
    otk = _chacha20_block(key, 0, nonce)[:32]
    ct = chacha20_xor(key, 1, nonce, plaintext)
    mac_data = (
        aad
        + _pad16(aad)
        + ct
        + _pad16(ct)
        + struct.pack("<QQ", len(aad), len(ct))
    )
    return ct + poly1305(otk, mac_data)


def open_(key: bytes, nonce: bytes, sealed: bytes, aad: bytes = b"") -> bytes:
    """AEAD decrypt; raises ValueError on tag mismatch."""
    import hmac as _hmac

    if len(sealed) < 16:
        raise ValueError("ciphertext too short")
    ct, tag = sealed[:-16], sealed[-16:]
    otk = _chacha20_block(key, 0, nonce)[:32]
    mac_data = (
        aad
        + _pad16(aad)
        + ct
        + _pad16(ct)
        + struct.pack("<QQ", len(aad), len(ct))
    )
    if not _hmac.compare_digest(poly1305(otk, mac_data), tag):
        raise ValueError("chacha20poly1305: tag mismatch")
    return chacha20_xor(key, 1, nonce, ct)
