"""secp256k1 ECDSA — the discv5 ENR identity scheme ("v4") signature
algorithm (enr crate / discv5 dependency in the reference). Pure
Python: ENR signing/verification happens at discovery cadence, not on
a hot path. Deterministic nonces per RFC 6979 (required for
reproducible ENR vectors). Pinned against the EIP-778 example record in
tests/test_enr.py (known private key -> known signed ENR)."""

from __future__ import annotations

import hashlib
import hmac

P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
Gx = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
Gy = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _inv(a: int, m: int) -> int:
    return pow(a, m - 2, m)


def _add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * _inv(2 * y1, P) % P
    else:
        lam = (y2 - y1) * _inv((x2 - x1) % P, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return x3, (lam * (x1 - x3) - y1) % P


# ------------------------------------------------- jacobian fast path
# Scalar multiplication runs in Jacobian coordinates: ONE field
# inversion per multiplication instead of one per point ADDITION
# (~256x fewer `pow(x, P-2, P)` calls). Discovery handshakes do 4 EC
# muls each (id_sign/id_verify/ecdh), so the affine version made every
# discv5 session setup cost ~a second of pure Python.


def _jadd(p1, p2):
    """Jacobian add; points are (X, Y, Z), Z=0 = infinity."""
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    if z1 == 0:
        return p2
    if z2 == 0:
        return p1
    z1z1 = z1 * z1 % P
    z2z2 = z2 * z2 % P
    u1 = x1 * z2z2 % P
    u2 = x2 * z1z1 % P
    s1 = y1 * z2 * z2z2 % P
    s2 = y2 * z1 * z1z1 % P
    if u1 == u2:
        if s1 != s2:
            return (1, 1, 0)  # infinity
        return _jdbl(p1)
    h = (u2 - u1) % P
    hh = h * h % P
    hhh = h * hh % P
    r = (s2 - s1) % P
    v = u1 * hh % P
    x3 = (r * r - hhh - 2 * v) % P
    y3 = (r * (v - x3) - s1 * hhh) % P
    z3 = z1 * z2 * h % P
    return (x3, y3, z3)


def _jdbl(p):
    x1, y1, z1 = p
    if z1 == 0 or y1 == 0:
        return (1, 1, 0)
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = b * b % P
    d = 2 * ((x1 + b) * (x1 + b) - a - c) % P
    e = 3 * a % P
    f = e * e % P
    x3 = (f - 2 * d) % P
    y3 = (e * (d - x3) - 8 * c) % P
    z3 = 2 * y1 * z1 % P
    return (x3, y3, z3)


def _mul(k: int, point):
    if point is None or k % N == 0:
        return None
    acc = (1, 1, 0)
    addend = (point[0], point[1], 1)
    while k:
        if k & 1:
            acc = _jadd(acc, addend)
        addend = _jdbl(addend)
        k >>= 1
    if acc[2] == 0:
        return None
    zinv = _inv(acc[2], P)
    zinv2 = zinv * zinv % P
    return (acc[0] * zinv2 % P, acc[1] * zinv2 * zinv % P)


def pubkey(private: bytes):
    return _mul(int.from_bytes(private, "big"), (Gx, Gy))


def pubkey_compressed(private: bytes) -> bytes:
    x, y = pubkey(private)
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def decompress(pub: bytes):
    if len(pub) != 33 or pub[0] not in (2, 3):
        raise ValueError("bad compressed secp256k1 point")
    x = int.from_bytes(pub[1:], "big")
    y2 = (pow(x, 3, P) + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("not on curve")
    if (y & 1) != (pub[0] & 1):
        y = P - y
    return x, y


def _rfc6979_k(msg_hash: bytes, private: bytes) -> int:
    """Deterministic nonce (RFC 6979, SHA-256)."""
    v = b"\x01" * 32
    k = b"\x00" * 32
    x = private
    h1 = msg_hash
    k = hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(msg_hash: bytes, private: bytes) -> bytes:
    """64-byte r||s signature (low-s normalized, the ENR convention)."""
    z = int.from_bytes(msg_hash, "big")
    d = int.from_bytes(private, "big")
    while True:
        k = _rfc6979_k(msg_hash, private)
        x, _y = _mul(k, (Gx, Gy))
        r = x % N
        if r == 0:
            continue
        s = _inv(k, N) * (z + r * d) % N
        if s == 0:
            continue
        if s > N // 2:
            s = N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def verify(msg_hash: bytes, sig: bytes, pub) -> bool:
    if len(sig) != 64:
        return False
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    if not (1 <= r < N and 1 <= s < N):
        return False
    if isinstance(pub, (bytes, bytearray)):
        try:
            pub = decompress(bytes(pub))
        except ValueError:
            return False
    z = int.from_bytes(msg_hash, "big")
    w = _inv(s, N)
    u1 = z * w % N
    u2 = r * w % N
    pt = _add(_mul(u1, (Gx, Gy)), _mul(u2, pub))
    if pt is None:
        return False
    return pt[0] % N == r
