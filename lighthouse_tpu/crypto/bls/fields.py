"""Pure-Python BLS12-381 field tower: Fp, Fp2, Fp6, Fp12.

This module is the *reference/oracle* arithmetic: small, obviously-correct,
operating on Python ints and tuples. The TPU execution backend
(lighthouse_tpu/ops/) is validated element-for-element against it.

Tower (standard):
    Fp2  = Fp[u]  / (u^2 + 1)
    Fp6  = Fp2[v] / (v^3 - xi),  xi = 1 + u
    Fp12 = Fp6[w] / (w^2 - v)

Representations:
    Fp   : int in [0, P)
    Fp2  : (c0, c1)            meaning c0 + c1*u
    Fp6  : (a0, a1, a2)        ai in Fp2, meaning a0 + a1*v + a2*v^2
    Fp12 : (b0, b1)            bi in Fp6, meaning b0 + b1*w
"""

from .params import P, XI

# ---------------------------------------------------------------- Fp

def fadd(a, b):
    return (a + b) % P


def fsub(a, b):
    return (a - b) % P


def fmul(a, b):
    return (a * b) % P


def finv(a):
    if a == 0:
        raise ZeroDivisionError("inverse of 0 in Fp")
    return pow(a, P - 2, P)


def fsqrt(a):
    """Square root in Fp (P % 4 == 3 so a^((P+1)/4) works). None if no root."""
    r = pow(a, (P + 1) // 4, P)
    return r if r * r % P == a else None


# ---------------------------------------------------------------- Fp2

F2_ZERO = (0, 0)
F2_ONE = (1, 0)


def f2add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2neg(a):
    return (-a[0] % P, -a[1] % P)


def f2mul(a, b):
    # (a0 + a1 u)(b0 + b1 u) = (a0 b0 - a1 b1) + (a0 b1 + a1 b0) u
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def f2sqr(a):
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    t0 = (a[0] + a[1]) * (a[0] - a[1])
    t1 = 2 * a[0] * a[1]
    return (t0 % P, t1 % P)


def f2smul(a, k):
    """Multiply Fp2 element by Fp scalar."""
    return (a[0] * k % P, a[1] * k % P)


def f2conj(a):
    return (a[0], -a[1] % P)


def f2inv(a):
    # 1/(a0 + a1 u) = (a0 - a1 u) / (a0^2 + a1^2)
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    inv = finv(norm)
    return (a[0] * inv % P, -a[1] * inv % P)


def f2mul_xi(a):
    """Multiply by xi = 1 + u: (a0 - a1) + (a0 + a1) u."""
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def f2pow(a, e):
    out = F2_ONE
    base = a
    while e:
        if e & 1:
            out = f2mul(out, base)
        base = f2sqr(base)
        e >>= 1
    return out


def f2sqrt(a):
    """Square root in Fp2, None if a is a non-residue.

    Uses the p % 4 == 3 algorithm (Adj–Rodríguez-Henríquez):
        a1 = a^((p-3)/4); x0 = a1 * a; alpha = a1 * x0
        if alpha == -1: root = i * x0
        else: root = (1 + alpha)^((p-1)/2) * x0
    """
    if a == F2_ZERO:
        return F2_ZERO
    a1 = f2pow(a, (P - 3) // 4)
    x0 = f2mul(a1, a)
    alpha = f2mul(a1, x0)
    if alpha == (P - 1, 0):
        root = (-x0[1] % P, x0[0])  # u * x0
    else:
        b = f2pow(f2add(F2_ONE, alpha), (P - 1) // 2)
        root = f2mul(b, x0)
    return root if f2sqr(root) == a else None


# ---------------------------------------------------------------- Fp6

F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f6add(a, b):
    return (f2add(a[0], b[0]), f2add(a[1], b[1]), f2add(a[2], b[2]))


def f6sub(a, b):
    return (f2sub(a[0], b[0]), f2sub(a[1], b[1]), f2sub(a[2], b[2]))


def f6neg(a):
    return (f2neg(a[0]), f2neg(a[1]), f2neg(a[2]))


def f6mul(a, b):
    # Toom/Karatsuba-lite (standard v^3 = xi reduction)
    t0 = f2mul(a[0], b[0])
    t1 = f2mul(a[1], b[1])
    t2 = f2mul(a[2], b[2])
    c0 = f2add(t0, f2mul_xi(f2sub(f2mul(f2add(a[1], a[2]), f2add(b[1], b[2])), f2add(t1, t2))))
    c1 = f2add(f2sub(f2mul(f2add(a[0], a[1]), f2add(b[0], b[1])), f2add(t0, t1)), f2mul_xi(t2))
    c2 = f2add(f2sub(f2mul(f2add(a[0], a[2]), f2add(b[0], b[2])), f2add(t0, t2)), t1)
    return (c0, c1, c2)


def f6sqr(a):
    return f6mul(a, a)


def f6mul_by_v(a):
    """Multiply by v: (a0 + a1 v + a2 v^2) * v = xi*a2 + a0 v + a1 v^2."""
    return (f2mul_xi(a[2]), a[0], a[1])


def f6inv(a):
    # Standard formula via the norm to Fp2.
    c0 = f2sub(f2sqr(a[0]), f2mul_xi(f2mul(a[1], a[2])))
    c1 = f2sub(f2mul_xi(f2sqr(a[2])), f2mul(a[0], a[1]))
    c2 = f2sub(f2sqr(a[1]), f2mul(a[0], a[2]))
    t = f2add(f2mul(a[0], c0), f2mul_xi(f2add(f2mul(a[2], c1), f2mul(a[1], c2))))
    ti = f2inv(t)
    return (f2mul(c0, ti), f2mul(c1, ti), f2mul(c2, ti))


# ---------------------------------------------------------------- Fp12

F12_ZERO = (F6_ZERO, F6_ZERO)
F12_ONE = (F6_ONE, F6_ZERO)


def f12add(a, b):
    return (f6add(a[0], b[0]), f6add(a[1], b[1]))


def f12sub(a, b):
    return (f6sub(a[0], b[0]), f6sub(a[1], b[1]))


def f12mul(a, b):
    t0 = f6mul(a[0], b[0])
    t1 = f6mul(a[1], b[1])
    c0 = f6add(t0, f6mul_by_v(t1))
    c1 = f6sub(f6sub(f6mul(f6add(a[0], a[1]), f6add(b[0], b[1])), t0), t1)
    return (c0, c1)


def f12sqr(a):
    return f12mul(a, a)


def f12conj(a):
    """Conjugation = Frobenius^6: a0 - a1 w."""
    return (a[0], f6neg(a[1]))


def f12inv(a):
    t = f6sub(f6sqr(a[0]), f6mul_by_v(f6sqr(a[1])))
    ti = f6inv(t)
    return (f6mul(a[0], ti), f6neg(f6mul(a[1], ti)))


def f12pow(a, e):
    if e < 0:
        return f12pow(f12inv(a), -e)
    out = F12_ONE
    base = a
    while e:
        if e & 1:
            out = f12mul(out, base)
        base = f12sqr(base)
        e >>= 1
    return out


# ------------------------------------------------- Frobenius on Fp2/Fp12

# frobenius on Fp2 is conjugation (since u^p = -u for p % 4 == 3).

# gamma constants for the psi endomorphism on the twist, computed at import
# (no magic constants): psi(x, y) = (PSI_CX * x^p, PSI_CY * y^p) maps the
# twist E2 to itself composed with untwist-frobenius-twist.
def _compute_psi_constants():
    # 1 / xi^((p-1)/3) and 1 / xi^((p-1)/2) in Fp2
    cx = f2inv(f2pow(XI, (P - 1) // 3))
    cy = f2inv(f2pow(XI, (P - 1) // 2))
    return cx, cy


PSI_CX, PSI_CY = _compute_psi_constants()
