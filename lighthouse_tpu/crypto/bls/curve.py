"""Pure-Python BLS12-381 group operations: G1 (over Fp) and G2 (over Fp2).

Reference/oracle implementation. Points are affine: ``None`` is the point at
infinity, otherwise ``(x, y)`` with coordinates in the base field (int for G1,
(c0, c1) tuples for G2).

Serialization follows the ZCash/Ethereum compressed format the reference
consumes on the wire (48-byte G1 / 96-byte G2, flag bits in the top byte),
mirroring what blst implements for crypto/bls/src/impls/blst.rs.
"""

from . import params
from .params import P, R, B
from . import fields as F


# ---------------------------------------------------------------- generic ops

def _make_ops(add, sub, mul, sqr, inv, neg, zero, one, b_coeff):
    """Build affine curve ops for y^2 = x^3 + b over a generic field."""

    def on_curve(pt):
        if pt is None:
            return True
        x, y = pt
        return sqr(y) == add(mul(sqr(x), x), b_coeff)

    def pt_neg(pt):
        if pt is None:
            return None
        return (pt[0], neg(pt[1]))

    def pt_double(pt):
        if pt is None:
            return None
        x, y = pt
        if y == zero:
            return None
        lam = mul(add(sqr(x), add(sqr(x), sqr(x))), inv(add(y, y)))  # 3x^2 / 2y
        x3 = sub(sqr(lam), add(x, x))
        y3 = sub(mul(lam, sub(x, x3)), y)
        return (x3, y3)

    def pt_add(p1, p2):
        if p1 is None:
            return p2
        if p2 is None:
            return p1
        x1, y1 = p1
        x2, y2 = p2
        if x1 == x2:
            if y1 == y2:
                return pt_double(p1)
            return None
        lam = mul(sub(y2, y1), inv(sub(x2, x1)))
        x3 = sub(sub(sqr(lam), x1), x2)
        y3 = sub(mul(lam, sub(x1, x3)), y1)
        return (x3, y3)

    # Scalar ladders run in Jacobian coordinates internally: affine
    # add/double pay a field inversion PER STEP (Fermat pow — the
    # dominant cost in profiles), Jacobian pays ONE at the end.

    def _jac_double(p):
        if p is None:
            return None
        X, Y, Z = p
        A = sqr(X)
        Bv = sqr(Y)
        Cv = sqr(Bv)
        D = sub(sub(sqr(add(X, Bv)), A), Cv)
        D = add(D, D)
        E = add(add(A, A), A)
        Fv = sqr(E)
        X3 = sub(Fv, add(D, D))
        c8 = add(Cv, Cv)
        c8 = add(c8, c8)
        c8 = add(c8, c8)
        Y3 = sub(mul(E, sub(D, X3)), c8)
        Z3 = mul(add(Y, Y), Z)
        return (X3, Y3, Z3)

    def _jac_add(p, q):
        if p is None:
            return q
        if q is None:
            return p
        X1, Y1, Z1 = p
        X2, Y2, Z2 = q
        Z1Z1 = sqr(Z1)
        Z2Z2 = sqr(Z2)
        U1 = mul(X1, Z2Z2)
        U2 = mul(X2, Z1Z1)
        S1 = mul(mul(Y1, Z2), Z2Z2)
        S2 = mul(mul(Y2, Z1), Z1Z1)
        if U1 == U2:
            if S1 == S2:
                return _jac_double(p)
            return None
        H = sub(U2, U1)
        I = sqr(add(H, H))
        J = mul(H, I)
        r2 = sub(S2, S1)
        rr = add(r2, r2)
        V = mul(U1, I)
        X3 = sub(sub(sqr(rr), J), add(V, V))
        SJ = mul(S1, J)
        Y3 = sub(mul(rr, sub(V, X3)), add(SJ, SJ))
        Z3 = mul(sub(sub(sqr(add(Z1, Z2)), Z1Z1), Z2Z2), H)
        return (X3, Y3, Z3)

    def _jac_from_affine(pt):
        return None if pt is None else (pt[0], pt[1], one)

    def _jac_to_affine(p):
        if p is None or p[2] == zero:
            return None
        X, Y, Z = p
        zi = inv(Z)
        zi2 = sqr(zi)
        return (mul(X, zi2), mul(mul(Y, zi2), zi))

    def _ladder(pt, k):
        out = None
        acc = _jac_from_affine(pt)
        while k:
            if k & 1:
                out = _jac_add(out, acc)
            acc = _jac_double(acc)
            k >>= 1
        return _jac_to_affine(out)

    def pt_mul(pt, k):
        k = k % R
        return _ladder(pt, k)

    def pt_mul_raw(pt, k):
        """Scalar mul WITHOUT reducing k mod R (for cofactor clearing)."""
        if k < 0:
            return pt_mul_raw(pt_neg(pt), -k)
        return _ladder(pt, k)

    return on_curve, pt_neg, pt_double, pt_add, pt_mul, pt_mul_raw


(g1_on_curve, g1_neg, g1_double, g1_add, g1_mul, g1_mul_raw) = _make_ops(
    F.fadd, F.fsub, F.fmul, lambda a: a * a % P, F.finv, lambda a: -a % P, 0, 1, B
)

_B2 = F.f2smul(params.XI, B)  # 4*(1+u)
(g2_on_curve, g2_neg, g2_double, g2_add, g2_mul, g2_mul_raw) = _make_ops(
    F.f2add, F.f2sub, F.f2mul, F.f2sqr, F.f2inv, F.f2neg, F.F2_ZERO, F.F2_ONE, _B2
)

G1_GEN = (params.G1X, params.G1Y)
G2_GEN = (params.G2X, params.G2Y)


# ---------------------------------------------------------------- endomorphisms

def psi(pt):
    """The psi endomorphism on the twist: untwist ∘ frobenius ∘ twist.

    psi(x, y) = (cx * x̄, cy * ȳ) with the constants computed in fields.py.
    Satisfies psi(P) == [X] P for P in G2 (used for fast subgroup checks and
    Budroni–Pintore cofactor clearing).
    """
    if pt is None:
        return None
    x, y = pt
    return (F.f2mul(F.PSI_CX, F.f2conj(x)), F.f2mul(F.PSI_CY, F.f2conj(y)))


def g2_subgroup_check(pt):
    """P ∈ G2 iff psi(P) == [X]P (Scott's fast check)."""
    if pt is None:
        return True
    if not g2_on_curve(pt):
        return False
    return psi(pt) == g2_mul_raw(pt, params.X % R) if params.X >= 0 else (
        psi(pt) == g2_neg(g2_mul_raw(pt, -params.X))
    )


def g1_subgroup_check(pt):
    """Reference check: [R]P == infinity."""
    if pt is None:
        return True
    if not g1_on_curve(pt):
        return False
    return g1_mul_raw(pt, R) is None


def g2_clear_cofactor(pt):
    """Budroni–Pintore fast cofactor clearing:

    h_eff · P ≡ [X^2 - X - 1]P + [X - 1]psi(P) + psi(psi(2P))   (mod G2)
    """
    x = params.X
    t0 = g2_mul_raw(pt, -(x * x - x - 1)) if (x * x - x - 1) < 0 else g2_mul_raw(pt, x * x - x - 1)
    t1 = g2_mul_raw(psi(pt), x - 1) if (x - 1) >= 0 else g2_neg(g2_mul_raw(psi(pt), -(x - 1)))
    t2 = psi(psi(g2_double(pt)))
    return g2_add(g2_add(t0, t1), t2)


# ---------------------------------------------------------------- serialization
# ZCash-style compressed encoding (what Ethereum consensus uses on the wire).

_SIGN_THRESHOLD = (P - 1) // 2


def _flags(compressed, infinity, sign):
    return (compressed << 7) | (infinity << 6) | (sign << 5)


def g1_compress(pt):
    if pt is None:
        return bytes([_flags(1, 1, 0)]) + b"\x00" * 47
    x, y = pt
    sign = 1 if y > _SIGN_THRESHOLD else 0
    raw = x.to_bytes(48, "big")
    return bytes([raw[0] | _flags(1, 0, sign)]) + raw[1:]


def g1_decompress(data, subgroup_check=True):
    """Decompress 48 bytes → G1 point. Raises ValueError on invalid encoding."""
    if len(data) != 48:
        raise ValueError("G1 compressed encoding must be 48 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G1 not supported on this codec")
    infinity, sign = (flags >> 6) & 1, (flags >> 5) & 1
    x = int.from_bytes(bytes([flags & 0x1F]) + data[1:], "big")
    if infinity:
        if x != 0 or sign:
            raise ValueError("malformed infinity encoding")
        return None
    if x >= P:
        raise ValueError("x out of range")
    y = F.fsqrt((x * x % P * x + B) % P)
    if y is None:
        raise ValueError("x not on curve")
    if (1 if y > _SIGN_THRESHOLD else 0) != sign:
        y = P - y
    pt = (x, y)
    if subgroup_check and not g1_subgroup_check(pt):
        raise ValueError("point not in G1 subgroup")
    return pt


def g2_compress(pt):
    if pt is None:
        return bytes([_flags(1, 1, 0)]) + b"\x00" * 95
    (x0, x1), (y0, y1) = pt
    # lexicographic sign on y: compare (y1, y0)
    sign = 1 if (y1 > _SIGN_THRESHOLD or (y1 == 0 and y0 > _SIGN_THRESHOLD)) else 0
    raw = x1.to_bytes(48, "big") + x0.to_bytes(48, "big")
    return bytes([raw[0] | _flags(1, 0, sign)]) + raw[1:]


def g2_decompress(data, subgroup_check=True):
    if len(data) != 96:
        raise ValueError("G2 compressed encoding must be 96 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G2 not supported on this codec")
    infinity, sign = (flags >> 6) & 1, (flags >> 5) & 1
    x1 = int.from_bytes(bytes([flags & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if infinity:
        if x0 or x1 or sign:
            raise ValueError("malformed infinity encoding")
        return None
    if x0 >= P or x1 >= P:
        raise ValueError("x out of range")
    x = (x0, x1)
    rhs = F.f2add(F.f2mul(F.f2sqr(x), x), _B2)
    y = F.f2sqrt(rhs)
    if y is None:
        raise ValueError("x not on curve")
    y0, y1 = y
    got_sign = 1 if (y1 > _SIGN_THRESHOLD or (y1 == 0 and y0 > _SIGN_THRESHOLD)) else 0
    if got_sign != sign:
        y = F.f2neg(y)
    pt = (x, y)
    if subgroup_check and not g2_subgroup_check(pt):
        raise ValueError("point not in G2 subgroup")
    return pt
