"""Pure-Python optimal-ate pairing on BLS12-381 (reference/oracle).

Deliberately the *generic* construction: G2 points are untwisted into
E(Fp12) and the Miller loop runs with full Fp12 affine arithmetic and true
field inversions. Slow but convention-proof — the optimized TPU pipeline
(sparse lines, projective coords, cyclotomic final exp) is validated against
this module (see reference hot path crypto/bls/src/impls/blst.rs:114-116,
which delegates the same math to blst's verify_multiple_aggregate_signatures).
"""

from . import params
from .params import P, R
from . import fields as F

# w and its inverse powers, for the untwist E2(Fp2) -> E(Fp12).
_W = (F.F6_ZERO, F.F6_ONE)  # w: w^2 = v, v^3 = xi
_WINV = F.f12inv(_W)
_WINV2 = F.f12mul(_WINV, _WINV)
_WINV3 = F.f12mul(_WINV2, _WINV)


def _emb2(a):
    """Embed Fp2 element into Fp12 (c0 slot of c0 slot)."""
    return ((a, F.F2_ZERO, F.F2_ZERO), F.F6_ZERO)


def _emb(a):
    """Embed Fp element into Fp12."""
    return _emb2((a, 0))


def untwist(q):
    """Map a point on the M-twist E2(Fp2) to E(Fp12): (x/w^2, y/w^3)."""
    if q is None:
        return None
    x, y = q
    return (F.f12mul(_emb2(x), _WINV2), F.f12mul(_emb2(y), _WINV3))


# Affine ops on E(Fp12): y^2 = x^3 + 4.
def _e12_double(pt):
    x, y = pt
    x2 = F.f12sqr(x)
    lam = F.f12mul(
        F.f12add(F.f12add(x2, x2), x2), F.f12inv(F.f12add(y, y))
    )
    x3 = F.f12sub(F.f12sqr(lam), F.f12add(x, x))
    y3 = F.f12sub(F.f12mul(lam, F.f12sub(x, x3)), y)
    return (x3, y3), lam


def _e12_add(p1, p2):
    x1, y1 = p1
    x2, y2 = p2
    lam = F.f12mul(F.f12sub(y2, y1), F.f12inv(F.f12sub(x2, x1)))
    x3 = F.f12sub(F.f12sub(F.f12sqr(lam), x1), x2)
    y3 = F.f12sub(F.f12mul(lam, F.f12sub(x1, x3)), y1)
    return (x3, y3), lam


def _line_eval(t, lam, p12):
    """Evaluate the line through t with slope lam at p12 (all in Fp12)."""
    xt, yt = t
    xp, yp = p12
    return F.f12sub(F.f12sub(yp, yt), F.f12mul(lam, F.f12sub(xp, xt)))


def miller_loop(p_g1, q_g2):
    """f_{|X|, Q}(P) with the ate loop count |X|; inverted for X < 0.

    p_g1: affine G1 point (ints); q_g2: affine G2 point (Fp2 pairs).
    Returns an Fp12 element (before final exponentiation).
    """
    if p_g1 is None or q_g2 is None:
        return F.F12_ONE
    pp = (_emb(p_g1[0]), _emb(p_g1[1]))
    qq = untwist(q_g2)
    n = -params.X  # positive loop count (X < 0 for BLS12-381)
    bits = bin(n)[3:]  # skip the leading 1
    f = F.F12_ONE
    t = qq
    for b in bits:
        t2, lam = _e12_double(t)
        f = F.f12mul(F.f12sqr(f), _line_eval(t, lam, pp))
        t = t2
        if b == "1":
            t2, lam = _e12_add(t, qq)
            f = F.f12mul(f, _line_eval(t, lam, pp))
            t = t2
    # X is negative: f_{-n} = 1 / f_n (vertical lines cancel under final exp)
    return F.f12inv(f)


FINAL_EXP_POWER = (P**12 - 1) // R


def final_exponentiation(f):
    return F.f12pow(f, FINAL_EXP_POWER)


def pairing(p_g1, q_g2):
    """Full pairing e(P, Q) ∈ mu_r ⊂ Fp12."""
    return final_exponentiation(miller_loop(p_g1, q_g2))


def multi_pairing(pairs):
    """prod_i e(P_i, Q_i): shared final exponentiation over the product of
    Miller loops — the structure the batch verifier exploits
    (one final exp per verify_signature_sets batch)."""
    f = F.F12_ONE
    for p_g1, q_g2 in pairs:
        f = F.f12mul(f, miller_loop(p_g1, q_g2))
    return final_exponentiation(f)


def pairings_product_is_one(pairs):
    return multi_pairing(pairs) == F.F12_ONE
