"""Backend-pluggable BLS12-381 — the north-star seam.

Public API mirroring the reference crate crypto/bls (crypto/bls/src/lib.rs:
87-142): key/signature types, SignatureSet, and `verify_signature_sets`
dispatched to a selected backend (cpu | tpu | fake). Random batch scalars
are always host-generated CSPRNG (never device-side), per
crypto/bls/src/impls/blst.rs:16,48-68 (RAND_BITS=64, nonzero).
"""

import os
import secrets

from . import params
from .keys import (
    SecretKey,
    PublicKey,
    Signature,
    SignatureSet,
    aggregate_signatures,
    aggregate_pubkey_point,
)
from . import backends as _backends

_DEFAULT_BACKEND = os.environ.get("LIGHTHOUSE_TPU_BLS_BACKEND", "cpu")


def gen_batch_scalars(n: int):
    """n nonzero RAND_BITS-bit CSPRNG scalars (blst.rs:48-68 semantics)."""
    out = []
    for _ in range(n):
        r = 0
        while r == 0:
            r = secrets.randbits(params.RAND_BITS)
        out.append(r)
    return out


def verify_signature_sets(sets, *, backend: str = None, rand_scalars=None) -> bool:
    """Batch-verify independently-signed SignatureSets.

    The entry point every verifier in the framework funnels into — gossip
    attestation batches, whole-block signature batches, sync-committee
    batches (reference call sites: attestation_verification/batch.rs:195,
    block_signature_verifier.rs:380-397)."""
    b = _backends.get(backend or _DEFAULT_BACKEND)
    if rand_scalars is None:
        rand_scalars = gen_batch_scalars(len(sets))
    return b.verify_signature_sets(sets, rand_scalars)


def verify(signature, pubkey, message: bytes, *, backend: str = None) -> bool:
    """Single-signature verification."""
    b = _backends.get(backend or _DEFAULT_BACKEND)
    return b.verify_single(signature, pubkey, message)


__all__ = [
    "params",
    "SecretKey",
    "PublicKey",
    "Signature",
    "SignatureSet",
    "aggregate_signatures",
    "aggregate_pubkey_point",
    "gen_batch_scalars",
    "verify_signature_sets",
    "verify",
]
