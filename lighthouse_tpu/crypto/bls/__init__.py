"""Backend-pluggable BLS12-381 — the north-star seam.

Public API mirroring the reference crate crypto/bls (crypto/bls/src/lib.rs:
87-142): key/signature types, SignatureSet, and `verify_signature_sets`
dispatched to a selected backend (cpu | tpu | fake). Random batch scalars
are always host-generated CSPRNG (never device-side), per
crypto/bls/src/impls/blst.rs:16,48-68 (RAND_BITS=64, nonzero).
"""

import os
import secrets
import time

from ...common import metrics as _metrics
from ...common import tracing as _tracing
from . import params
from .keys import (
    SecretKey,
    PublicKey,
    Signature,
    SignatureSet,
    aggregate_signatures,
    aggregate_pubkey_point,
)
from . import backends as _backends

_DEFAULT_BACKEND = os.environ.get("LIGHTHOUSE_TPU_BLS_BACKEND", "cpu")

# Backend-agnostic observability at the ONE seam every verifier funnels
# into (gossip batches, block batches, sync batches). Labeled by backend
# and by the AOT lane bucket the batch pads into, so the /metrics scrape
# attributes verify latency and padding waste per compiled program.
# tools/metrics_lint.py pins these names.
M_SETS = _metrics.counter(
    "bls_verify_sets_total",
    "Signature sets submitted for verification, by backend",
    labelnames=("backend",),
)
M_BATCHES = _metrics.counter(
    "bls_verify_batches_total",
    "verify_signature_sets calls, by backend",
    labelnames=("backend",),
)
M_FAILED = _metrics.counter(
    "bls_verify_failed_batches_total",
    "verify_signature_sets calls that returned invalid (bad signature "
    "or policy-rejected input), by backend",
    labelnames=("backend",),
)
M_ERRORED = _metrics.counter(
    "bls_verify_errored_batches_total",
    "verify_signature_sets calls where the backend RAISED (device "
    "error, not an invalid signature), by backend",
    labelnames=("backend",),
)
M_BATCH_SECONDS = _metrics.histogram(
    "bls_verify_batch_seconds",
    "Whole-batch verify latency, by backend and AOT lane bucket",
    labelnames=("backend", "bucket"),
)
M_OCCUPANCY = _metrics.histogram(
    "bls_verify_batch_occupancy_ratio",
    "Real sets / padded bucket size per batch, by backend and AOT lane "
    "bucket (only the tpu backends actually pad — filter on backend)",
    buckets=(0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
    labelnames=("backend", "bucket"),
)
M_PADDING = _metrics.counter(
    "bls_verify_padding_slots_total",
    "Lane slots the batch's AOT bucket pads (only the tpu backends "
    "actually pad — filter on backend; cpu/fake report the slots the "
    "batch WOULD waste on the device path)",
    labelnames=("backend", "bucket"),
)


def gen_batch_scalars(n: int):
    """n nonzero RAND_BITS-bit CSPRNG scalars (blst.rs:48-68 semantics)."""
    out = []
    for _ in range(n):
        r = 0
        while r == 0:
            r = secrets.randbits(params.RAND_BITS)
        out.append(r)
    return out


def verify_signature_sets(sets, *, backend: str = None, rand_scalars=None) -> bool:
    """Batch-verify independently-signed SignatureSets.

    The entry point every verifier in the framework funnels into — gossip
    attestation batches, whole-block signature batches, sync-committee
    batches (reference call sites: attestation_verification/batch.rs:195,
    block_signature_verifier.rs:380-397)."""
    name = backend or _DEFAULT_BACKEND
    b = _backends.get(name)
    if rand_scalars is None:
        rand_scalars = gen_batch_scalars(len(sets))
    n = len(sets)
    bucket = str(params.lane_bucket(n)) if n else "0"
    t0 = time.perf_counter()
    ok = False
    raised = True
    try:
        with _tracing.span(
            "bls_verify", backend=name, bucket=bucket, sets=n
        ):
            ok = b.verify_signature_sets(sets, rand_scalars)
        raised = False
    finally:
        # record in finally: a backend that RAISES (chip drops mid-
        # batch) is exactly the event these series must attribute —
        # but as an ERROR, not as an invalid signature
        M_BATCH_SECONDS.labels(backend=name, bucket=bucket).observe(
            time.perf_counter() - t0
        )
        M_SETS.labels(backend=name).inc(n)
        M_BATCHES.labels(backend=name).inc()
        if n:
            npad = int(bucket)
            M_OCCUPANCY.labels(backend=name, bucket=bucket).observe(n / npad)
            M_PADDING.labels(backend=name, bucket=bucket).inc(npad - n)
        if raised:
            M_ERRORED.labels(backend=name).inc()
        elif not ok:
            M_FAILED.labels(backend=name).inc()
        if n and not raised and name == "tpu":
            # cumulative kernel work for the cost observatory: per-
            # batch elem-op/byte totals come from the checked-in
            # census (device_metrics), not from tracing anything here.
            # Only the DIRECT device backend counts at this seam; the
            # warm dispatcher answers cold buckets from the CPU
            # fallback, so it records its own device-path batches
            # (backends/warm.py) — counting it here would book kernel
            # flops the device never executed.
            from .backends import device_metrics as _dm

            _dm.record_kernel_dispatch(bucket)
    return ok


def verify(signature, pubkey, message: bytes, *, backend: str = None) -> bool:
    """Single-signature verification."""
    b = _backends.get(backend or _DEFAULT_BACKEND)
    return b.verify_single(signature, pubkey, message)


__all__ = [
    "params",
    "SecretKey",
    "PublicKey",
    "Signature",
    "SignatureSet",
    "aggregate_signatures",
    "aggregate_pubkey_point",
    "gen_batch_scalars",
    "verify_signature_sets",
    "verify",
]
