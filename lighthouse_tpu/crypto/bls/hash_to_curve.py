"""Hash-to-curve for BLS12-381 G2 (ciphersuite BLS12381G2_XMD:SHA-256_SSWU_RO_).

RFC 9380 construction: expand_message_xmd(SHA-256) → hash_to_field(Fp2, m=2)
→ simplified-SWU on the isogenous curve E2' → Vélu-derived 3-isogeny to E2
(see tools/derive_g2_isogeny.py) → fast cofactor clearing (Budroni–Pintore).

The reference delegates this to blst's hash-to-curve inside signing and
inside signature-set verification (crypto/bls/src/impls/blst.rs message
hashing with DST crypto/bls/src/impls/blst.rs:15).

Byte-exactness is anchored by the RFC 9380 appendix J.10.1 known-answer
vectors in tests/test_h2c_vectors.py (host oracle AND device ops/htc path);
the Vélu derivation's [-1] sign ambiguity is pinned there too
(tools/derive_g2_isogeny.py).
"""

import hashlib

from . import params
from .params import P
from . import fields as F
from . import curve as C
from . import _g2_isogeny_consts as ISO

# SSWU parameters for E2': y^2 = x^3 + A'x + B' (RFC 9380 §8.8.2).
A_PRIME = (0, 240)
B_PRIME = (1012, 1012)
Z = (-2 % P, -1 % P)  # Z = -(2 + u)

_SHA256_BLOCK = 64
_L = 64  # bytes per field element draw: ceil((381 + 128) / 8)


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 §5.3.1 expand_message_xmd with SHA-256."""
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + 31) // 32
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * _SHA256_BLOCK
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    bvals = [hashlib.sha256(b0 + b"\x01" + dst_prime).digest()]
    b0_int = int.from_bytes(b0, "big")
    for i in range(2, ell + 1):
        # strxor via int xor: C-speed, ~10x the per-byte genexpr on the
        # gossip packing hot path (hash draws per message)
        mixed = (b0_int ^ int.from_bytes(bvals[-1], "big")).to_bytes(32, "big")
        bvals.append(hashlib.sha256(mixed + bytes([i]) + dst_prime).digest())
    return b"".join(bvals)[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes = params.DST):
    """RFC 9380 §5.2: draw `count` Fp2 elements from msg."""
    out = expand_message_xmd(msg, dst, count * 2 * _L)
    els = []
    for i in range(count):
        c0 = int.from_bytes(out[(2 * i) * _L : (2 * i + 1) * _L], "big") % P
        c1 = int.from_bytes(out[(2 * i + 1) * _L : (2 * i + 2) * _L], "big") % P
        els.append((c0, c1))
    return els


def sgn0(a) -> int:
    """RFC 9380 §4.1 sgn0 for Fp2."""
    s0 = a[0] % 2
    z0 = a[0] == 0
    s1 = a[1] % 2
    return s0 | (int(z0) & s1)


def _is_square(a) -> bool:
    if a == F.F2_ZERO:
        return True
    return F.f2pow(a, (P * P - 1) // 2) == F.F2_ONE


def _g_prime(x):
    """g'(x) = x^3 + A'x + B' on E2'."""
    return F.f2add(F.f2add(F.f2mul(F.f2sqr(x), x), F.f2mul(A_PRIME, x)), B_PRIME)


def map_to_curve_sswu(u):
    """Simplified SWU (RFC 9380 §6.6.2) onto E2'(Fp2)."""
    u2 = F.f2sqr(u)
    zu2 = F.f2mul(Z, u2)
    tv1 = F.f2add(F.f2sqr(zu2), zu2)  # Z^2 u^4 + Z u^2
    if tv1 == F.F2_ZERO:
        x1 = F.f2mul(B_PRIME, F.f2inv(F.f2mul(Z, A_PRIME)))
    else:
        # x1 = (-B/A) * (1 + 1/tv1)
        x1 = F.f2mul(
            F.f2mul(F.f2neg(B_PRIME), F.f2inv(A_PRIME)),
            F.f2add(F.F2_ONE, F.f2inv(tv1)),
        )
    gx1 = _g_prime(x1)
    if _is_square(gx1):
        x, y = x1, F.f2sqrt(gx1)
    else:
        x2 = F.f2mul(zu2, x1)
        x, y = x2, F.f2sqrt(_g_prime(x2))
    if sgn0(u) != sgn0(y):
        y = F.f2neg(y)
    return (x, y)


def _eval_poly(coeffs, x):
    acc = F.F2_ZERO
    for c in reversed(coeffs):
        acc = F.f2add(F.f2mul(acc, x), c)
    return acc


def iso_map(pt):
    """The 3-isogeny E2' -> E2 (rational maps from _g2_isogeny_consts)."""
    if pt is None:
        return None
    x, y = pt
    xd = _eval_poly(ISO.XDEN, x)
    yd = _eval_poly(ISO.YDEN, x)
    if xd == F.F2_ZERO or yd == F.F2_ZERO:
        return None  # x is the kernel abscissa → image is the identity
    xx = F.f2mul(_eval_poly(ISO.XNUM, x), F.f2inv(xd))
    yy = F.f2mul(y, F.f2mul(_eval_poly(ISO.YNUM, x), F.f2inv(yd)))
    return (xx, yy)


import functools


@functools.lru_cache(maxsize=512)
def hash_to_g2(msg: bytes, dst: bytes = params.DST):
    """Full hash_to_curve: msg → point in G2 (r-torsion of E2).

    Memoized: many signers hash the SAME message (a slot's sync
    committee all sign the head root; a committee's attesters share
    attestation data) — the map runs once per distinct message."""
    u0, u1 = hash_to_field_fp2(bytes(msg), 2, bytes(dst))
    q0 = iso_map(map_to_curve_sswu(u0))
    q1 = iso_map(map_to_curve_sswu(u1))
    return C.g2_clear_cofactor(C.g2_add(q0, q1))
