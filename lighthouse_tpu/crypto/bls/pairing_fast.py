"""Optimized pairing on BLS12-381 — host prototype of the TPU pipeline.

Same math as pairing.py's generic oracle, restructured exactly the way
the batched JAX kernels (ops/pairing.py) compute it:

- Miller loop over Jacobian twisted coordinates with *polynomial* line
  coefficients (denominators cleared by Fp2 factors, which the final
  exponentiation kills: any a in a proper subfield satisfies
  a^((p^2-1)*k) = 1 and (p^2-1) | (p^12-1)/r).
- Sparse line elements: l = c0 + c1*v + c4*v*w in the Fp12 basis
  w^(2i+j) (v = w^2, w^6 = xi).
- Final exponentiation: easy part f^((p^6-1)(p^2+1)), then the
  Hayashida–Hayasaka–Teruya hard part to the exponent
  3(p^4-p^2+1)/r = (u-1)^2 (u+p) (u^2+p^2-1) + 3
  (cubing is a bijection on mu_r, so the verdict f^E == 1 is unchanged),
  with Granger–Scott cyclotomic squarings inside the u-exponentiations.

ops/pairing.py must match this module ELEMENTWISE pre-final-exp (same
scalings), which is what makes the JAX port debuggable step by step.

Reference parity: crypto/bls/src/impls/blst.rs:114-116 delegates this
exact computation (n-pair product + single final exp) to blst.
"""

from . import params
from .params import P, R, X
from . import fields as F
from . import pairing as PR

U = X  # signed curve parameter (negative for BLS12-381)

# ------------------------------------------------------------ basis helpers
# Fp12 as 6 Fp2 slots indexed by k = 2i + j for slot (j, i) (basis w^k).


def slots_from_f12(f):
    (a0, a1, a2), (b0, b1, b2) = f
    return [a0, b0, a1, b1, a2, b2]  # k = 0..5


def f12_from_slots(c):
    return ((c[0], c[2], c[4]), (c[1], c[3], c[5]))


def sparse_line(c0, c1, c4):
    """c0 + c1*v + c4*v*w as a full Fp12 element."""
    return ((c0, c1, F.F2_ZERO), (F.F2_ZERO, c4, F.F2_ZERO))


# ------------------------------------------------------------ miller loop

_ATE_BITS = bin(-X)[3:]  # MSB-first bits of |X| after the leading 1


def _dbl_step(T, xP, yP):
    """Jacobian doubling + line through T evaluated at P=(xP,yP) in G1.

    Line (scaled by 2*YT*ZT^3 in Fp2 — killed by final exp):
        c0 = 3 XT^3 - 2 YT^2
        c1 = -3 XT^2 ZT^2 * xP
        c4 =  2 YT ZT^3 * yP
    """
    XT, YT, ZT = T
    A = F.f2sqr(XT)
    Bv = F.f2sqr(YT)
    Cv = F.f2sqr(Bv)
    Zsq = F.f2sqr(ZT)
    D = F.f2sub(F.f2sqr(F.f2add(XT, Bv)), F.f2add(A, Cv))
    D = F.f2add(D, D)
    E = F.f2add(F.f2add(A, A), A)
    Fv = F.f2sqr(E)
    X3 = F.f2sub(Fv, F.f2add(D, D))
    Y3 = F.f2sub(F.f2mul(E, F.f2sub(D, X3)), F.f2smul(Cv, 8))
    Z3 = F.f2add(F.f2mul(YT, ZT), F.f2mul(YT, ZT))
    c0 = F.f2sub(F.f2smul(F.f2mul(XT, A), 3), F.f2add(Bv, Bv))
    c1 = F.f2smul(F.f2mul(A, Zsq), (-3 * xP) % P)
    c4 = F.f2smul(F.f2mul(Z3, Zsq), yP)  # Z3 = 2 YT ZT
    return (X3, Y3, Z3), sparse_line(c0, c1, c4)


def _add_step(T, Q, xP, yP):
    """Mixed addition T += Q (Q affine) + line through T,Q at P.

    With H = U2 - XT (U2 = xQ ZT^2), M = S2 - YT (S2 = yQ ZT^3), the
    line scaled by (-1) * H*ZT (subfield factors; sign killed too):
        c0 = H ZT yQ - M xQ
        c1 = M * xP
        c4 = -H ZT * yP
    """
    XT, YT, ZT = T
    xQ, yQ = Q
    Zsq = F.f2sqr(ZT)
    U2 = F.f2mul(xQ, Zsq)
    S2 = F.f2mul(F.f2mul(yQ, ZT), Zsq)
    H = F.f2sub(U2, XT)
    M = F.f2sub(S2, YT)
    HH = F.f2sqr(H)
    I = F.f2smul(HH, 4)
    J = F.f2mul(H, I)
    rr = F.f2add(M, M)
    V = F.f2mul(XT, I)
    X3 = F.f2sub(F.f2sqr(rr), F.f2add(J, F.f2add(V, V)))
    Y3 = F.f2sub(F.f2mul(rr, F.f2sub(V, X3)), F.f2add(F.f2mul(YT, J), F.f2mul(YT, J)))
    Z3 = F.f2sub(F.f2sqr(F.f2add(ZT, H)), F.f2add(Zsq, HH))
    HZ = F.f2mul(H, ZT)
    c0 = F.f2sub(F.f2mul(HZ, yQ), F.f2mul(M, xQ))
    c1 = F.f2smul(M, xP)
    c4 = F.f2smul(HZ, (-yP) % P)
    return (X3, Y3, Z3), sparse_line(c0, c1, c4)


def miller_loop_fast(p_g1, q_g2):
    """f_{|X|,Q}(P), conjugated at the end for X < 0. Returns Fp12 equal
    to the oracle's miller_loop UP TO subfield factors (same image under
    final exponentiation)."""
    if p_g1 is None or q_g2 is None:
        return F.F12_ONE
    xP, yP = p_g1
    T = (q_g2[0], q_g2[1], F.F2_ONE)
    f = F.F12_ONE
    for b in _ATE_BITS:
        T, line = _dbl_step(T, xP, yP)
        f = F.f12mul(F.f12sqr(f), line)
        if b == "1":
            T, line = _add_step(T, q_g2, xP, yP)
            f = F.f12mul(f, line)
    return F.f12conj(f)  # X < 0: f_{-n} ~ conj(f_n) under final exp


# ------------------------------------------------------------ cyclotomic

# Fp4 = Fp2[t]/(t^2 - xi): (a + b t)^2 = a^2 + xi b^2 + 2ab t.


def _fp4_sqr(a, b):
    a2 = F.f2sqr(a)
    b2 = F.f2sqr(b)
    ra = F.f2add(a2, F.f2mul_xi(b2))
    rb = F.f2sub(F.f2sqr(F.f2add(a, b)), F.f2add(a2, b2))  # 2ab
    return ra, rb


def cyclotomic_sqr(f):
    """Granger–Scott squaring for f in the cyclotomic subgroup.

    Slots k = 2i+j; Fp4 pairs (c0,c3), (c1,c4), (c2,c5):
        (t0a,t0b) = sqr(c0,c3); (t1a,t1b) = sqr(c1,c4); (t2a,t2b) = sqr(c2,c5)
        c0' = 3 t0a - 2 c0        c3' = 3 t0b + 2 c3
        c2' = 3 t1a - 2 c2        c5' = 3 t1b + 2 c5
        c4' = 3 t2a - 2 c4        c1' = 3 xi t2b + 2 c1
    (verified against f12sqr on cyclotomic elements in tests)."""
    c = slots_from_f12(f)
    t0a, t0b = _fp4_sqr(c[0], c[3])
    t1a, t1b = _fp4_sqr(c[1], c[4])
    t2a, t2b = _fp4_sqr(c[2], c[5])
    out = [None] * 6
    out[0] = F.f2sub(F.f2smul(t0a, 3), F.f2smul(c[0], 2))
    out[3] = F.f2add(F.f2smul(t0b, 3), F.f2smul(c[3], 2))
    out[2] = F.f2sub(F.f2smul(t1a, 3), F.f2smul(c[2], 2))
    out[5] = F.f2add(F.f2smul(t1b, 3), F.f2smul(c[5], 2))
    out[4] = F.f2sub(F.f2smul(t2a, 3), F.f2smul(c[4], 2))
    out[1] = F.f2add(F.f2smul(F.f2mul_xi(t2b), 3), F.f2smul(c[1], 2))
    return f12_from_slots(out)


def cyc_pow_abs_u(f):
    """f^|u| with cyclotomic squarings (f must be in the cyclotomic
    subgroup)."""
    bits = bin(-U)[3:]
    out = f
    for b in bits:
        out = cyclotomic_sqr(out)
        if b == "1":
            out = F.f12mul(out, f)
    return out


def cyc_pow_u(f):
    """f^u (u negative: conjugate = inverse in the cyclotomic subgroup)."""
    return F.f12conj(cyc_pow_abs_u(f))


# ------------------------------------------------------------ final exp


def frob(f, n=1):
    """f^(p^n) via the slot gamma constants."""
    out = f
    for _ in range(n):
        out = _frob1(out)
    return out


# gamma constants (same derivation as ops/tower.py)
_G1CONSTS = [F.f2pow(params.XI, k * ((P - 1) // 6)) for k in range(6)]


def _frob1(f):
    c = slots_from_f12(f)
    out = [F.f2mul(F.f2conj(c[k]), _G1CONSTS[k]) for k in range(6)]
    return f12_from_slots(out)


def final_exp_fast(f):
    """Easy part then HHT hard part (exponent 3(p^4-p^2+1)/r)."""
    # easy: f^((p^6-1)(p^2+1))
    t = F.f12mul(F.f12conj(f), F.f12inv(f))       # f^(p^6-1)
    m = F.f12mul(frob(t, 2), t)                   # ^(p^2+1); now cyclotomic
    # hard: m^((u-1)^2 (u+p) (u^2+p^2-1)) * m^3
    a = F.f12mul(cyc_pow_u(m), F.f12conj(m))      # m^(u-1)
    a = F.f12mul(cyc_pow_u(a), F.f12conj(a))      # m^((u-1)^2)
    b = F.f12mul(cyc_pow_u(a), _frob1(a))         # a^(u+p)
    c2 = F.f12mul(cyc_pow_u(cyc_pow_u(b)), F.f12mul(frob(b, 2), F.f12conj(b)))
    #    b^(u^2) * b^(p^2) * b^(-1) = b^(u^2+p^2-1)
    m3 = F.f12mul(F.f12mul(m, m), m)
    return F.f12mul(c2, m3)


def pairings_product_is_one_fast(pairs) -> bool:
    f = F.F12_ONE
    for p_g1, q_g2 in pairs:
        f = F.f12mul(f, miller_loop_fast(p_g1, q_g2))
    return final_exp_fast(f) == F.F12_ONE
