"""BLS key/signature wrapper types.

Mirrors the reference's generic wrappers (GenericPublicKey, GenericSignature,
GenericAggregateSignature, GenericSignatureSet over backend traits,
crypto/bls/src/lib.rs:87-142) as plain Python classes holding affine points
plus their compressed wire encodings. The heavy math lives in the backends.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional, Sequence

from . import params, curve as C, hash_to_curve as H2C


import hashlib as _hashlib

# pubkey memoization keyed on a DIGEST of the secret, never the raw
# scalar: the cache must not retain secret key material beyond the
# SecretKey object's life. Values are immutable affine points.
_PUBKEY_CACHE: dict = {}
_PUBKEY_CACHE_MAX = 4096


def _pubkey_point(scalar: int):
    h = _hashlib.sha256(
        b"lh-pk-cache" + scalar.to_bytes(32, "big")
    ).digest()
    pt = _PUBKEY_CACHE.get(h)
    if pt is None:
        pt = C.g1_mul(C.G1_GEN, scalar)
        if len(_PUBKEY_CACHE) < _PUBKEY_CACHE_MAX:
            _PUBKEY_CACHE[h] = pt
    return pt


class SecretKey:
    __slots__ = ("scalar",)

    def __init__(self, scalar: int):
        if not 0 < scalar < params.R:
            raise ValueError("secret key scalar out of range")
        self.scalar = scalar

    @classmethod
    def from_seed(cls, seed: bytes) -> "SecretKey":
        """Deterministic dev keygen (NOT EIP-2333 HD derivation; see
        crypto/eth2_key_derivation for the reference's production scheme —
        implemented in lighthouse_tpu.crypto.keystore)."""
        h = hashlib.sha256(b"lighthouse-tpu-keygen" + seed).digest()
        return cls(int.from_bytes(h + hashlib.sha256(h).digest(), "big") % (params.R - 1) + 1)

    def public_key(self) -> "PublicKey":
        pt = _pubkey_point(self.scalar)
        return PublicKey(point=pt)

    def sign(self, message: bytes) -> "Signature":
        return Signature(point=C.g2_mul(H2C.hash_to_g2(message), self.scalar))


class PublicKey:
    """A G1 public key. `point` is the decompressed, subgroup-checked affine
    point (the role of the reference's decompressed ValidatorPubkeyCache,
    beacon_node/beacon_chain/src/validator_pubkey_cache.rs:1-20)."""

    __slots__ = ("point", "_compressed")

    def __init__(self, point=None, compressed: Optional[bytes] = None):
        if point is None and compressed is None:
            raise ValueError("need point or compressed bytes")
        self.point = point if point is not None or compressed is None else None
        self._compressed = compressed
        if self.point is None and compressed is not None:
            self.point = C.g1_decompress(compressed)
        if self.point is None:
            raise ValueError("infinity public key rejected")

    @classmethod
    def from_bytes(cls, data: bytes) -> "PublicKey":
        return cls(compressed=data)

    def to_bytes(self) -> bytes:
        if self._compressed is None:
            self._compressed = C.g1_compress(self.point)
        return self._compressed

    def __eq__(self, other):
        return isinstance(other, PublicKey) and self.point == other.point

    def __hash__(self):
        return hash(self.to_bytes())


class Signature:
    """A G2 signature (possibly an aggregate). Decompression performs the
    subgroup check, like blst's sig_validate (crypto/bls/src/impls/blst.rs
    subgroup-checks the signature before batch aggregation)."""

    __slots__ = ("point", "_compressed")

    def __init__(self, point=None, compressed: Optional[bytes] = None):
        self.point = point
        self._compressed = compressed
        if self.point is None and compressed is not None:
            self.point = C.g2_decompress(compressed)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Signature":
        return cls(compressed=data)

    def to_bytes(self) -> bytes:
        if self._compressed is None:
            self._compressed = C.g2_compress(self.point)
        return self._compressed

    def is_infinity(self) -> bool:
        return self.point is None

    def __eq__(self, other):
        return isinstance(other, Signature) and self.point == other.point


def aggregate_signatures(sigs: Sequence[Signature]) -> Signature:
    acc = None
    for s in sigs:
        acc = C.g2_add(acc, s.point)
    return Signature(point=acc)


def aggregate_pubkey_point(keys: Sequence[PublicKey]):
    acc = None
    for k in keys:
        acc = C.g1_add(acc, k.point)
    return acc


@dataclass
class SignatureSet:
    """One independently-verifiable (signature, pubkeys, message) triple —
    the reference's GenericSignatureSet
    (crypto/bls/src/generic_signature_set.rs:61-107)."""

    signature: Signature
    signing_keys: Sequence[PublicKey]
    message: bytes

    @classmethod
    def single_pubkey(cls, signature: Signature, key: PublicKey, message: bytes):
        return cls(signature=signature, signing_keys=[key], message=message)

    @classmethod
    def multiple_pubkeys(cls, signature: Signature, keys: Sequence[PublicKey], message: bytes):
        return cls(signature=signature, signing_keys=list(keys), message=message)
