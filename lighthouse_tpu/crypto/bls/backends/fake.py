"""Fake BLS backend: every verification succeeds.

Mirrors the reference's fake_crypto backend
(crypto/bls/src/impls/fake_crypto.rs:31-35), used to test consensus logic
at speed without paying for crypto.
"""


def verify_signature_sets(sets, rand_scalars) -> bool:
    return True


def verify_single(signature, pubkey, message: bytes) -> bool:
    return True
