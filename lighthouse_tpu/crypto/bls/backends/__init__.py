"""BLS execution backends.

Three backends, like the reference's feature-selected impls
(crypto/bls/src/lib.rs:130-142: blst | fake_crypto, plus the seam this
project exists to fill — a TPU backend):

  cpu  — pure-Python oracle (control / correctness baseline)
  tpu  — JAX/XLA batched kernels (lighthouse_tpu.ops), the hot path
  fake — always-valid stub for fast consensus-logic tests
         (crypto/bls/src/impls/fake_crypto.rs:31-35)
"""

from . import cpu, fake

_BACKENDS = {"cpu": cpu, "fake": fake}


def get(name: str):
    if name == "tpu":
        from . import tpu  # deferred: importing jax is slow

        _BACKENDS["tpu"] = tpu
    try:
        return _BACKENDS[name]
    except KeyError:
        raise ValueError(f"unknown BLS backend {name!r}") from None
