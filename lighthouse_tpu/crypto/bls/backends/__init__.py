"""BLS execution backends.

Three backends, like the reference's feature-selected impls
(crypto/bls/src/lib.rs:130-142: blst | fake_crypto, plus the seam this
project exists to fill — a TPU backend):

  cpu      — pure-Python oracle (control / correctness baseline)
  tpu      — JAX/XLA batched kernels (lighthouse_tpu.ops), the hot path
  tpu-warm — tpu with CPU-fallback-while-compiling: cold batch buckets
             answer from the CPU backend while a background thread
             compiles the device program (the node default posture for
             first-seen bucket sizes; backends/warm.py)
  fake     — always-valid stub for fast consensus-logic tests
             (crypto/bls/src/impls/fake_crypto.rs:31-35)
"""

from . import cpu, fake

_BACKENDS = {"cpu": cpu, "fake": fake}


def get(name: str):
    if name == "tpu":
        from . import tpu  # deferred: importing jax is slow

        _BACKENDS["tpu"] = tpu
    elif name in ("tpu-warm", "tpu_warm"):
        from . import warm

        _BACKENDS[name] = warm
    try:
        return _BACKENDS[name]
    except KeyError:
        raise ValueError(f"unknown BLS backend {name!r}") from None
