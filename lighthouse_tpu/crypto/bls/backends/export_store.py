"""AOT export-artifact store: inventory, seeding and replay
(ISSUE 10 tentpole, layer 2 — the tunnel-proof half).

The verify kernel's AOT artifacts (`.graft_export/verify_{backend}_
{bucket}_{srchash}.bin`, written by jax.export) were chip-only until
this round: tools/export_verify.py ran on the tunneled TPU, and when
the tunnel died, three straight bench rounds recorded 0.0. This module
makes the artifact ladder a first-class, any-backend facility:

- `artifact_inventory()` — what is on disk, per bucket: age, size and
  whether the embedded source hash matches the CURRENT kernel sources
  (a mismatched artifact will not load — tpu.export_artifact_path
  embeds the fingerprint in the name precisely so a stale module can
  never serve a new kernel). bench records this in
  detail.backend_init and mirrors it into bls_export_artifact_info.
- `export_bucket(n)` — serialize the lowered module for the CURRENT
  backend (cpu on a tunnel-dead box: that is the point). Abstract
  shapes only: exporting needs no signature sets and no device math.
- `replay_callable(bucket)` — deserialize the artifact and return its
  call (or None); first invocation pays the backend compile, recorded
  as jax_compile_seconds{program="verify_replay_<bucket>"}.

tools/seed_cache.py drives the same functions for the on-chip seeding
path; tests/test_tpu_export_replay.py holds replay bit-identical to
the jit path.
"""

from __future__ import annotations

import glob
import os
import re
import time


def _tb():
    from . import tpu as TB

    return TB


def export_dir() -> str:
    return os.path.dirname(os.path.abspath(_tb().export_artifact_path(128)))


_NAME_RE = re.compile(
    r"^verify_(?P<backend>[a-zA-Z0-9_]+)_(?P<bucket>\d+)_"
    r"(?P<srchash>[0-9a-f]{16})\.bin$"
)


def artifact_inventory() -> list:
    """Every verify artifact on disk (any backend), with bucket, age,
    size, backend and source-hash match against the current sources.
    Mesh artifacts (__graft_entry__.dryrun_multichip) key on the
    fingerprint EXTENDED with parallel/verify.py — comparing them
    against the plain kernel hash would report every mesh artifact as
    stale forever."""
    TB = _tb()
    current = TB.source_fingerprint()
    try:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))))))
        current_mesh = TB.source_fingerprint(
            extra_paths=[
                os.path.join(repo, "lighthouse_tpu", "parallel",
                             "verify.py")
            ]
        )
    except OSError:
        current_mesh = current
    out = []
    now = time.time()
    for path in sorted(glob.glob(os.path.join(export_dir(), "verify_*.bin"))):
        m = _NAME_RE.match(os.path.basename(path))
        if not m:
            continue
        try:
            st = os.stat(path)
        except OSError:
            continue
        out.append(
            {
                "bucket": int(m.group("bucket")),
                "backend": m.group("backend"),
                "source_hash": m.group("srchash"),
                "source_hash_match": m.group("srchash") == (
                    current_mesh if m.group("backend") == "mesh"
                    else current
                ),
                "age_s": round(now - st.st_mtime, 1),
                "size_bytes": st.st_size,
                "path": path,
            }
        )
    return out


def _abstract_args(npad: int):
    import jax
    import jax.numpy as jnp

    from ....ops.lane import fp

    W = fp.W
    i32 = jnp.int32
    return (
        jax.ShapeDtypeStruct((W, npad), i32),
        jax.ShapeDtypeStruct((W, npad), i32),
        jax.ShapeDtypeStruct((2, W, npad), i32),
        jax.ShapeDtypeStruct((2, W, npad), i32),
        jax.ShapeDtypeStruct((2, W, npad), i32),
        jax.ShapeDtypeStruct((2, W, npad), i32),
        jax.ShapeDtypeStruct((64, npad), i32),
        jax.ShapeDtypeStruct((npad,), jnp.bool_),
    )


def export_bucket(npad: int) -> str:
    """Trace+lower the verify kernel for one bucket on the current
    backend and persist the serialized module. Minutes of tracing —
    callers budget for it (bench gates on remaining budget)."""
    from jax import export as jexport

    from . import device_metrics

    TB = _tb()
    path = TB.export_artifact_path(npad)
    t0 = time.perf_counter()
    exported = jexport.export(TB._verify_kernel)(*_abstract_args(npad))
    blob = exported.serialize()
    device_metrics.observe_compile(
        f"export_verify_{npad}", time.perf_counter() - t0
    )
    TB.write_artifact(path, blob)
    return path


def ensure_exports(buckets, min_budget_s: float = 0.0,
                   budget_left=None) -> list:
    """Make sure a loadable artifact exists for each bucket on the
    current backend; export the missing/stale ones while the budget
    allows. Returns per-bucket action records."""
    TB = _tb()
    actions = []
    for b in buckets:
        path = TB.export_artifact_path(b)
        if os.path.exists(path):
            actions.append({"bucket": b, "action": "fresh"})
            continue
        if budget_left is not None and budget_left() < min_budget_s:
            actions.append(
                {"bucket": b, "action": "skipped_budget",
                 "left_s": round(budget_left(), 1)}
            )
            continue
        t0 = time.perf_counter()
        try:
            export_bucket(b)
            actions.append(
                {"bucket": b, "action": "exported",
                 "seconds": round(time.perf_counter() - t0, 1)}
            )
        except Exception as e:  # noqa: BLE001 — recorded, never fatal
            actions.append(
                {"bucket": b, "action": "error",
                 "error": f"{type(e).__name__}: {e}"}
            )
    return actions


def replay_callable(npad: int):
    """The deserialized exported module's call for this bucket on the
    current backend, or None if no loadable artifact exists. Unlike
    tpu._exported_for this does NOT consult LH_TPU_USE_EXPORT — replay
    is an explicit request, not a dispatch policy."""
    from jax import export as jexport

    TB = _tb()
    path = TB.export_artifact_path(npad)
    if not os.path.exists(path):
        return None
    with open(path, "rb") as f:
        return jexport.deserialize(f.read()).call


# --------------------------------------------------------- replay env
#
# Replay always happens in a SUBPROCESS with this exact environment:
# - JAX_PLATFORMS=cpu: a dead-tunnel box has a poisoned PJRT client in
#   the bench process (jax.devices() hung mid-init); a fresh process
#   pinned to cpu cannot deadlock on it.
# - the LLVM flag cuts the module's first backend compile on the
#   one-core image; it changes CPU cache keys ONLY inside the replay
#   subprocess, so the chip-side .jax_cache keys (which must survive
#   for the next tunnel window) are untouched.
# The env is pinned HERE so bench.py, tests and manual seeding all hit
# the same .jax_cache entry — a flag-string drift would silently turn
# every replay into a fresh tens-of-minutes compile.

REPLAY_XLA_FLAGS = "--xla_llvm_disable_expensive_passes=true"


def replay_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # EXACTLY the pinned flags — inherited XLA_FLAGS are dropped, not
    # merged: the test tier injects --xla_force_host_platform_device_
    # count=8 (conftest), and any flag drift changes the compile-cache
    # key, silently turning the warm replay back into a tens-of-
    # minutes compile (observed: >900 s vs 434 s warm). Same for
    # LIBTPU_INIT_ARGS (bench exports it for the chip path; observed
    # to fork a second 50 MB cache entry for the identical program).
    env["XLA_FLAGS"] = REPLAY_XLA_FLAGS
    env.pop("LIBTPU_INIT_ARGS", None)
    env.setdefault("LH_TPU_USE_EXPORT", "1")
    return env


# --------------------------------------------------------- warm stamps
#
# The replay module's FIRST backend compile is tens of minutes on the
# one-core image (cached in .jax_cache afterwards). A stamp next to
# the artifact records that this box has paid it, so tier-1 tests can
# run the differential when it is seconds and skip (loudly, with the
# seeding command) when it would be an hour. bench.py stamps after
# every successful replay.

def _warm_stamp_path(npad: int) -> str:
    return _tb().export_artifact_path(npad) + ".warm"


def mark_replay_warm(npad: int, first_call_s: float) -> None:
    try:
        with open(_warm_stamp_path(npad), "w") as f:
            f.write(f"first_call_s={first_call_s:.1f}\n")
    except OSError:
        pass


def replay_is_warm(npad: int) -> bool:
    """True when this box has already compiled the replay module for
    the CURRENT sources (the stamp lives next to the fingerprint-named
    artifact, so a kernel edit un-warms it automatically)."""
    return os.path.exists(_warm_stamp_path(npad))


# --------------------------------------------------------- replay CLI
#
#   python -m lighthouse_tpu.crypto.bls.backends.export_store \
#       replay-bench [bucket] [reps]
#
# Exports the bucket's module if missing, replays it with correctness
# checks (valid full bucket -> True, forged set -> False, padded
# 4-set batch -> True), times steady-state reps, stamps the box warm,
# and prints ONE JSON line. bench.py and the tier-1 differential test
# both drive THIS entry point under replay_env().

def _replay_sets(n: int, forge_index=None):
    """Deterministic signature sets (shared with the differential
    test, which recomputes oracle verdicts over the same sets)."""
    from ..keys import SecretKey, SignatureSet

    out = []
    for i in range(n):
        sk = SecretKey.from_seed(bytes([i % 250 + 1, 13]) * 2)
        msg = b"replay-%d" % (i % 5)
        sig = sk.sign(msg)
        if i == forge_index:
            msg = b"replay-forged"
        out.append(SignatureSet.single_pubkey(sig, sk.public_key(), msg))
    return out


def replay_bench(bucket: int = 128, reps: int = 3) -> dict:
    import numpy as np

    import lighthouse_tpu

    lighthouse_tpu.enable_compilation_cache()
    import jax

    from ... import bls
    from . import device_metrics

    TB = _tb()
    out = {"bucket": bucket, "backend": jax.default_backend()}
    if replay_callable(bucket) is None:
        t0 = time.perf_counter()
        export_bucket(bucket)
        out["export_s"] = round(time.perf_counter() - t0, 1)
    fn = replay_callable(bucket)
    if fn is None:
        out["error"] = "export produced no loadable artifact"
        return out

    def verdict(sets, scalars):
        args = TB.prepare_batch(sets, scalars)
        return bool(np.asarray(jax.block_until_ready(fn(*args))))

    scalars = bls.gen_batch_scalars(bucket)
    sets = _replay_sets(bucket)
    t0 = time.perf_counter()
    ok_valid = verdict(sets, scalars)
    first_s = time.perf_counter() - t0
    out["first_call_s"] = round(first_s, 2)
    device_metrics.observe_compile(f"verify_replay_{bucket}", first_s)
    ok_forged = verdict(_replay_sets(bucket, forge_index=1), scalars)
    pad_scalars = bls.gen_batch_scalars(4)
    ok_padded = verdict(_replay_sets(4), pad_scalars)
    out["checks"] = {
        "valid_full": ok_valid,
        "forged_rejected": not ok_forged,
        "valid_padded": ok_padded,
    }
    out["checked"] = bool(ok_valid and not ok_forged and ok_padded)
    if not out["checked"]:
        out["error"] = f"correctness check failed: {out['checks']}"
        return out
    args = TB.prepare_batch(sets, scalars)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    out["times_s"] = [round(t, 3) for t in times]
    out["sets_per_s"] = round(bucket / min(times), 2)
    mark_replay_warm(bucket, first_s)
    return out


if __name__ == "__main__":
    import json
    import sys

    cmd = sys.argv[1] if len(sys.argv) > 1 else "replay-bench"
    if cmd == "inventory":
        print(json.dumps(artifact_inventory(), indent=1))
    elif cmd == "replay-bench":
        bucket = int(sys.argv[2]) if len(sys.argv) > 2 else 128
        reps = int(sys.argv[3]) if len(sys.argv) > 3 else 3
        result = replay_bench(bucket, reps)
        print(json.dumps(result, sort_keys=True))
        sys.exit(0 if result.get("checked") else 1)
    else:
        print(f"unknown command {cmd!r}", file=sys.stderr)
        sys.exit(2)
