"""CPU (pure-Python) BLS backend — the control implementation.

Implements the same batch verification scheme as the reference's blst
backend (crypto/bls/src/impls/blst.rs:37-119): per set draw a nonzero
64-bit random scalar r_i, subgroup-check the signature, aggregate the set's
pubkeys; then check

    prod_i e([r_i] apk_i, H(m_i)) * e(-g1, sum_i [r_i] sig_i) == 1

with one shared final exponentiation (blst.rs:114-116 semantics;
"fast verification of multiple BLS signatures", random linear combination).
"""

from .. import params, curve as C, pairing_fast as PR, hash_to_curve as H2C


def verify_signature_sets(sets, rand_scalars) -> bool:
    """Batch-verify. Returns False on empty input or any set with no keys
    (blst.rs:42,80-89 rejection semantics)."""
    if not sets:
        return False
    if len(rand_scalars) != len(sets):
        raise ValueError("need one random scalar per set")
    pairs = []
    sig_acc = None
    for s, r in zip(sets, rand_scalars):
        if not s.signing_keys:
            return False
        if not (0 < r < 2**params.RAND_BITS):
            raise ValueError("batch scalar out of range")
        if s.signature.point is None:
            return False  # infinity signature
        apk = None
        for k in s.signing_keys:
            apk = C.g1_add(apk, k.point)
        if apk is None:
            return False
        pairs.append((C.g1_mul(apk, r), H2C.hash_to_g2(s.message)))
        sig_acc = C.g2_add(sig_acc, C.g2_mul(s.signature.point, r))
    pairs.append((C.g1_neg(C.G1_GEN), sig_acc))
    return PR.pairings_product_is_one_fast(pairs)


def verify_single(signature, pubkey, message: bytes) -> bool:
    """Plain (non-batch) verification: e(pk, H(m)) == e(g1, sig)."""
    if signature.point is None:
        return False
    pairs = [
        (pubkey.point, H2C.hash_to_g2(message)),
        (C.g1_neg(C.G1_GEN), signature.point),
    ]
    return PR.pairings_product_is_one_fast(pairs)
