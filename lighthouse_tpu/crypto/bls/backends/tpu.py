"""TPU BLS backend — the reason this framework exists.

Same semantics as the CPU control (backends/cpu.py, mirroring
crypto/bls/src/impls/blst.rs:37-119): random-linear-combination batch
verification,

    prod_i e([r_i] apk_i, H(m_i)) * e(-g1, sum_i [r_i] sig_i) == 1,

but with every expensive step — hash-to-curve maps, G2 subgroup checks
of the signatures, the 64-bit scalar ladders, the point-sum tree, n+1
Miller loops, one final exponentiation — fused into ONE jitted XLA
program over the whole batch. Batch sizes are padded to power-of-two
buckets so recompilation is rare; padding slots use r = 0 and are masked
out of the pairing product.

Division of labor:
  host   — input policy checks (empty sets, infinity points), per-set
           pubkey aggregation (the decompressed-pubkey-cache role,
           validator_pubkey_cache.rs:138), SHA-256 message expansion,
           CSPRNG scalars, packing.
  device — all field/curve/pairing arithmetic, batched.
"""

import numpy as np
import jax
import jax.numpy as jnp

from .. import params
from lighthouse_tpu.ops import fp, tower, jacobian as J, pairing as OP, htc

W = fp.W

_G1_GEN_NEG_X = fp.to_limbs(params.G1X)
_G1_GEN_NEG_Y = fp.to_limbs((-params.G1Y) % params.P)
_G2_GEN_X = tower.f2_pack(params.G2X)
_G2_GEN_Y = tower.f2_pack(params.G2Y)


def _to_affine_g1(p):
    X, Y, Z = p
    zi = fp.inv(Z)
    zi2 = fp.sqr(zi)
    return fp.mul(X, zi2), fp.mul(fp.mul(Y, zi2), zi)


def _to_affine_g2(p):
    X, Y, Z = p
    zi = tower.f2inv(Z)
    zi2 = tower.f2sqr(zi)
    return tower.f2mul(X, zi2), tower.f2mul(tower.f2mul(Y, zi2), zi)


def local_phase(apk_x, apk_y, sig_x, sig_y, t0, t1, rbits, pad):
    """The per-shard portion of batch verification: everything except
    the global signature aggregate. Returns
      (f_local, r_sig, sub_ok_all):
      f_local [2,3,2,W]  — product of this shard's n Miller values
      r_sig              — this shard's SUM of [r_i]sig_i (Jacobian G2)
      sub_ok_all []      — AND of this shard's subgroup checks.
    Used unsharded by `_verify_kernel` and per-device by
    lighthouse_tpu.parallel.verify under shard_map (SURVEY.md §5.7: the
    batch axis is this project's sequence-parallel analog)."""
    n = apk_x.shape[0]
    one1 = tower.bcast(jnp.asarray(fp.ONE), (n,))
    one2 = tower.bcast(jnp.asarray(np.stack([fp.ONE, fp.ZERO])), (n,))

    # hash-to-curve for all messages
    hm = htc.hash_draws_to_g2(t0, t1)                    # [n] Jacobian G2

    # Two scalar multiplications of the SAME base (subgroup check's
    # [|u|]S and the random-combination [r]S) share one doubling chain:
    # a single scan with two conditional-add accumulators — half the
    # ladder cost and one compiled body instead of two.
    sig_jac = (sig_x, sig_y, one2)
    mbits = htc._m_bits(n)
    m_sig, r_sig = J.scalar_mul2(J.FP2, sig_jac, mbits, rbits)

    # signature subgroup checks: psi(S) == [u]S = -[|u|]S
    sub_ok = J.jac_eq(J.FP2, J.psi(sig_jac), J.neg(J.FP2, m_sig)) | pad

    s_local = J.sum_tree(J.FP2, r_sig, n)                # shard's sum
    r_apk = J.scalar_mul(J.FP1, (apk_x, apk_y, one1), rbits)

    # to affine for the Miller loop
    px, py = _to_affine_g1(r_apk)
    qx, qy = _to_affine_g2(hm)
    q_inf = J.FP2.is_zero_struct(hm[2]) | pad

    fs = OP.miller_loop(px, py, qx, qy, p_inf=pad, q_inf=q_inf)
    f_local = OP.f12_product_tree(fs, n)
    return f_local, s_local, jnp.all(sub_ok)


def finish_phase(f_prod, s_agg, sub_ok_all):
    """Global finish: the (-g1, S) pair, final exponentiation, verdict."""
    sx, sy = _to_affine_g2(tuple(c[None] for c in s_agg))
    s_inf = J.FP2.is_zero_struct(s_agg[2])[None]
    xP = tower.bcast(jnp.asarray(_G1_GEN_NEG_X), (1,))
    yP = tower.bcast(jnp.asarray(_G1_GEN_NEG_Y), (1,))
    f_last = OP.miller_loop(xP, yP, sx, sy, q_inf=s_inf)[0]
    prod = tower.f12mul(f_prod, f_last)
    ok = tower.f12_eq_one(OP.final_exp(prod))
    return ok & sub_ok_all


@jax.jit
def _verify_kernel(apk_x, apk_y, sig_x, sig_y, t0, t1, rbits, pad):
    """One fused single-device batch verification."""
    f_local, s_local, sub_ok = local_phase(
        apk_x, apk_y, sig_x, sig_y, t0, t1, rbits, pad
    )
    return finish_phase(f_local, s_local, sub_ok)


def _bucket(n: int) -> int:
    return 1 << max(3, (n - 1).bit_length())


def prepare_batch(sets, rand_scalars):
    """Host packing: sets -> kernel inputs, or None if policy-rejected
    (empty input / empty keys / infinity points — blst.rs:42,80-89)."""
    n = len(sets)
    if n == 0:
        return None
    apk_pts, sig_pts, msgs = [], [], []
    for s, r in zip(sets, rand_scalars):
        if not s.signing_keys:
            return None
        if not (0 < r < 2**params.RAND_BITS):
            raise ValueError("batch scalar out of range")
        if s.signature.point is None:
            return None
        apk = None
        from .. import curve as C

        for k in s.signing_keys:
            apk = C.g1_add(apk, k.point)
        if apk is None:
            return None
        apk_pts.append(apk)
        sig_pts.append(s.signature.point)
        msgs.append(s.message)

    npad = _bucket(n)
    apk_x = np.stack(
        [fp.to_limbs(p[0]) for p in apk_pts]
        + [_G1_GEN_NEG_X] * (npad - n)
    )
    apk_y = np.stack(
        [fp.to_limbs(p[1]) for p in apk_pts]
        + [fp.to_limbs(params.G1Y)] * (npad - n)
    )
    sig_x = np.stack(
        [tower.f2_pack(p[0]) for p in sig_pts] + [_G2_GEN_X] * (npad - n)
    )
    sig_y = np.stack(
        [tower.f2_pack(p[1]) for p in sig_pts] + [_G2_GEN_Y] * (npad - n)
    )
    t0, t1 = htc.pack_draws(msgs + [b""] * (npad - n))
    rbits = np.zeros((npad, 64), dtype=np.int32)
    rbits[:n] = J.scalars_to_bits(rand_scalars, 64)
    pad = np.zeros(npad, dtype=bool)
    pad[n:] = True
    return (
        jnp.asarray(apk_x),
        jnp.asarray(apk_y),
        jnp.asarray(sig_x),
        jnp.asarray(sig_y),
        t0,
        t1,
        jnp.asarray(rbits),
        jnp.asarray(pad),
    )


def verify_signature_sets(sets, rand_scalars) -> bool:
    args = prepare_batch(sets, rand_scalars)
    if args is None:
        return False
    return bool(np.asarray(_verify_kernel(*args)))


def verify_single(signature, pubkey, message: bytes) -> bool:
    from ..keys import SignatureSet

    if signature.point is None:
        return False
    s = SignatureSet.single_pubkey(signature, pubkey, message)
    return verify_signature_sets([s], [1])
