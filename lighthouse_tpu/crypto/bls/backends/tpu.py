"""TPU BLS backend — the reason this framework exists.

Same semantics as the CPU control (backends/cpu.py, mirroring
crypto/bls/src/impls/blst.rs:37-119): random-linear-combination batch
verification,

    prod_i e([r_i] apk_i, H(m_i)) * e(-g1, sum_i [r_i] sig_i) == 1,

with every expensive step — hash-to-curve maps, G2 subgroup checks
of the signatures, the 64-bit scalar ladders, the point-sum tree, n+1
Miller loops, one final exponentiation — fused into ONE jitted XLA
program over the whole batch.

Round 3 rebuilt the compute core on ops/lane (lane-major layout +
Pallas-fused kernels; see ops/lane/__init__.py for the measured
rationale) and cut the operation count:

- subgroup-check ladder [|u|]S shares the doubling chain with the
  random-combination ladder [r]S, and its adds are static-unrolled
  (scalar_mul_with_static);
- the Miller loop is unrolled over the static ate bits with sparse
  line products (ops/lane/pairing.py);
- batch sizes are padded to power-of-two buckets >= 128 lanes so the
  128-wide TPU lane axis is full and recompilation is rare; padding
  slots use r = 0 and are masked out of the pairing product.

Division of labor:
  host   — input policy checks (empty sets, infinity points), per-set
           pubkey aggregation (the decompressed-pubkey-cache role,
           validator_pubkey_cache.rs:138), SHA-256 message expansion,
           CSPRNG scalars, packing.
  device — all field/curve/pairing arithmetic, batched.
"""

import time

import numpy as np
import jax
import jax.numpy as jnp

from .device_metrics import (
    M_DEVICE_SECONDS,
    M_EXPORT_CACHE,
    M_HOST_PACK_SECONDS,
)
from .. import params
from lighthouse_tpu.ops.lane import (
    fp,
    tower,
    jacobian as J,
    pairing as OP,
    htc,
    chains,
)

W = fp.W

# Backend observability (families registered in device_metrics.py;
# tools/metrics_lint.py pins the names): where the batch's wall time
# goes — host packing vs device compute — and whether the AOT export
# ladder is actually being hit (a miss means this process pays a
# multi-minute jax trace+lower for the bucket).

_G1_GEN_NEG_X = fp.to_limbs(params.G1X)
_G1_GEN_NEG_Y = fp.to_limbs((-params.G1Y) % params.P)
_M_ABS = -params.X


def _to_affine_g1(p):
    X, Y, Z = p
    zi = chains.inv(Z)
    zi2 = fp.sqr(zi)
    return fp.mul(X, zi2), fp.mul(fp.mul(Y, zi2), zi)


def _to_affine_g2(p):
    X, Y, Z = p
    zi = chains.f2inv(Z)
    zi2 = tower.f2sqr(zi)
    return tower.f2mul(X, zi2), tower.f2mul(tower.f2mul(Y, zi2), zi)


def local_phase(apk_x, apk_y, sig_x, sig_y, t0, t1, rbits, pad):
    """The per-shard portion of batch verification: everything except
    the global signature aggregate. All arrays lane-major (batch on the
    trailing axis): apk_* [W, S]; sig_*, t0, t1 [2, W, S]; rbits
    [64, S]; pad [S] bool. Returns
      (f_local, r_sig, sub_ok_all):
      f_local [2,3,2,W,1] — product of this shard's Miller values
      r_sig             — this shard's SUM of [r_i]sig_i (Jacobian G2)
      sub_ok_all []     — AND of this shard's subgroup checks.
    Used unsharded by `_verify_kernel` and per-device by
    lighthouse_tpu.parallel.verify under shard_map (SURVEY.md §5.7)."""
    S = apk_x.shape[-1]
    one1 = tower.bcast(jnp.asarray(fp.ONE)[:, None], S)
    one2 = tower.bcast(jnp.asarray(np.stack([fp.ONE, fp.ZERO]))[..., None], S)

    # hash-to-curve for all messages
    hm = htc.hash_draws_to_g2(t0, t1)                    # [2, W, S] Jacobian

    # [r]S via the windowed ladder (64 dbl + 32 table adds); the
    # subgroup check's [|u|]S runs its own static chain (63 dbl + 5
    # executed adds). Split beats the round-3 shared chain (64 dbl +
    # 64 computed adds) by ~480 Fp muls per set (ops/lane/chains doc).
    sig_jac = (sig_x, sig_y, one2)
    r_sig = chains.scalar_mul_w2(J.FP2, sig_jac, rbits)
    m_sig = J.scalar_mul_static(J.FP2, sig_jac, _M_ABS)

    # signature subgroup checks: psi(S) == [u]S = -[|u|]S
    sub_ok = J.jac_eq(J.FP2, J.psi(sig_jac), J.neg(J.FP2, m_sig)) | pad

    s_local = J.lane_sum(J.FP2, r_sig, S)                # shard's sum
    # G1 RLC ladder: MSB 2-bit windows, 32 fewer adds (ops/lane/chains)
    r_apk = chains.scalar_mul_w2(J.FP1, (apk_x, apk_y, one1), rbits)

    # to affine for the Miller loop
    px, py = _to_affine_g1(r_apk)
    qx, qy = _to_affine_g2(hm)
    q_inf = J.FP2.is_zero_struct(hm[2]) | pad

    fs = OP.miller_loop(px, py, qx, qy, p_inf=pad, q_inf=q_inf)
    f_local = OP.lane_product(fs, S)
    return f_local, s_local, jnp.all(sub_ok)


def finish_phase(f_prod, s_agg, sub_ok_all):
    """Global finish: the (-g1, S) pair, final exponentiation, verdict."""
    sx, sy = _to_affine_g2(s_agg)
    s_inf = J.FP2.is_zero_struct(s_agg[2])
    xP = jnp.asarray(_G1_GEN_NEG_X)[:, None]
    yP = jnp.asarray(_G1_GEN_NEG_Y)[:, None]
    f_last = OP.miller_loop(xP, yP, sx, sy, q_inf=s_inf)
    prod = tower.f12mul(f_prod, f_last)
    ok = tower.f12_eq_one(OP.final_exp(prod))
    return jnp.all(ok) & sub_ok_all


@jax.jit
def _verify_kernel(apk_x, apk_y, sig_x, sig_y, t0, t1, rbits, pad):
    """One fused single-device batch verification."""
    f_local, s_local, sub_ok = local_phase(
        apk_x, apk_y, sig_x, sig_y, t0, t1, rbits, pad
    )
    return finish_phase(f_local, s_local, sub_ok)


# ------------------------------------------------ AOT bucket ladder
# VERDICT r3 weak #5: a fresh process pays minutes of jax trace+lower
# per batch bucket even on a warm XLA cache. tools/export_verify.py
# serializes the lowered module per (backend, bucket, source hash);
# when LH_TPU_EXPORT_DIR holds a fresh artifact the dispatcher calls
# the deserialized module instead of tracing _verify_kernel.

_EXPORTED: dict = {}


def _export_enabled() -> bool:
    """The ONE LH_TPU_USE_EXPORT gate (dispatch + probe must agree, or
    the export-cache series misclassifies disabled as miss)."""
    import os

    return os.environ.get("LH_TPU_USE_EXPORT", "0") not in ("", "0")


def source_fingerprint(extra_paths=()) -> str:
    """Hash of the kernel-defining sources (any edit invalidates):
    ops/lane/*.py + this file + bls params (whose constants — pad
    points, RAND_BITS, generators — are baked into the traced program).
    Callers whose program traces through more files (the mesh program's
    parallel/verify.py) pass them via extra_paths."""
    import glob
    import hashlib
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    lane = os.path.join(here, "..", "..", "..", "ops", "lane")
    params = os.path.join(here, "..", "params.py")
    h = hashlib.sha256()
    srcs = sorted(glob.glob(os.path.join(lane, "*.py"))) + [__file__, params]
    for p in list(srcs) + sorted(extra_paths):
        with open(p, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def write_artifact(path: str, blob: bytes) -> None:
    """Atomic artifact write (tmp + rename) shared by the export tools."""
    import os

    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)


def export_artifact_path(npad: int) -> str:
    import os

    d = os.environ.get("LH_TPU_EXPORT_DIR") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "..", "..", "..", "..", ".graft_export",
    )
    return os.path.join(
        os.path.abspath(d),
        f"verify_{jax.default_backend()}_{npad}_{source_fingerprint()}.bin",
    )


def _exported_for(npad: int):
    """Cached deserialized module for the bucket, or None.

    Opt-in via LH_TPU_USE_EXPORT: the exported module's FIRST backend
    compile in a process can cost as much as the trace it saves, so
    only long-lived consumers that amortize it (bench, the node) should
    take this path — the test tier must keep tracing."""
    import os

    if not _export_enabled():
        return None
    if npad in _EXPORTED:
        return _EXPORTED[npad]
    exp = None
    try:
        path = export_artifact_path(npad)
        if os.path.exists(path):
            from jax import export as jexport

            with open(path, "rb") as f:
                exp = jexport.deserialize(f.read()).call
    except Exception:
        exp = None
    _EXPORTED[npad] = exp
    return exp


def _bucket(n: int) -> int:
    """Power-of-two lane buckets, minimum 128 (a full TPU lane tile).
    One shared definition (params.lane_bucket) so metrics labels and
    export artifacts agree on the ladder."""
    return params.lane_bucket(n)


def _pack_draws_fast(messages):
    """htc.pack_draws with the vectorized limb packer: SHA-256 message
    expansion on host (irreducible), fastpack for the Fp2 limb arrays."""
    import jax.numpy as jnp

    from ....crypto.bls import hash_to_curve as H2C_host
    from ....ops.lane import fastpack

    t0s, t1s = [], []
    cache = {}  # bucket padding repeats b"" npad-n times; expand once
    for m in messages:
        hit = cache.get(m)
        if hit is None:
            hit = cache[m] = H2C_host.hash_to_field_fp2(m, 2)
        u0, u1 = hit
        t0s.append(u0)
        t1s.append(u1)
    return (
        jnp.asarray(fastpack.f2_pack_many(t0s)),
        jnp.asarray(fastpack.f2_pack_many(t1s)),
    )


def prepare_batch(sets, rand_scalars):
    """Host packing: sets -> kernel inputs, or None if policy-rejected
    (empty input / empty keys / infinity points — blst.rs:42,80-89)."""
    n = len(sets)
    if n == 0:
        return None
    apk_pts, sig_pts, msgs = [], [], []
    from .. import curve as C

    for s, r in zip(sets, rand_scalars):
        if not s.signing_keys:
            return None
        if not (0 < r < 2**params.RAND_BITS):
            raise ValueError("batch scalar out of range")
        if s.signature.point is None:
            return None
        apk = None
        for k in s.signing_keys:
            apk = C.g1_add(apk, k.point)
        if apk is None:
            return None
        apk_pts.append(apk)
        sig_pts.append(s.signature.point)
        msgs.append(s.message)

    npad = _bucket(n)
    # vectorized host packing (ops/lane/fastpack): at 10k+ sets/s device
    # throughput the per-int python limb conversion was the sustained
    # pipeline bottleneck (BASELINE.md round-4 notes)
    from ....ops.lane import fastpack

    apk_x = fastpack.pack_ints(
        [p[0] for p in apk_pts] + [params.G1X] * (npad - n)
    )
    apk_y = fastpack.pack_ints(
        [p[1] for p in apk_pts] + [params.G1Y] * (npad - n)
    )
    sig_x = fastpack.f2_pack_many(
        [p[0] for p in sig_pts] + [params.G2X] * (npad - n)
    )
    sig_y = fastpack.f2_pack_many(
        [p[1] for p in sig_pts] + [params.G2Y] * (npad - n)
    )
    t0, t1 = _pack_draws_fast(msgs + [b""] * (npad - n))
    rbits = np.zeros((64, npad), dtype=np.int32)
    rbits[:, :n] = J.scalars_to_bits(rand_scalars, 64)
    pad = np.zeros(npad, dtype=bool)
    pad[n:] = True
    return (
        jnp.asarray(apk_x),
        jnp.asarray(apk_y),
        jnp.asarray(sig_x),
        jnp.asarray(sig_y),
        t0,
        t1,
        jnp.asarray(rbits),
        jnp.asarray(pad),
    )


def verify_callable(npad: int):
    """The verify entry point for a padded bucket: the AOT-exported
    module when a fresh artifact exists, else the jitted kernel.

    The export-cache series counts HERE — the dispatch decision — not
    in _exported_for, whose callers also probe speculatively (warm.py
    _is_warm): hit = exported module used, miss = the jit path (a cold
    bucket pays trace+lower), disabled = the ladder is off by config."""
    if not _export_enabled():
        M_EXPORT_CACHE.labels(result="disabled").inc()
        return _verify_kernel
    exp = _exported_for(npad)
    if exp is not None:
        M_EXPORT_CACHE.labels(result="hit").inc()
        return exp
    M_EXPORT_CACHE.labels(result="miss").inc()
    return _verify_kernel


def verify_signature_sets(sets, rand_scalars) -> bool:
    t0 = time.perf_counter()
    args = prepare_batch(sets, rand_scalars)
    if args is None:
        return False
    npad = args[0].shape[-1]
    bucket = str(npad)
    M_HOST_PACK_SECONDS.labels(bucket=bucket).observe(
        time.perf_counter() - t0
    )
    fn = verify_callable(npad)
    t1 = time.perf_counter()
    # np.asarray blocks on the device result, so this timing covers
    # dispatch + compute + transfer (the whole device-side share)
    ok = bool(np.asarray(fn(*args)))
    M_DEVICE_SECONDS.labels(bucket=bucket).observe(time.perf_counter() - t1)
    return ok


def verify_single(signature, pubkey, message: bytes) -> bool:
    from ..keys import SignatureSet

    if signature.point is None:
        return False
    s = SignatureSet.single_pubkey(signature, pubkey, message)
    return verify_signature_sets([s], [1])
