"""Shared metric families for the device-path backends (tpu, warm).

One registration site, jax-free, so warm.py can reference the same
series without importing the jax-heavy tpu module and without a
copy-pasted registration that could silently drift (the registry
validates type/labels/buckets on re-registration, but not help text).

Cost-observatory series (ISSUE 10): the per-bucket flops/bytes
counters are populated from the checked-in census budgets
(tests/budgets/kernel_costs.json, written by ops/costs.py /
tools/kernel_report.py) — the same numbers the tier-1 op-count gate
pins — so the scrape carries cumulative kernel work without paying a
census at verify time. jax_compile_seconds attributes every observed
trace/lower/compile event (warm.py background warms, export replays,
the epoch program build) to a named program.
"""

from ....common import metrics as _metrics

M_EXPORT_CACHE = _metrics.counter(
    "bls_tpu_export_cache_total",
    "AOT exported-module dispatches by result (hit = exported module, "
    "miss = jit path despite the ladder being on, disabled = ladder off)",
    labelnames=("result",),
)
M_HOST_PACK_SECONDS = _metrics.histogram(
    "bls_tpu_host_pack_seconds",
    "prepare_batch host packing time, by AOT lane bucket",
    labelnames=("bucket",),
)
M_DEVICE_SECONDS = _metrics.histogram(
    "bls_tpu_device_seconds",
    "Device verify-call time (dispatch + compute + sync), by bucket",
    labelnames=("bucket",),
)

# compile events are seconds-to-minutes: the default request-latency
# bucket layout would collapse everything into +Inf
_COMPILE_BUCKETS = (
    0.1, 0.5, 1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1200.0,
    1800.0,
)
M_COMPILE_SECONDS = _metrics.histogram(
    "jax_compile_seconds",
    "Observed jax trace/lower/compile wall time, by program (verify "
    "bucket warms, export replays, the fused epoch program)",
    buckets=_COMPILE_BUCKETS,
    labelnames=("program",),
)
M_KERNEL_FLOPS = _metrics.counter(
    "bls_kernel_flops_total",
    "Cumulative elementwise kernel ops dispatched to the device path, "
    "by AOT bucket (per-batch totals from the checked-in op-count "
    "census, tests/budgets/kernel_costs.json)",
    labelnames=("bucket",),
)
M_KERNEL_BYTES = _metrics.counter(
    "bls_kernel_bytes_total",
    "Cumulative kernel-boundary HBM bytes dispatched to the device "
    "path, by AOT bucket (census model, kernel_op I/O only)",
    labelnames=("bucket",),
)
M_EXPORT_ARTIFACT = _metrics.gauge(
    "bls_export_artifact_info",
    "AOT export artifact age in seconds, by bucket and source-hash "
    "state (source=match: loadable by this build; source=stale_hash: "
    "present but the kernel sources changed; absent buckets have no "
    "artifact)",
    labelnames=("bucket", "source"),
)


def observe_compile(program: str, seconds: float) -> None:
    """Record one observed compile/trace event for a named program."""
    M_COMPILE_SECONDS.labels(program=str(program)).observe(float(seconds))


_CENSUS_BY_BUCKET: dict = {}
_CENSUS_TRIED = False


def _census_for(bucket: str):
    """Per-bucket {elem_ops, hbm_bytes} from the checked-in budgets
    file, loaded once; None when the file or bucket is absent. Path
    resolution + parsing live in ops/costs.py (the budgets' owner);
    costs' module level is jax-free, so the lazy import keeps this
    module importable everywhere the metrics registry is."""
    global _CENSUS_TRIED
    if not _CENSUS_TRIED:
        _CENSUS_TRIED = True
        try:
            from ....ops import costs

            doc = costs.load_budgets()
            for b, entry in doc.get("buckets", {}).items():
                _CENSUS_BY_BUCKET[str(b)] = entry
        except Exception:
            pass
    return _CENSUS_BY_BUCKET.get(str(bucket))


def record_kernel_dispatch(bucket) -> None:
    """Count one device-path verify dispatch against the census
    counters (no-op for buckets without a checked-in census)."""
    entry = _census_for(str(bucket))
    if not entry:
        return
    elem_ops = entry.get("elem_ops")
    hbm = entry.get("hbm_bytes")
    if elem_ops:
        M_KERNEL_FLOPS.labels(bucket=str(bucket)).inc(float(elem_ops))
    if hbm:
        M_KERNEL_BYTES.labels(bucket=str(bucket)).inc(float(hbm))


def record_artifact_inventory(inventory) -> None:
    """Mirror an export-artifact inventory (backends/export_store.py)
    into the bls_export_artifact_info gauge. The registry cannot drop
    children, so every previously-seen series is zeroed first — a
    re-exported bucket's old stale_hash series (or a deleted
    artifact's) must not keep reporting its last age forever."""
    for labelvalues in M_EXPORT_ARTIFACT.label_values():
        M_EXPORT_ARTIFACT.labels(*labelvalues).set(0.0)
    for item in inventory:
        src = "match" if item.get("source_hash_match") else "stale_hash"
        M_EXPORT_ARTIFACT.labels(
            bucket=str(item.get("bucket")), source=src
        ).set(float(item.get("age_s", 0.0)))
