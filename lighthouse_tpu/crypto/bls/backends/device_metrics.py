"""Shared metric families for the device-path backends (tpu, warm).

One registration site, jax-free, so warm.py can reference the same
series without importing the jax-heavy tpu module and without a
copy-pasted registration that could silently drift (the registry
validates type/labels/buckets on re-registration, but not help text).
"""

from ....common import metrics as _metrics

M_EXPORT_CACHE = _metrics.counter(
    "bls_tpu_export_cache_total",
    "AOT exported-module dispatches by result (hit = exported module, "
    "miss = jit path despite the ladder being on, disabled = ladder off)",
    labelnames=("result",),
)
M_HOST_PACK_SECONDS = _metrics.histogram(
    "bls_tpu_host_pack_seconds",
    "prepare_batch host packing time, by AOT lane bucket",
    labelnames=("bucket",),
)
M_DEVICE_SECONDS = _metrics.histogram(
    "bls_tpu_device_seconds",
    "Device verify-call time (dispatch + compute + sync), by bucket",
    labelnames=("bucket",),
)
