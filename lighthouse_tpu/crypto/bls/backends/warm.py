"""Compile-cliff management (VERDICT r4 weak #7): CPU fallback while a
TPU batch bucket compiles in the background.

The first call for a batch bucket pays jax trace+lower (+ a backend
compile on a cold cache) — minutes during which a naive node would
stall verification entirely. This dispatch keeps the node LIVE:

  - a WARM bucket (one completed device call this process, or a fresh
    AOT export artifact on disk) runs on the device;
  - a COLD bucket verifies THIS batch on the CPU backend immediately,
    while one background thread warms the device program for that
    bucket (compiles persist to .jax_cache, so the warmup also
    benefits future processes); the next batch of that size takes the
    device path.

Reference anchor: the reference never faces this (blst has no compile
step); this is the TPU-native operational cost the node runtime must
absorb, like state-advance timers absorb epoch-processing cost.
"""

from __future__ import annotations

import threading

from . import cpu as _cpu

# shared device-path families (one registration site, jax-free) —
# referenced directly, not through the device object, so a test fake in
# `_device_override` never has to carry metric attributes
from .device_metrics import M_DEVICE_SECONDS, M_HOST_PACK_SECONDS

_lock = threading.Lock()
_warm: set = set()
_inflight: dict = {}
_device_override = None


def _device():
    """The device backend (lazy: importing jax is slow); tests may set
    `_device_override` to a slow fake."""
    if _device_override is not None:
        return _device_override
    from . import tpu as _tpu

    return _tpu


def _is_warm(npad: int) -> bool:
    if npad in _warm:
        return True
    # a fresh AOT export loads in seconds — near-warm, take the device
    try:
        if _device()._exported_for(npad) is not None:
            _warm.add(npad)
            return True
    except Exception:
        pass
    return False


def _warmup(npad: int, args) -> None:
    import time as _time

    t0 = _time.perf_counter()
    try:
        _device()._verify_kernel(*args)
        with _lock:
            _warm.add(npad)
        # the background compile IS the compile-cliff cost this
        # dispatcher absorbs — record it per program (ISSUE 10)
        from .device_metrics import observe_compile

        observe_compile(f"verify_warmup_{npad}", _time.perf_counter() - t0)
    except Exception:
        pass  # chip gone mid-compile: stay on CPU, retry next batch
    finally:
        with _lock:
            _inflight.pop(npad, None)


def verify_signature_sets(sets, rand_scalars) -> bool:
    import time as _time

    dev = _device()
    t0 = _time.perf_counter()
    args = dev.prepare_batch(sets, rand_scalars)
    if args is None:
        return False
    npad = args[0].shape[-1]
    # same host-pack/device split series as the direct tpu backend —
    # warm is the node-default posture, its batches must not be blind
    M_HOST_PACK_SECONDS.labels(bucket=str(npad)).observe(
        _time.perf_counter() - t0
    )
    with _lock:
        warm = _is_warm(npad)
        if not warm and npad not in _inflight:
            t = threading.Thread(
                target=_warmup, args=(npad, args), daemon=True
            )
            _inflight[npad] = t
            t.start()
    if warm:
        t1 = _time.perf_counter()
        result = dev.verify_callable(npad)(*args)
        import numpy as np

        ok = bool(np.asarray(result))
        M_DEVICE_SECONDS.labels(bucket=str(npad)).observe(
            _time.perf_counter() - t1
        )
        # census flops/bytes count ONLY batches the device program
        # actually ran — the cold-bucket CPU fallback below does no
        # kernel work (ISSUE 10; the direct tpu backend is counted at
        # the crypto/bls dispatch seam instead)
        from .device_metrics import record_kernel_dispatch

        record_kernel_dispatch(npad)
        with _lock:
            _warm.add(npad)
        return ok
    # cold bucket: answer from the CPU backend NOW; the device program
    # is compiling behind us
    return _cpu.verify_signature_sets(sets, rand_scalars)


def verify_single(signature, pubkey, message: bytes) -> bool:
    from ..keys import SignatureSet

    if signature.point is None:
        return False
    s = SignatureSet.single_pubkey(signature, pubkey, message)
    return verify_signature_sets([s], [1])
