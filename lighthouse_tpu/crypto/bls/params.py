"""BLS12-381 curve parameters.

All constants below are standard public parameters of the BLS12-381 curve
(the curve used by Ethereum consensus; the reference binds them via the blst
library, crypto/bls/src/impls/blst.rs). They were self-verified in-tree by
algebraic identity:

    r == x^4 - x^2 + 1
    p == ((x - 1)^2 * r) // 3 + x
    G1 on  y^2 = x^3 + 4         over Fp
    G2 on  y^2 = x^3 + 4(1 + u)  over Fp2 = Fp[u]/(u^2 + 1)
    #E(Fp) == h1 * r == p + 1 - t,  t = x + 1

(see tests/test_bls_ref.py::test_params_identities).
"""

# BLS parameter (the "x" of the BLS12 family). Negative.
X = -0xD201000000010000

# Base field prime (381 bits).
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

# Subgroup order (255 bits) — the scalar field.
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# Curve coefficients: E1: y^2 = x^3 + B ; E2 (M-twist): y^2 = x^3 + B*(1+u)
B = 4

# Cofactors.
H1 = (X - 1) ** 2 // 3  # = 0x396C8C005555E1568C00AAAB0000AAAB
H2 = (X**8 - 4 * X**7 + 5 * X**6 - 4 * X**4 + 6 * X**3 - 4 * X**2 - 4 * X + 13) // 9

# Effective cofactor for G2 cofactor clearing per RFC 9380 §8.8.2 style
# (h_eff = h2 * (3 * z^2 - 3) ... implementations commonly use the
# Budroni–Pintore psi-based fast clearing instead; see hash_to_curve.py).

# G1 generator (standard).
G1X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

# G2 generator (standard). Fp2 elements are (c0, c1) meaning c0 + c1*u.
G2X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

# Domain separation tag for Ethereum consensus BLS signatures
# (proof-of-possession scheme; reference: crypto/bls/src/impls/blst.rs:15).
DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# Number of random bits per batch-verification scalar
# (reference: crypto/bls/src/impls/blst.rs:16 RAND_BITS = 64).
RAND_BITS = 64

# Fp2 non-residue used to build the tower: Fp2 = Fp[u]/(u^2 + 1),
# Fp6 = Fp2[v]/(v^3 - XI), Fp12 = Fp6[w]/(w^2 - v), XI = 1 + u.
XI = (1, 1)


def lane_bucket(n: int) -> int:
    """Power-of-two lane buckets, minimum 128 (a full TPU lane tile).

    The ONE definition of the AOT bucket ladder every layer shares: the
    TPU backend pads batches to it, tools/export_verify.py serializes
    per-bucket programs, and the metrics layer labels occupancy/latency
    series with it. Lives here (pure int math, no jax import) so the
    dispatch layer can bucket-label without touching a backend."""
    return 1 << max(7, (n - 1).bit_length())
