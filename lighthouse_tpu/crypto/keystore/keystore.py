"""EIP-2335 BLS keystores (crypto/eth2_keystore/src/keystore.rs analog).

JSON envelope holding an AES-128-CTR-encrypted secret key, a scrypt or
pbkdf2 password KDF, and a sha256 checksum binding cipher message to
decryption key. Passwords are NFKD-normalized with C0/C1 control
characters stripped, per the EIP.
"""

from __future__ import annotations

import hashlib
import json
import os
import unicodedata
import uuid as uuid_mod

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

from ..bls.keys import SecretKey


class KeystoreError(Exception):
    pass


def normalize_password(password: str) -> bytes:
    norm = unicodedata.normalize("NFKD", password)
    stripped = "".join(
        c
        for c in norm
        if not (0x00 <= ord(c) <= 0x1F or 0x7F <= ord(c) <= 0x9F)
    )
    return stripped.encode("utf-8")


def _kdf(password: bytes, params: dict) -> bytes:
    fn = params["function"]
    p = params["params"]
    salt = bytes.fromhex(p["salt"])
    if fn == "scrypt":
        return hashlib.scrypt(
            password,
            salt=salt,
            n=p["n"],
            r=p["r"],
            p=p["p"],
            dklen=p["dklen"],
            maxmem=128 * p["n"] * p["r"] * 2,
        )
    if fn == "pbkdf2":
        if p.get("prf", "hmac-sha256") != "hmac-sha256":
            raise KeystoreError("unsupported prf")
        return hashlib.pbkdf2_hmac("sha256", password, salt, p["c"], p["dklen"])
    raise KeystoreError(f"unsupported kdf {fn}")


def _aes128ctr(key16: bytes, iv: bytes, data: bytes) -> bytes:
    cipher = Cipher(algorithms.AES(key16), modes.CTR(iv))
    enc = cipher.encryptor()
    return enc.update(data) + enc.finalize()


class Keystore:
    """One encrypted validator key, JSON round-trippable."""

    def __init__(self, obj: dict):
        self.obj = obj

    # ------------------------------------------------------------ create

    @classmethod
    def encrypt(
        cls,
        secret_key: SecretKey,
        password: str,
        path: str = "",
        kdf: str = "scrypt",
        description: str = "",
        scrypt_n: int = 262144,
    ) -> "Keystore":
        secret = secret_key.scalar.to_bytes(32, "big")
        pw = normalize_password(password)
        salt = os.urandom(32)
        iv = os.urandom(16)
        if kdf == "scrypt":
            kdf_module = {
                "function": "scrypt",
                "params": {
                    "dklen": 32,
                    "n": scrypt_n,
                    "r": 8,
                    "p": 1,
                    "salt": salt.hex(),
                },
                "message": "",
            }
        elif kdf == "pbkdf2":
            kdf_module = {
                "function": "pbkdf2",
                "params": {
                    "dklen": 32,
                    "c": 262144,
                    "prf": "hmac-sha256",
                    "salt": salt.hex(),
                },
                "message": "",
            }
        else:
            raise KeystoreError(f"unsupported kdf {kdf}")
        dk = _kdf(pw, kdf_module)
        cipher_text = _aes128ctr(dk[:16], iv, secret)
        checksum = hashlib.sha256(dk[16:32] + cipher_text).hexdigest()
        obj = {
            "crypto": {
                "kdf": kdf_module,
                "checksum": {
                    "function": "sha256",
                    "params": {},
                    "message": checksum,
                },
                "cipher": {
                    "function": "aes-128-ctr",
                    "params": {"iv": iv.hex()},
                    "message": cipher_text.hex(),
                },
            },
            "description": description,
            "pubkey": secret_key.public_key().to_bytes().hex(),
            "path": path,
            "uuid": str(uuid_mod.uuid4()),
            "version": 4,
        }
        return cls(obj)

    # ------------------------------------------------------------ open

    def decrypt(self, password: str) -> SecretKey:
        crypto = self.obj["crypto"]
        if crypto["cipher"]["function"] != "aes-128-ctr":
            raise KeystoreError("unsupported cipher")
        if crypto["checksum"]["function"] != "sha256":
            raise KeystoreError("unsupported checksum")
        pw = normalize_password(password)
        dk = _kdf(pw, crypto["kdf"])
        cipher_text = bytes.fromhex(crypto["cipher"]["message"])
        checksum = hashlib.sha256(dk[16:32] + cipher_text).hexdigest()
        if checksum != crypto["checksum"]["message"]:
            raise KeystoreError("invalid password (checksum mismatch)")
        iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
        secret = _aes128ctr(dk[:16], iv, cipher_text)
        sk = SecretKey(int.from_bytes(secret, "big"))
        if self.obj.get("pubkey"):
            if sk.public_key().to_bytes().hex() != self.obj["pubkey"]:
                raise KeystoreError("decrypted key does not match pubkey")
        return sk

    # ------------------------------------------------------------ io

    @property
    def pubkey(self) -> bytes:
        return bytes.fromhex(self.obj["pubkey"])

    @property
    def uuid(self) -> str:
        return self.obj["uuid"]

    @property
    def path(self) -> str:
        return self.obj.get("path", "")

    def to_json(self) -> str:
        return json.dumps(self.obj)

    @classmethod
    def from_json(cls, raw: str) -> "Keystore":
        obj = json.loads(raw)
        if obj.get("version") != 4:
            raise KeystoreError("only EIP-2335 version 4 supported")
        return cls(obj)
