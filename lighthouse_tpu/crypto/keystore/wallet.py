"""EIP-2386 hierarchical-deterministic wallets
(crypto/eth2_wallet analog).

A wallet = an encrypted seed (reusing the EIP-2335 crypto envelope) plus
a `nextaccount` counter; each account derives a validator signing key at
the EIP-2334 path m/12381/3600/<i>/0/0 and wraps it in its own
password-protected keystore.
"""

from __future__ import annotations

import json
import os
import uuid as uuid_mod

from ..bls.keys import SecretKey
from . import key_derivation as kd
from .keystore import Keystore, KeystoreError, _aes128ctr, _kdf, normalize_password
import hashlib


class Wallet:
    def __init__(self, obj: dict):
        self.obj = obj

    @classmethod
    def create(
        cls, seed: bytes, password: str, name: str = "wallet", scrypt_n: int = 262144
    ) -> "Wallet":
        if len(seed) < 32:
            raise KeystoreError("seed must be at least 32 bytes")
        pw = normalize_password(password)
        salt = os.urandom(32)
        iv = os.urandom(16)
        kdf_module = {
            "function": "scrypt",
            "params": {"dklen": 32, "n": scrypt_n, "r": 8, "p": 1, "salt": salt.hex()},
            "message": "",
        }
        dk = _kdf(pw, kdf_module)
        cipher_text = _aes128ctr(dk[:16], iv, seed)
        checksum = hashlib.sha256(dk[16:32] + cipher_text).hexdigest()
        obj = {
            "crypto": {
                "kdf": kdf_module,
                "checksum": {"function": "sha256", "params": {}, "message": checksum},
                "cipher": {
                    "function": "aes-128-ctr",
                    "params": {"iv": iv.hex()},
                    "message": cipher_text.hex(),
                },
            },
            "name": name,
            "nextaccount": 0,
            "type": "hierarchical deterministic",
            "uuid": str(uuid_mod.uuid4()),
            "version": 1,
        }
        return cls(obj)

    def decrypt_seed(self, password: str) -> bytes:
        crypto = self.obj["crypto"]
        pw = normalize_password(password)
        dk = _kdf(pw, crypto["kdf"])
        cipher_text = bytes.fromhex(crypto["cipher"]["message"])
        checksum = hashlib.sha256(dk[16:32] + cipher_text).hexdigest()
        if checksum != crypto["checksum"]["message"]:
            raise KeystoreError("invalid wallet password")
        iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
        return _aes128ctr(dk[:16], iv, cipher_text)

    def next_validator(
        self,
        wallet_password: str,
        keystore_password: str,
        scrypt_n: int = 262144,
    ) -> Keystore:
        """Derive the next account's signing keystore and advance the
        counter (eth2_wallet next_account)."""
        seed = self.decrypt_seed(wallet_password)
        index = self.obj["nextaccount"]
        path = kd.validator_signing_path(index)
        sk = SecretKey(kd.derive_path(seed, path))
        store = Keystore.encrypt(
            sk, keystore_password, path=path, scrypt_n=scrypt_n
        )
        self.obj["nextaccount"] = index + 1
        return store

    @property
    def name(self) -> str:
        return self.obj["name"]

    @property
    def nextaccount(self) -> int:
        return self.obj["nextaccount"]

    def to_json(self) -> str:
        return json.dumps(self.obj)

    @classmethod
    def from_json(cls, raw: str) -> "Wallet":
        return cls(json.loads(raw))
