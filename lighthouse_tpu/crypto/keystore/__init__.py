"""Key management: EIP-2333 HD derivation, EIP-2335 keystores,
EIP-2386 wallets (crypto/eth2_key_derivation, eth2_keystore,
eth2_wallet analogs).
"""

from .key_derivation import (
    derive_master_sk,
    derive_child_sk,
    derive_path,
    validator_signing_path,
    validator_withdrawal_path,
)
from .keystore import Keystore, KeystoreError
from .wallet import Wallet

__all__ = [
    "derive_master_sk",
    "derive_child_sk",
    "derive_path",
    "validator_signing_path",
    "validator_withdrawal_path",
    "Keystore",
    "KeystoreError",
    "Wallet",
]
