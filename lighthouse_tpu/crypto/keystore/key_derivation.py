"""EIP-2333 BLS hierarchical key derivation + EIP-2334 paths
(crypto/eth2_key_derivation/src/derived_key.rs analog).

The tree: a master secret from a seed, children derived via Lamport
hashes of the parent key — deterministic, no stored chain state.
Anchored by the EIP-2333 published test case in tests/test_keystore.py.
"""

from __future__ import annotations

import hashlib
import hmac

from ..bls.params import R

_SALT0 = b"BLS-SIG-KEYGEN-SALT-"
_L = 48  # ceil((3 * ceil(log2(r))) / 16)


def _hkdf_extract(salt: bytes, ikm: bytes) -> bytes:
    return hmac.new(salt, ikm, hashlib.sha256).digest()


def _hkdf_expand(prk: bytes, info: bytes, length: int) -> bytes:
    out, t, i = b"", b"", 1
    while len(out) < length:
        t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:length]


def hkdf_mod_r(ikm: bytes, key_info: bytes = b"") -> int:
    """EIP-2333 hkdf_mod_r: loop re-salting until nonzero mod r."""
    salt = _SALT0
    while True:
        salt = hashlib.sha256(salt).digest()
        prk = _hkdf_extract(salt, ikm + b"\x00")
        okm = _hkdf_expand(prk, key_info + _L.to_bytes(2, "big"), _L)
        sk = int.from_bytes(okm, "big") % R
        if sk != 0:
            return sk


def _ikm_to_lamport_sk(ikm: bytes, salt: bytes) -> list:
    prk = _hkdf_extract(salt, ikm)
    okm = _hkdf_expand(prk, b"", 255 * 32)
    return [okm[i * 32 : (i + 1) * 32] for i in range(255)]


def _parent_sk_to_lamport_pk(parent_sk: int, index: int) -> bytes:
    salt = index.to_bytes(4, "big")
    ikm = parent_sk.to_bytes(32, "big")
    lamport_0 = _ikm_to_lamport_sk(ikm, salt)
    not_ikm = bytes(b ^ 0xFF for b in ikm)
    lamport_1 = _ikm_to_lamport_sk(not_ikm, salt)
    lamport_pk = b"".join(
        hashlib.sha256(chunk).digest() for chunk in lamport_0 + lamport_1
    )
    return hashlib.sha256(lamport_pk).digest()


def derive_master_sk(seed: bytes) -> int:
    if len(seed) < 32:
        raise ValueError("seed must be at least 32 bytes")
    return hkdf_mod_r(seed)


def derive_child_sk(parent_sk: int, index: int) -> int:
    return hkdf_mod_r(_parent_sk_to_lamport_pk(parent_sk, index))


def derive_path(seed: bytes, path: str) -> int:
    """EIP-2334 path derivation: 'm/12381/3600/i/0/0' etc."""
    parts = path.split("/")
    if parts[0] != "m":
        raise ValueError("path must start at the master node 'm'")
    sk = derive_master_sk(seed)
    for p in parts[1:]:
        if not p.isdigit():
            raise ValueError(f"invalid path component {p!r} (no hardening marks in EIP-2334)")
        sk = derive_child_sk(sk, int(p))
    return sk


def validator_signing_path(index: int) -> str:
    """EIP-2334 g = m/12381/3600/<index>/0/0 (signing key)."""
    return f"m/12381/3600/{index}/0/0"


def validator_withdrawal_path(index: int) -> str:
    """EIP-2334 m/12381/3600/<index>/0 (withdrawal key)."""
    return f"m/12381/3600/{index}/0"


def mnemonic_to_seed(mnemonic: str, passphrase: str = "") -> bytes:
    """BIP-39 seed: PBKDF2-HMAC-SHA512(mnemonic, 'mnemonic'+passphrase,
    2048 rounds, 64 bytes). The mnemonic is taken as given (NFKD), no
    wordlist validation — callers own checksum policy. This is the
    staking-deposit-cli / eth2_wallet entry into EIP-2333 derivation."""
    import unicodedata

    m = unicodedata.normalize("NFKD", mnemonic).encode()
    salt = unicodedata.normalize("NFKD", "mnemonic" + passphrase).encode()
    return hashlib.pbkdf2_hmac("sha512", m, salt, 2048, dklen=64)
